"""Memory observability subsystem tests (ISSUE 8): per-buffer attribution,
OOM preflight, live-memory telemetry, and the trainer integration's
acceptance pillars:

* attribution is EXACT and exhaustive — hand-computed on synthetic stats,
  buffer-class fractions sum to 1 on the real single-step AND chained
  programs, and the predicted peak equals the number re-derived from
  ``compiled.memory_analysis()`` (self-parity);
* preflight bisection is boundary-exact: the recommended batch's predicted
  peak fits, the next shard-multiple's does not;
* ``Trainer(preflight=None)`` reproduces the historical program —
  trace_counts identical and params bit-exact with a preflight-on run
  (the telemetry/profiling parity convention) — and a predicted OOM fails
  BEFORE anything is dispatched (trace_counts empty);
* the memory-growth detector fires on an injected leak and stays quiet on
  a flat run; statless backends (CPU) degrade to absent fields everywhere.

Cost note: every attribution/preflight check lowers the TinyMLP engine on
abstract avals (sub-second CPU compiles); nothing here executes a step
except the trainer parity tests (the test_telemetry TinyTrainer).
"""

import os

import jax
import numpy as np
import pytest

from distributed_training_pytorch_tpu.memory import (
    BUFFER_CLASSES,
    Preflight,
    PreflightOOMError,
    analyze_step_memory,
    attribute_memory,
    device_memory_stats,
    is_oom_error,
    live_memory_fields,
    memory_skew,
    resolve_preflight,
    run_preflight,
    top_buffers_from_hlo,
)
from distributed_training_pytorch_tpu.memory.analysis import stack_chain_batch
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.telemetry import AnomalyDetector, read_events

from test_engine import make_engine, synthetic_batch
from test_telemetry import assert_trees_equal, make_tiny


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


@pytest.fixture(scope="module")
def engine_state():
    return make_engine()


# ---------------------------------------------------------------------------
# Attribution core: pure arithmetic, hand-checkable.


def test_attribute_memory_hand_computed():
    """Exact partition on synthetic stats: arg 1000 pro-rated 500/300/200
    over params/opt/batch, grads = min(temp, grad_bytes) = 400, activations
    = remaining temp 200 + unaliased out 50, executable = code 30. Peak =
    1000 + 150 - 100 + 600 + 30 = 1680 and the classes sum to it exactly."""
    stats = {
        "argument_size_in_bytes": 1000,
        "output_size_in_bytes": 150,
        "alias_size_in_bytes": 100,
        "temp_size_in_bytes": 600,
        "generated_code_size_in_bytes": 30,
    }
    profile = attribute_memory(
        stats,
        {"params": 500.0, "optimizer_state": 300.0, "input_batch": 200.0},
        grad_bytes=400.0,
    )
    assert profile.peak_bytes == 1680
    assert profile.bytes_by_class == {
        "params": 500.0,
        "optimizer_state": 300.0,
        "input_batch": 200.0,
        "gradients": 400.0,
        "activations": 200.0 + 50.0,
        "executable": 30.0,
    }
    assert sum(profile.bytes_by_class.values()) == profile.peak_bytes
    assert abs(sum(profile.fractions().values()) - 1.0) < 1e-12


def test_attribute_memory_pro_rata_absorbs_padding():
    """XLA-reported argument bytes (padding included) are what gets
    partitioned — the class split scales to the reported total, not the
    aval sum (600 reported vs 300 aval: every class doubles)."""
    stats = {
        "argument_size_in_bytes": 600,
        "output_size_in_bytes": 0,
        "alias_size_in_bytes": 0,
        "temp_size_in_bytes": 0,
        "generated_code_size_in_bytes": 0,
    }
    profile = attribute_memory(
        stats, {"params": 100.0, "optimizer_state": 100.0, "input_batch": 100.0}, 0.0
    )
    assert profile.bytes_by_class["params"] == 200.0
    assert sum(profile.bytes_by_class.values()) == 600


def test_attribute_memory_no_classable_inputs_spills_to_activations():
    stats = {
        "argument_size_in_bytes": 64,
        "output_size_in_bytes": 0,
        "alias_size_in_bytes": 0,
        "temp_size_in_bytes": 0,
        "generated_code_size_in_bytes": 0,
    }
    profile = attribute_memory(stats, {}, 0.0)
    assert profile.bytes_by_class["activations"] == 64.0
    assert profile.peak_bytes == 64


def test_attribute_memory_grads_capped_by_temp():
    """XLA may alias/fold gradient buffers away: the gradients class never
    exceeds the temp space that actually exists."""
    stats = {
        "argument_size_in_bytes": 0,
        "output_size_in_bytes": 0,
        "alias_size_in_bytes": 0,
        "temp_size_in_bytes": 100,
        "generated_code_size_in_bytes": 0,
    }
    profile = attribute_memory(stats, {}, grad_bytes=1_000_000.0)
    assert profile.bytes_by_class["gradients"] == 100.0
    assert profile.bytes_by_class["activations"] == 0.0


def test_top_buffers_from_hlo_exact_rows():
    hlo = """
ENTRY %main (p0: f32[8,16]) -> f32[8] {
  %p0 = f32[8,16]{1,0} parameter(0)
  %big = bf16[64,64]{1,0} fusion(f32[8,16]{1,0} %p0), metadata={op_name="jit(step)/dot"}
  ROOT %out = f32[8]{0} reduce(f32[8,16]{1,0} %p0)
}
"""
    rows = top_buffers_from_hlo(hlo, top_k=2)
    assert rows[0]["name"] == "big" and rows[0]["op"] == "fusion"
    assert rows[0]["bytes"] == 64 * 64 * 2  # bf16
    assert rows[0]["op_name"] == "jit(step)/dot"
    assert rows[1] == {
        "name": "p0", "op": "parameter", "shape": [8, 16], "dtype": "f32",
        "bytes": 8 * 16 * 4, "op_name": "",
    }
    assert top_buffers_from_hlo(hlo, top_k=0) == []


# ---------------------------------------------------------------------------
# Real programs: exhaustive fractions + self-parity with memory_analysis.


def _independent_peak(engine, state, batch, chain_length=None):
    """Re-derive the peak straight from the probe's CompiledMemoryStats —
    stdlib arithmetic, independent of memory/analysis.py."""
    probe_batch = stack_chain_batch(batch, chain_length) if chain_length else batch
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(tuple(np.shape(x)), np.asarray(x).dtype)
        if not hasattr(x, "dtype") or not hasattr(x, "shape")
        else jax.ShapeDtypeStruct(tuple(x.shape), x.dtype),
        probe_batch,
    )
    stats = engine.compile_step_probe(
        state, abstract, donate=True, chain_length=chain_length
    ).memory_analysis()
    return int(
        stats.argument_size_in_bytes
        + stats.output_size_in_bytes
        - stats.alias_size_in_bytes
        + stats.temp_size_in_bytes
        + stats.generated_code_size_in_bytes
    )


def test_fractions_sum_to_one_single_step(devices, engine_state):
    engine, state = engine_state
    profile = analyze_step_memory(engine, state, synthetic_batch(32))
    assert set(profile.bytes_by_class) == set(BUFFER_CLASSES)
    assert all(v >= 0 for v in profile.bytes_by_class.values())
    assert abs(sum(profile.fractions().values()) - 1.0) < 1e-6
    assert profile.peak_bytes > 0
    assert profile.top_buffers and profile.top_buffers[0]["bytes"] > 0


def test_fractions_sum_to_one_chained(devices, engine_state):
    engine, state = engine_state
    batch = synthetic_batch(32)
    single = analyze_step_memory(engine, state, batch)
    chained = analyze_step_memory(engine, state, batch, chain_length=2)
    assert abs(sum(chained.fractions().values()) - 1.0) < 1e-6
    assert chained.chain_length == 2
    # two global batches staged at once: the window program's input-batch
    # class (and so its peak) exceeds the single step's
    assert chained.bytes_by_class["input_batch"] > single.bytes_by_class["input_batch"]
    assert chained.peak_bytes > single.peak_bytes


def test_predicted_peak_self_parity_with_memory_analysis(devices, engine_state):
    """THE tentpole invariant: the preflight's prediction IS XLA's buffer
    assignment, on both real programs."""
    engine, state = engine_state
    batch = synthetic_batch(32)
    for chain_length in (None, 2):
        profile = analyze_step_memory(engine, state, batch, chain_length=chain_length)
        assert profile.peak_bytes == _independent_peak(engine, state, batch, chain_length)


def test_analyze_leaves_trace_counts_alone(devices, engine_state):
    """Attribution rides compile_step_probe: zero trace-count side effects
    (the MFU-probe/profiling convention) — dispatch executables untouched."""
    engine, state = engine_state
    before = dict(engine.trace_counts)
    analyze_step_memory(engine, state, synthetic_batch(32), chain_length=2)
    assert dict(engine.trace_counts) == before


# ---------------------------------------------------------------------------
# Preflight: fit verdicts, bisection boundary, resolution protocol.


def test_preflight_fits_under_huge_capacity(devices, engine_state):
    engine, state = engine_state
    report = run_preflight(
        engine, state, synthetic_batch(32), Preflight(capacity_bytes=1 << 50)
    )
    assert report.fits is True
    assert report.recommended_batch is None and report.recommended_accum is None
    assert report.batch_size == 32
    assert report.predicted_peak_bytes == report.profile.peak_bytes


def test_preflight_bisection_monotonic_boundary(devices, engine_state):
    """The recommendation is boundary-exact: the recommended batch's
    predicted peak fits the usable budget, the next shard-multiple's does
    not (monotonicity of peak in batch size, bisected)."""
    engine, state = engine_state
    batch = synthetic_batch(32)
    shard = 8  # data-axis extent of the 8-device mesh
    p_small = analyze_step_memory(
        engine, state, synthetic_batch(shard), top_k=0
    ).peak_bytes
    p_full = analyze_step_memory(engine, state, batch, top_k=0).peak_bytes
    assert p_small < p_full
    usable = (p_small + p_full) // 2
    with pytest.raises(PreflightOOMError) as err:
        run_preflight(
            engine, state, batch,
            Preflight(capacity_bytes=usable, headroom=0.0),
        )
    report = err.value.report
    rec = report.recommended_batch
    assert rec is not None and rec % shard == 0 and shard <= rec < 32
    fit_peak = analyze_step_memory(
        engine, state, synthetic_batch(rec), top_k=0
    ).peak_bytes
    next_peak = analyze_step_memory(
        engine, state, synthetic_batch(rec + shard), top_k=0
    ).peak_bytes
    assert fit_peak <= report.usable_bytes < next_peak
    assert report.trials <= Preflight().max_trials
    # the failure message names the recommendation
    assert f"batch {rec}" in str(err.value)


def test_preflight_warn_action_does_not_raise(devices, engine_state):
    engine, state = engine_state
    warnings_seen = []
    report = run_preflight(
        engine, state, synthetic_batch(32),
        Preflight(capacity_bytes=1000, action="warn", recommend=False),
        log=lambda msg, log_type="info": warnings_seen.append((log_type, msg)),
    )
    assert report.fits is False
    assert any(t == "warning" and "predicted OOM" in m for t, m in warnings_seen)


def test_preflight_unknown_capacity_skips_check(devices, engine_state):
    """CPU reports no memory_stats: the fit check is skipped (fits=None),
    the prediction still lands, nothing raises."""
    engine, state = engine_state
    report = run_preflight(engine, state, synthetic_batch(32), Preflight())
    assert report.fits is None and report.capacity_bytes is None
    assert report.predicted_peak_bytes > 0


def test_preflight_degrades_when_backend_has_no_memory_analysis(devices, engine_state):
    """A backend whose compiled programs expose no memory_analysis must not
    kill training through an observability knob: run_preflight warns and
    returns None instead of raising."""
    engine, state = engine_state

    class NoAnalysis:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            if name == "memory_analysis":
                raise AttributeError(name)
            return getattr(self._inner, name)

    real = engine.compile_step_probe
    warnings_seen = []
    try:
        engine.compile_step_probe = lambda *a, **k: NoAnalysis(real(*a, **k))
        report = run_preflight(
            engine, state, synthetic_batch(32), Preflight(capacity_bytes=1),
            log=lambda msg, log_type="info": warnings_seen.append((log_type, msg)),
        )
    finally:
        engine.compile_step_probe = real
    assert report is None
    assert any(t == "warning" and "preflight skipped" in m for t, m in warnings_seen)


def test_preflight_bisection_does_not_grow_probe_cache(devices):
    """Recommendation trials are throwaway compiles: the engine's memoizing
    probe cache must not accumulate one loaded executable per trial shape
    (only the configured shape's probe may land there)."""
    engine, state = make_engine()
    batch = synthetic_batch(32)
    p_small = analyze_step_memory(engine, state, synthetic_batch(8), top_k=0).peak_bytes
    p_full = analyze_step_memory(engine, state, batch, top_k=0).peak_bytes
    cache_before = len(engine._step_probe_cache)
    with pytest.raises(PreflightOOMError) as err:
        run_preflight(
            engine, state, batch,
            Preflight(capacity_bytes=(p_small + p_full) // 2, headroom=0.0),
        )
    assert err.value.report.trials > 0
    assert len(engine._step_probe_cache) == cache_before


def test_resolve_preflight_specs():
    assert resolve_preflight(None) is None
    assert resolve_preflight(False) is None
    assert resolve_preflight("off") is None
    assert isinstance(resolve_preflight(True), Preflight)
    assert isinstance(resolve_preflight("on"), Preflight)
    assert isinstance(resolve_preflight("check"), Preflight)
    config = Preflight(headroom=0.2)
    assert resolve_preflight(config) is config
    with pytest.raises(ValueError):
        resolve_preflight("sideways")
    with pytest.raises(TypeError):
        resolve_preflight(3.14)
    with pytest.raises(ValueError):
        Preflight(action="explode")
    with pytest.raises(ValueError):
        Preflight(headroom=1.5)


def test_engine_with_accum_twin(devices, engine_state):
    engine, state = engine_state
    twin = engine.with_accum(2)
    assert twin is not engine and twin.accum_steps == 2
    assert twin.mesh is engine.mesh and twin.loss_fn is engine.loss_fn
    # the twin's program lowers and analyzes like the original's
    profile = analyze_step_memory(twin, state, synthetic_batch(32), top_k=0)
    assert profile.peak_bytes > 0
    with pytest.raises(ValueError):
        engine.with_accum(0)


# ---------------------------------------------------------------------------
# Sharded avals (ISSUE 10): per-device attribution + the fsdp recommendation.


def _wide_mlp_engine(mesh, fsdp_min_size=256):
    """A param-heavy MLP (one 48x512 kernel dominates) so fsdp sharding
    moves the predicted peak measurably — the capacity window the
    recommendation test sits inside."""
    import optax
    from flax import linen as nn

    from distributed_training_pytorch_tpu.ops import cross_entropy_loss
    from distributed_training_pytorch_tpu.train import (
        TrainEngine,
        make_supervised_loss,
    )

    class WideMLP(nn.Module):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = x.reshape(x.shape[0], -1)
            x = nn.relu(nn.Dense(512)(x))
            return nn.Dense(3)(x)

    model = WideMLP()

    def criterion(logits, batch):
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"loss": loss}

    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optax.sgd(0.05, momentum=0.9),
        mesh,
        fsdp_min_size=fsdp_min_size,
    )
    state = engine.init_state(
        jax.random.key(0),
        lambda r: model.init(r, jax.numpy.zeros((1, 4, 4, 3))),
    )
    return engine, state


def test_fsdp_attribution_uses_per_device_shard_bytes(devices):
    """ISSUE 10 satellite acceptance: on an FSDP program the params /
    optimizer classes must be the per-device SHARD bytes (global / extent
    for the sharded leaves), and input_batch the per-device rows — exactly
    what the SPMD executable's memory_analysis() reports — not global aval
    bytes, which would overstate the sharded classes by the extent."""
    from distributed_training_pytorch_tpu.memory.analysis import state_class_bytes

    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.FSDP_AXIS: 4}, devices=devices
    )
    engine, state = _wide_mlp_engine(mesh)
    sharding_tree = engine.state_sharding_tree(state)
    specs = [str(s.spec) for s in jax.tree.leaves(
        sharding_tree, is_leaf=lambda x: hasattr(x, "spec"))]
    assert any("fsdp" in s for s in specs), specs

    batch = synthetic_batch(32)
    profile = analyze_step_memory(engine, state, batch, top_k=0)
    # Exact hand-derivation: per-device class bytes through the same shard
    # arithmetic, pro-rated over XLA's reported argument total.
    per_device = state_class_bytes(state, sharding_tree)
    global_classes = state_class_bytes(state)
    # the 48x512 kernel (and its momentum) shard 4-way: per-device params
    # land well under global.
    assert per_device["params"] < 0.5 * global_classes["params"]
    assert per_device["optimizer_state"] < 0.5 * global_classes["optimizer_state"]
    batch_sharding = mesh_lib.batch_sharding(mesh)
    from distributed_training_pytorch_tpu.memory.analysis import batch_class_bytes

    per_device_batch = batch_class_bytes(
        jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype), batch
        ),
        batch_sharding,
    )
    assert per_device_batch == batch_class_bytes(batch) / 8  # data x fsdp = 8
    arg = float(profile.stats["argument_size_in_bytes"])
    in_total = per_device["params"] + per_device["optimizer_state"] + per_device_batch
    expected_params = arg * per_device["params"] / in_total
    assert profile.bytes_by_class["params"] == pytest.approx(expected_params)
    # and the pro-rata anchor itself is the per-device sum: XLA's reported
    # argument bytes must be near it (padding only), nowhere near the
    # global sum.
    global_total = (
        global_classes["params"] + global_classes["optimizer_state"]
        + batch_class_bytes(batch)
    )
    assert arg == pytest.approx(in_total, rel=0.02)
    assert arg < 0.6 * global_total


def test_tree_shard_bytes_exact_on_hand_built_shardings(devices):
    """Hand-built FSDP layout: a [48, 512] f32 leaf sharded 4-way over fsdp
    is 48*512*4/4 bytes per device; a replicated [32] leaf stays whole."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_training_pytorch_tpu.parallel.sharding import tree_shard_bytes

    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.FSDP_AXIS: 4}, devices=devices
    )
    tree = {
        "kernel": jax.ShapeDtypeStruct((48, 512), np.float32),
        "bias": jax.ShapeDtypeStruct((32,), np.float32),
    }
    shardings = {
        "kernel": NamedSharding(mesh, P(None, "fsdp")),
        "bias": NamedSharding(mesh, P()),
    }
    assert tree_shard_bytes(tree, shardings) == 48 * 512 * 4 / 4 + 32 * 4
    # single-sharding broadcast: everything replicated = global sum
    assert tree_shard_bytes(tree, NamedSharding(mesh, P())) == 48 * 512 * 4 + 32 * 4


def test_preflight_recommends_fsdp(devices):
    """On predicted OOM from a pure-data mesh, the recommendation set
    includes 'enable fsdp=N' — probed on with_mesh twins, so the
    recommended extent is one whose per-device peak actually fits."""
    dp_mesh = mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)
    engine, state = _wide_mlp_engine(dp_mesh)
    batch = synthetic_batch(32)
    full_peak = analyze_step_memory(engine, state, batch, top_k=0).peak_bytes
    fsdp2 = engine.with_mesh(
        mesh_lib.create_mesh(
            {mesh_lib.DATA_AXIS: 4, mesh_lib.FSDP_AXIS: 2}, devices=devices
        )
    )
    fsdp2_peak = analyze_step_memory(fsdp2, state, batch, top_k=0).peak_bytes
    assert fsdp2_peak < full_peak  # params dominate: sharding must help
    capacity = (fsdp2_peak + full_peak) // 2
    report = run_preflight(
        engine,
        state,
        batch,
        Preflight(capacity_bytes=int(capacity), headroom=0.0, action="warn"),
    )
    assert report.fits is False
    assert report.recommended_fsdp == 2
    # the recommendation is honest: the probed twin's peak fits capacity
    assert fsdp2_peak <= capacity


# ---------------------------------------------------------------------------
# Live telemetry: the shared memory_stats read degrades to absent on CPU.


def test_live_memory_degrades_to_absent_on_cpu(devices):
    from distributed_training_pytorch_tpu.memory import window_memory_fields

    assert device_memory_stats() is None  # CPU backend has no allocator stats
    assert live_memory_fields() == {}
    assert live_memory_fields(include_peak=False) == {}
    assert memory_skew() == {}
    assert window_memory_fields() == {}


def test_window_memory_fields_single_pass_consistency():
    """One sampling instant: live_bytes always sits within its own
    min/max (two separate reads could interleave with allocations and emit
    a self-contradictory record)."""
    from distributed_training_pytorch_tpu.memory import window_memory_fields

    class FakeDevice:
        def __init__(self, live):
            self._live = live

        def memory_stats(self):
            return {"bytes_in_use": self._live, "peak_bytes_in_use": self._live * 2}

    fields = window_memory_fields([FakeDevice(100), FakeDevice(300), FakeDevice(200)])
    assert fields["live_bytes"] == 100 and fields["peak_bytes"] == 200
    assert fields["live_bytes_min"] == 100 and fields["live_bytes_max"] == 300
    assert fields["live_bytes_skew"] == 200
    assert fields["live_bytes_min"] <= fields["live_bytes"] <= fields["live_bytes_max"]
    solo = window_memory_fields([FakeDevice(42)], include_peak=False)
    assert solo == {"live_bytes": 42}  # no skew fields on single-chip


def test_is_oom_error_classification():
    from jaxlib.xla_extension import XlaRuntimeError

    assert is_oom_error(XlaRuntimeError("RESOURCE_EXHAUSTED: 1.2GiB > 1.0GiB"))
    assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating"))
    assert is_oom_error(XlaRuntimeError("Execution failed: Out of memory while trying"))
    # host-side failures are bugs to surface, not device fit boundaries
    assert not is_oom_error(MemoryError())
    assert not is_oom_error(Exception("Out of memory while trying"))
    assert not is_oom_error(ValueError("shapes do not match"))


# ---------------------------------------------------------------------------
# Memory-growth anomaly detector: leak fires, flat stays quiet.


def test_memory_growth_fires_on_injected_leak():
    detector = AnomalyDetector(warmup=2, memory_growth=1.5)
    fired = []
    live = 1000.0
    for step in range(20):
        live += 120.0  # a steady host-side leak
        fired += detector.observe(step, live_bytes=live)
    kinds = {a.kind for a in fired}
    assert kinds == {"memory_growth"}, fired
    first = fired[0]
    # the baseline is the steady-state floor, never dragged up by the leak
    assert first.value > 1.5 * first.baseline
    assert detector.total_fired == len(fired) > 0


def test_memory_growth_quiet_on_flat_run():
    detector = AnomalyDetector(warmup=2, memory_growth=1.5)
    rng = np.random.RandomState(0)
    for step in range(50):
        live = 1_000_000 + rng.randint(-5000, 5000)  # flat ± noise
        assert detector.observe(step, live_bytes=float(live)) == []
    assert detector.total_fired == 0


def test_memory_growth_warmup_allows_allocator_ramp():
    """The allocator legitimately ramps while caches/prefetch fill: warmup
    observations are untracked, so the floor is the steady state, not the
    cold start."""
    detector = AnomalyDetector(warmup=3, memory_growth=1.5)
    for step, live in enumerate([100.0, 10_000.0, 50_000.0, 100_000.0, 101_000.0, 99_000.0]):
        assert detector.observe(step, live_bytes=live) == []


def test_memory_growth_absent_value_never_fires():
    detector = AnomalyDetector(warmup=0, memory_growth=1.5)
    for step in range(10):
        assert detector.observe(step, live_bytes=None) == []
    disabled = AnomalyDetector(warmup=0, memory_growth=None)
    for step in range(10):
        assert disabled.observe(step, live_bytes=float(10 ** (step + 2))) == []


# ---------------------------------------------------------------------------
# Trainer integration: preflight=None parity, fail-fast, event + degradation.


def test_trainer_preflight_parity_and_event(tmp_path, mesh):
    """THE acceptance test: preflight observes, it does not alter —
    trace_counts identical and params bit-exact between preflight=None (the
    historical program) and a preflight-on run; the on run leaves one
    memory_preflight event with the attribution payload; on CPU the window
    records degrade to absent live-memory fields."""
    off = make_tiny(tmp_path / "off", mesh, telemetry="on", preflight=None)
    off.train()
    on = make_tiny(
        tmp_path / "on", mesh, telemetry="on",
        preflight=Preflight(capacity_bytes=1 << 50),
    )
    on.train()
    assert dict(on.engine.trace_counts) == dict(off.engine.trace_counts)
    assert_trees_equal(on.state.params, off.state.params)
    assert_trees_equal(on.state.opt_state, off.state.opt_state)
    assert off.memory_report is None and on.memory_report.fits is True
    events = list(
        read_events(os.path.join(on.save_folder, "telemetry", "events.jsonl"))
    )
    preflights = [e for e in events if e["event"] == "memory_preflight"]
    assert len(preflights) == 1
    record = preflights[0]
    assert record["fits"] is True
    assert record["chain_length"] == 2  # the chained window IS the program
    assert abs(sum(record["fractions"].values()) - 1.0) < 1e-3
    assert record["predicted_peak_bytes"] == on.memory_report.predicted_peak_bytes
    assert record["top_buffers"]
    # statless backend: window records carry no live-memory fields
    windows = [e for e in events if e["event"] == "window"]
    assert windows and all("live_bytes" not in w for w in windows)
    # the off run has no memory_preflight record at all
    off_events = list(
        read_events(os.path.join(off.save_folder, "telemetry", "events.jsonl"))
    )
    assert not [e for e in off_events if e["event"] == "memory_preflight"]


def test_trainer_preflight_short_epoch_predicts_single_step_program(tmp_path, mesh):
    """An epoch shorter than one chained window never dispatches the window
    program — the preflight verdict must cover the single-step program that
    actually runs, not a 4-batch window that never forms (which could fail
    a run whose real program fits)."""
    trainer = make_tiny(
        tmp_path, mesh,
        batch_size=16,  # 48 records -> 3 batches/epoch, below the window
        chain_steps=4,
        log_every=4,
        telemetry="on",
        preflight=Preflight(capacity_bytes=1 << 50),
    )
    trainer.train()
    assert trainer.memory_report is not None
    assert trainer.memory_report.chain_length is None
    assert trainer.memory_report.fits is True


def test_trainer_preflight_oom_fails_before_any_dispatch(tmp_path, mesh):
    trainer = make_tiny(
        tmp_path, mesh, preflight=Preflight(capacity_bytes=2048)
    )
    with pytest.raises(PreflightOOMError) as err:
        trainer.train()
    # fail-fast means FAST: nothing was ever compiled or dispatched
    assert dict(trainer.engine.trace_counts) == {}
    assert err.value.report.fits is False


def test_trainer_preflight_skipped_under_custom_train_step(tmp_path, mesh):
    from test_telemetry import TinyTrainer

    class CustomStep(TinyTrainer):
        def train_step(self, state, batch):
            return self.engine.train_step(state, batch)

    logs = []
    trainer = CustomStep(
        max_epoch=1, batch_size=8, have_validate=False,
        save_folder=str(tmp_path / "runs"), num_workers=0, log_every=0,
        chain_steps=1, async_checkpoint=False, mesh=mesh, progress=False,
        preflight=Preflight(capacity_bytes=1),  # would fail if it ran
        logger=type("L", (), {"log": staticmethod(lambda m, t="info": logs.append(m))})(),
    )
    trainer.train()  # does NOT raise: preflight skipped with a warning
    assert trainer.memory_report is None
    assert any("preflight skipped" in m for m in logs)
