"""Autotuner core (train/autotune.py) + the ONE shared timing implementation.

The ranking/refusal/keep logic is unit-tested on authored measurements (the
end-to-end sweep including the injected-known-win seam runs in verify.sh
stage 15 via ``scripts/autotune.py --self-test``); the shared scan-chain
timer is exercised for real and AST-enforced against private copies in
``scripts/resnet_pallas_probe.py`` (the test_run_compare.py satellite
pattern).
"""

import ast
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_pytorch_tpu.telemetry.history import FLAT_REL_TOL
from distributed_training_pytorch_tpu.train import autotune as autotune_lib
from distributed_training_pytorch_tpu.train.engine import xla_flag_options

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


# ---------------------------------------------------------------------------
# the one timing implementation
# ---------------------------------------------------------------------------


def test_time_chained_measures_a_real_function():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 32), jnp.float32)
    w = jnp.asarray(rng.randn(32, 16), jnp.float32)

    def f(x, w):
        return jnp.tanh(x @ w)

    dt = autotune_lib.time_chained(f, x, w, steps=4, windows=2)
    # Differencing of noisy sub-ms windows can land at ~0; it must at least
    # be a finite float and not wildly negative (window noise bound).
    assert np.isfinite(dt)
    assert dt > -1e-3


def test_probe_imports_the_shared_timer_and_keeps_no_private_copy():
    """Satellite 1, test-enforced: resnet_pallas_probe.py imports
    train.autotune.time_chained and defines NO local timing twin."""
    path = os.path.join(REPO, "scripts", "resnet_pallas_probe.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename="resnet_pallas_probe.py")
    imports_timer = any(
        isinstance(node, ast.ImportFrom)
        and node.module
        and node.module.endswith("train.autotune")
        and any(alias.name == "time_chained" for alias in node.names)
        for node in ast.walk(tree)
    )
    assert imports_timer, (
        "the probe must import train.autotune.time_chained (the ONE "
        "two-length-differencing timer)"
    )
    local_defs = [
        node.name for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and ("time_chained" in node.name or "timed" in node.name)
    ]
    assert not local_defs, (
        f"the probe defines a private timer {local_defs} — the timing "
        "implementation lives in train/autotune.py only"
    )


# ---------------------------------------------------------------------------
# ranking / refusal / keep rule
# ---------------------------------------------------------------------------

_CATS_BASE = {"convolution": 0.5, "matmul": 0.2, "other": 0.1, "idle": 0.2}
_CATS_FAST = {"convolution": 0.55, "matmul": 0.22, "other": 0.13, "idle": 0.1}


def _prov(**over):
    prov = {"jax": "0.9", "jaxlib": "0.9", "xla_flags": "", "mesh": None,
            "dtype": "float32", "chain_steps": 4, "batch": 64}
    prov.update(over)
    return prov


def _meas(step_ms, *, cats=None, prov=None):
    m = {"step_ms": step_ms, "chain_steps": 4, "windows": 3}
    if cats is not None:
        m["categories"] = cats
    if prov is not None:
        m["provenance"] = prov
    return m


def _baseline(step_ms=10.0):
    return {"name": "baseline", "knobs": {},
            "measurement": _meas(step_ms, cats=_CATS_BASE, prov=_prov())}


def test_rank_orders_by_metric_and_attributes_the_delta():
    results = [
        {"name": "slow", "knobs": {"chain_steps": 8},
         "measurement": _meas(11.0, cats=_CATS_BASE, prov=_prov(chain_steps=8))},
        {"name": "fast", "knobs": {"xla_flags": "--xla_x=1"},
         "measurement": _meas(8.0, cats=_CATS_FAST,
                              prov=_prov(xla_flags="--xla_x=1"))},
    ]
    report = autotune_lib.rank_candidates(_baseline(), results)
    assert [e["name"] for e in report["ranked"]] == ["fast", "slow"]
    assert report["refused"] == []
    winner = report["ranked"][0]
    assert winner["delta_ms"] == pytest.approx(-2.0)
    # attribution rows come from profiling.diff and must cover the delta
    assert winner["attribution"], "categories on both sides -> rows required"
    total = sum(row["delta"] for row in winner["attribution"])
    assert total == pytest.approx(-2.0, abs=0.2)
    assert sum(row["frac_of_delta"] for row in winner["attribution"]) == (
        pytest.approx(1.0, abs=0.02))
    assert winner["attribution_text"]
    assert report["kept"] is True and report["winner"]["name"] == "fast"


def test_undeclared_provenance_drift_is_refused_not_ranked():
    """The PR 14 rule, sweep-adapted: a facet the candidate did not declare
    as swept (here dtype) refuses the comparison; a declared one (here
    chain_steps) is allowed."""
    results = [
        {"name": "dtype-drift", "knobs": {"chain_steps": 8},
         "measurement": _meas(7.0, cats=_CATS_FAST,
                              prov=_prov(chain_steps=8, dtype="bfloat16"))},
        {"name": "declared", "knobs": {"chain_steps": 8},
         "measurement": _meas(9.0, cats=_CATS_FAST, prov=_prov(chain_steps=8))},
    ]
    report = autotune_lib.rank_candidates(_baseline(), results)
    assert [r["name"] for r in report["refused"]] == ["dtype-drift"]
    assert report["refused"][0]["differing_keys"] == ["dtype"]
    # the refused (faster!) candidate must not leak into the ranking
    assert [e["name"] for e in report["ranked"]] == ["declared"]
    assert report["winner"]["name"] == "declared"


def test_sub_noise_win_is_not_kept():
    """A 'win' inside the flat-streak band (FLAT_REL_TOL) would re-flatten
    the bench line next round — ranked, but kept=False, winner=None."""
    inside = 10.0 * (1.0 - FLAT_REL_TOL / 2)
    results = [{"name": "noise", "knobs": {},
                "measurement": _meas(inside, cats=_CATS_BASE, prov=_prov())}]
    report = autotune_lib.rank_candidates(_baseline(), results)
    assert report["ranked"] and report["kept"] is False
    assert report["winner"] is None


def test_missing_categories_rank_without_attribution():
    results = [{"name": "blind", "knobs": {},
                "measurement": _meas(8.0, prov=_prov())}]
    report = autotune_lib.rank_candidates(_baseline(), results)
    entry = report["ranked"][0]
    assert entry["attribution"] is None and entry["attribution_text"] == ""


# ---------------------------------------------------------------------------
# TUNED.json round-trip + the entry-side opt-in
# ---------------------------------------------------------------------------


def _kept_report():
    results = [{"name": "fast", "knobs": {"chain_steps": 8, "xla_flags": "--xla_y=1"},
                "measurement": _meas(8.0, cats=_CATS_FAST,
                                     prov=_prov(chain_steps=8,
                                                xla_flags="--xla_y=1"))}]
    return autotune_lib.rank_candidates(_baseline(), results)


def test_tuned_round_trip_and_opt_in(tmp_path):
    path = str(tmp_path / "TUNED.json")
    report = _kept_report()
    autotune_lib.emit_tuned(path, report)
    assert autotune_lib.load_tuned(path) == json.loads(json.dumps(report))

    # TUNED unset -> {} (autotuner off = no behavior change anywhere)
    assert autotune_lib.tuned_defaults(path, env={}) == {}
    assert autotune_lib.tuned_defaults(path, env={"TUNED": "0"}) == {}
    # TUNED=1 -> the kept winner's knobs, and the xla_flags install
    env = {"TUNED": "1"}
    knobs = autotune_lib.tuned_defaults(path, env=env)
    assert knobs == {"chain_steps": 8, "xla_flags": "--xla_y=1"}
    assert env["XLA_FLAGS"] == "--xla_y=1"
    # an explicit XLA_FLAGS is never overridden
    env = {"TUNED": "1", "XLA_FLAGS": "--xla_mine=1"}
    autotune_lib.tuned_defaults(path, env=env)
    assert env["XLA_FLAGS"] == "--xla_mine=1"


def test_tuned_flags_not_installed_under_an_explicit_cpu_pin(tmp_path):
    """A CPU-pinned process must degrade to untuned, not die: the committed
    winners carry --xla_tpu_* flags and XLA's parse_flags_from_env ABORTS on
    flags the compiled-in backend doesn't know. Knobs still flow; only the
    flag install is withheld. A TPU pin (tpu or the axon plugin) installs."""
    path = str(tmp_path / "TUNED.json")
    autotune_lib.emit_tuned(path, _kept_report())
    for pin in ("cpu", "cpu,cuda", "CPU"):
        env = {"TUNED": "1", "JAX_PLATFORMS": pin}
        knobs = autotune_lib.tuned_defaults(path, env=env)
        assert knobs == {"chain_steps": 8, "xla_flags": "--xla_y=1"}
        assert "XLA_FLAGS" not in env, pin
    for pin in ("tpu", "axon", "tpu,cpu", ""):
        env = {"TUNED": "1", "JAX_PLATFORMS": pin}
        autotune_lib.tuned_defaults(path, env=env)
        assert env.get("XLA_FLAGS") == "--xla_y=1", pin


def test_tuned_defaults_empty_when_not_kept(tmp_path):
    path = str(tmp_path / "TUNED.json")
    report = _kept_report()
    report["kept"], report["winner"] = False, None
    autotune_lib.emit_tuned(path, report)
    assert autotune_lib.tuned_defaults(path, env={"TUNED": "1"}) == {}
    # absent / unreadable files are an empty opt-in, never a crash
    assert autotune_lib.tuned_defaults(str(tmp_path / "nope.json"),
                                       env={"TUNED": "1"}) == {}


def test_committed_tuned_json_is_a_kept_sweep_with_attribution():
    """The committed TUNED.json IS the evidence artifact: a kept winner with
    per-category attribution and a declared-knobs grammar."""
    data = autotune_lib.load_tuned()
    assert data and data["schema"] == 1 and data["kept"] is True
    winner = data["winner"]
    assert winner["delta_ms"] < 0
    assert winner["attribution"], "a kept win ships WITH its attribution"
    assert set(winner["knobs"]) <= {"xla_flags", "chain_steps", "batch",
                                    "accum_steps", "pallas", "block_rows"}
    # every ranked candidate declared its sweep facets; refusals name keys
    for entry in data["ranked"]:
        assert "measurement" in entry and "delta_ms" in entry
    for refusal in data["refused"]:
        assert refusal["differing_keys"]


# ---------------------------------------------------------------------------
# the XLA-flag -> per-compile compiler-options bridge
# ---------------------------------------------------------------------------


def test_xla_flag_options_parses_the_flag_grammar():
    assert xla_flag_options("--xla_a=true --xla_b=2") == {
        "xla_a": "true", "xla_b": "2"}
    assert xla_flag_options("--xla_bare") == {"xla_bare": "true"}
    assert xla_flag_options("") == {}
    assert xla_flag_options(None) == {}
    with pytest.raises(ValueError):
        xla_flag_options("xla_no_dashes=1")
    with pytest.raises(ValueError):
        xla_flag_options("--not_an_xla_flag=1")
