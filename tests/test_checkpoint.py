"""Checkpoint round-trip + best/last/periodic policy tests (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_pytorch_tpu.checkpoint import (
    BEST,
    LAST,
    CheckpointManager,
    epoch_checkpoint_name,
)
from distributed_training_pytorch_tpu.models import VGG16
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss
from distributed_training_pytorch_tpu.ops import cross_entropy_loss


def _small_state(devices, seed=0):
    mesh = mesh_lib.create_mesh({mesh_lib.DATA_AXIS: len(devices)}, devices=devices)
    model = VGG16(
        num_classes=3, stage_features=(4, 8), stage_layers=(1, 1), classifier_widths=(16,)
    )

    def criterion(logits, batch):
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"loss": loss}

    engine = TrainEngine(
        make_supervised_loss(model, criterion), optax.sgd(0.01, momentum=0.9), mesh
    )
    state = engine.init_state(
        jax.random.key(seed), lambda rng: model.init(rng, jnp.zeros((1, 16, 16, 3)))
    )
    return engine, state


@pytest.fixture(scope="module")
def shared(devices):
    """(engine, state, differently-seeded state) built once — each init pays a
    multi-second jit compile on the CPU test platform. Managers only read the
    states (saves copy, restores return new pytrees), so sharing is safe."""
    engine, state = _small_state(devices, seed=0)
    _, other = _small_state(devices, seed=1)
    return engine, state, other


def test_round_trip(tmp_path, shared):
    engine, state, other = shared
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    mgr.save(LAST, state, epoch=7)
    assert mgr.exists(LAST)

    # Restore into a differently-seeded state; values must match the saved one.
    restored, epoch = mgr.restore(LAST, other)
    assert epoch == 7
    leaves_a = jax.tree.leaves(state.params)
    leaves_b = jax.tree.leaves(restored.params)
    for a, b in zip(leaves_a, leaves_b, strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # opt_state (momentum buffers) round-trips too.
    for a, b in zip(jax.tree.leaves(state.opt_state), jax.tree.leaves(restored.opt_state), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


def test_best_policy_geq(tmp_path, shared):
    _, state, _ = shared
    mgr = CheckpointManager(
        tmp_path / "ckpt", save_best_for=("accuracy", "geq"), async_save=False
    )
    assert mgr.maybe_save_best({"accuracy": 0.5}, state, epoch=0)
    assert mgr.best_value == 0.5
    assert not mgr.maybe_save_best({"accuracy": 0.4}, state, epoch=5)
    assert mgr.best_value == 0.5
    # geq: equal counts as improvement (trainer/trainer.py:119 semantics).
    assert mgr.maybe_save_best({"accuracy": 0.5}, state, epoch=10)
    assert mgr.maybe_save_best({"accuracy": 0.9}, state, epoch=15)
    assert mgr.exists(BEST)
    _, epoch = mgr.restore(BEST, state)
    assert epoch == 15
    assert mgr.best_value == 0.9
    mgr.close()


def test_best_policy_leq(tmp_path, shared):
    _, state, _ = shared
    mgr = CheckpointManager(tmp_path / "c", save_best_for=("loss", "leq"), async_save=False)
    assert mgr.maybe_save_best({"loss": 1.0}, state, epoch=0)
    assert not mgr.maybe_save_best({"loss": 2.0}, state, epoch=1)
    assert mgr.maybe_save_best({"loss": 0.5}, state, epoch=2)
    mgr.close()


def test_best_value_survives_restore(tmp_path, shared):
    _, state, _ = shared
    mgr = CheckpointManager(tmp_path / "c", save_best_for=("accuracy", "geq"), async_save=False)
    mgr.maybe_save_best({"accuracy": 0.8}, state, epoch=3)
    mgr.close()
    # Fresh manager (new process analog): best threshold recovers from meta.
    mgr2 = CheckpointManager(tmp_path / "c", save_best_for=("accuracy", "geq"), async_save=False)
    mgr2.restore(BEST, state)
    assert mgr2.best_value == 0.8
    assert not mgr2.maybe_save_best({"accuracy": 0.7}, state, epoch=4)
    mgr2.close()


def test_epoch_name_and_missing(tmp_path, shared):
    _, state, _ = shared
    assert epoch_checkpoint_name(40) == "checkpoint_epoch_40"
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    with pytest.raises(FileNotFoundError):
        mgr.restore("nope", state)
    mgr.close()


def test_async_save_overwrite(tmp_path, shared):
    engine, state, _ = shared
    mgr = CheckpointManager(tmp_path / "c", async_save=True)
    mgr.save(LAST, state, epoch=1)
    mgr.save(LAST, state, epoch=2)  # overwrites; must wait for in-flight save
    restored, epoch = mgr.restore(LAST, state)
    assert epoch == 2
    mgr.close()


def test_logger(tmp_path, capsys):
    from distributed_training_pytorch_tpu.utils import Logger

    log_file = tmp_path / "runs" / "logfile.log"
    logger = Logger("VGG16", str(log_file))
    logger.log("hello", "info")
    logger.log("watch out", "warning")
    logger.log("boom", "error")
    logger.log("default path", "anything-else")  # maps to info (utils/logger.py:33)
    out = capsys.readouterr().out
    assert "hello" in out and "watch out" in out and "boom" in out
    content = log_file.read_text()
    assert "hello" in content and "WARNING" in content and "ERROR" in content
    assert "default path" in content


def test_max_to_keep_prunes_periodic_only(tmp_path, shared):
    """Retention keeps the newest N checkpoint_epoch_* and never touches
    best/last."""
    _, state, _ = shared
    mgr = CheckpointManager(tmp_path / "c", async_save=False, max_to_keep=2)
    for ep in (1, 2, 3, 4):
        mgr.save(epoch_checkpoint_name(ep), state, epoch=ep)
    mgr.save(LAST, state, epoch=5)  # triggers gc of committed periodics
    mgr.close()
    kept = sorted(p.name for p in (tmp_path / "c").iterdir())
    assert "last" in kept
    assert "checkpoint_epoch_4" in kept and "checkpoint_epoch_3" in kept
    assert "checkpoint_epoch_1" not in kept and "checkpoint_epoch_2" not in kept


def test_params_only_restore_across_prng_impls(tmp_path, shared):
    """A checkpoint saved by an rbg-keyed training run must restore
    params_only into a threefry-keyed eval process (key widths differ: 4 vs 2
    words) — regression for the eval_lm cross-impl failure."""
    from distributed_training_pytorch_tpu.train import TrainState

    _, state, _ = shared
    rbg_state = state.replace(rng=jax.random.key(0, impl="rbg"))
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    mgr.save("last", rbg_state, epoch=3)

    target = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=jax.tree.map(jnp.zeros_like, state.params),
        opt_state=(),
        model_state={},
        rng=jax.random.key(0),  # default threefry (2 words)
    )
    restored, epoch = mgr.restore("last", target, params_only=True)
    assert epoch == 3
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


# ---------------------------------------------------------------------------
# Sharded-state checkpointing (r4 VERDICT item 4): FSDP+TP-sharded TrainState
# round-trips, including onto a DIFFERENT mesh topology — the pod-scale resume
# capability (ref trainer/trainer.py:96-101 once params are sharded).


def _vit_engine(devices, axes, *, rules=None, min_size=2**18, seed=0, steps=0):
    from distributed_training_pytorch_tpu.models import ViTTiny

    mesh = mesh_lib.create_mesh(axes, devices=devices)
    model = ViTTiny(num_classes=4)

    def criterion(logits, batch):
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"loss": loss}

    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optax.sgd(0.05, momentum=0.9),
        mesh,
        sharding_rules=rules,
        fsdp_min_size=min_size,
    )
    state = engine.init_state(
        jax.random.key(seed), lambda r: model.init(r, jnp.zeros((1, 16, 16, 3)))
    )
    for i in range(steps):  # make step/opt-state momentum non-trivial
        rng = np.random.RandomState(i)
        batch = engine.shard_batch(
            {
                "image": rng.randn(8, 16, 16, 3).astype(np.float32),
                "label": rng.randint(0, 4, size=(8,)).astype(np.int32),
            }
        )
        state, _ = engine.train_step(state, batch)
    return engine, state


def _leaves_equal(a_state, b_state, *, opt=True):
    for a, b in zip(jax.tree.leaves(a_state.params), jax.tree.leaves(b_state.params), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    if opt:
        for a, b in zip(jax.tree.leaves(a_state.opt_state), jax.tree.leaves(b_state.opt_state), strict=True):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


SHARDED_AXES = {mesh_lib.DATA_AXIS: 2, mesh_lib.FSDP_AXIS: 2, mesh_lib.TENSOR_AXIS: 2}


@pytest.mark.slow
def test_sharded_roundtrip_same_mesh(tmp_path, devices):
    """FSDP+TP-sharded state (momentum + step included) survives save/restore
    onto the same mesh, and the restored leaves land with the target's
    shardings (not replicated)."""
    from distributed_training_pytorch_tpu.parallel.sharding import transformer_tp_rules

    engine, state = _vit_engine(
        devices, SHARDED_AXES, rules=transformer_tp_rules(), min_size=1024, steps=2
    )
    specs = [
        str(l.sharding.spec) for l in jax.tree.leaves(state.params) if hasattr(l, "sharding")
    ]
    assert any("fsdp" in s for s in specs) and any("tensor" in s for s in specs), specs

    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    mgr.save(LAST, state, epoch=3)
    mgr.close()

    engine2, target = _vit_engine(
        devices, SHARDED_AXES, rules=transformer_tp_rules(), min_size=1024, seed=1
    )
    mgr2 = CheckpointManager(tmp_path / "c", async_save=False)
    restored, epoch = mgr2.restore(LAST, target)
    mgr2.close()
    assert epoch == 3
    assert int(restored.step) == 2
    _leaves_equal(state, restored)
    # restored leaves keep the engine's sharded layout
    r_specs = [
        str(l.sharding.spec) for l in jax.tree.leaves(restored.params) if hasattr(l, "sharding")
    ]
    assert any("fsdp" in s for s in r_specs) and any("tensor" in s for s in r_specs), r_specs
    # and the engine can keep training from the restored state on its mesh
    rng = np.random.RandomState(9)
    batch = engine2.shard_batch(
        {
            "image": rng.randn(8, 16, 16, 3).astype(np.float32),
            "label": rng.randint(0, 4, size=(8,)).astype(np.int32),
        }
    )
    stepped, m = engine2.train_step(restored, batch)
    assert np.isfinite(float(m["loss"]))
    assert int(stepped.step) == 3


@pytest.mark.slow
def test_sharded_restore_onto_different_topology(tmp_path, devices):
    """A checkpoint saved from an 8-device data*fsdp*tensor mesh restores onto
    (a) a 4-device fsdp*tensor mesh and (b) a single-device replicated mesh —
    the resume-after-resize capability at pod scale."""
    from distributed_training_pytorch_tpu.parallel.sharding import transformer_tp_rules

    _, state = _vit_engine(
        devices, SHARDED_AXES, rules=transformer_tp_rules(), min_size=1024, steps=2
    )
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    mgr.save(LAST, state, epoch=5)
    mgr.close()

    # (a) fewer devices, different axis shape
    engine4, target4 = _vit_engine(
        devices[:4],
        {mesh_lib.FSDP_AXIS: 2, mesh_lib.TENSOR_AXIS: 2},
        rules=transformer_tp_rules(),
        min_size=1024,
        seed=2,
    )
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    restored4, epoch = mgr.restore(LAST, target4)
    assert epoch == 5
    _leaves_equal(state, restored4)
    batch_rng = np.random.RandomState(3)
    batch = engine4.shard_batch(
        {
            "image": batch_rng.randn(4, 16, 16, 3).astype(np.float32),
            "label": batch_rng.randint(0, 4, size=(4,)).astype(np.int32),
        }
    )
    _, m = engine4.train_step(restored4, batch)
    assert np.isfinite(float(m["loss"]))

    # (b) single device, fully replicated target
    _, target1 = _vit_engine(devices[:1], {mesh_lib.DATA_AXIS: 1}, seed=3)
    restored1, _ = mgr.restore(LAST, target1)
    mgr.close()
    _leaves_equal(state, restored1)


def test_meta_records_param_layout_and_reads_back(tmp_path, shared):
    """save() records the param tree's top level; read_meta returns it
    without a restore target — the wrapper-layout auto-select contract
    (examples/eval.py builds InputNormalizer targets from it)."""
    _, state, _ = shared
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    mgr.save(LAST, state, epoch=2)
    meta = mgr.read_meta(LAST)
    assert meta["epoch"] == 2
    assert meta["params_top_level"] == sorted(state.params.keys())

    # a wrapped-layout state (params nested under 'inner') records that
    wrapped = state.replace(params={"inner": state.params})
    mgr.save("wrapped", wrapped, epoch=3)
    assert mgr.read_meta("wrapped")["params_top_level"] == ["inner"]
    mgr.close()
