"""Headline benchmark: VGG16 / CIFAR-10-shape training throughput on TPU.

BASELINE.json metric: images/sec/chip (VGG16, CIFAR-10), north star >= 60% MFU.
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
``vs_baseline`` is measured MFU / 0.60 (the north-star MFU target — the
reference publishes no numbers of its own, BASELINE.md).

Runs on whatever jax.devices() provides (one real TPU chip under the driver;
CPU fallback works for smoke-testing with BENCH_STEPS/BENCH_BATCH overrides).
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_training_pytorch_tpu.models import VGG16
from distributed_training_pytorch_tpu.ops import cross_entropy_loss, accuracy
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss

# bf16 peak TFLOP/s per chip, by PJRT device_kind substring.
PEAK_FLOPS = {
    "v5 lite": 197e12,  # v5e litepod chip (197 bf16 TFLOP/s)
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,
    "cpu": 1e12,  # nominal, for smoke runs
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return 1e12


def main():
    batch = int(os.environ.get("BENCH_BATCH", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    image_size = int(os.environ.get("BENCH_IMAGE_SIZE", "32"))
    num_classes = 10

    mesh = mesh_lib.create_mesh()
    model = VGG16(num_classes=num_classes, dtype=jnp.bfloat16)

    def criterion(logits, b):
        loss = cross_entropy_loss(logits, b["label"])
        return loss, {"loss": loss, "accuracy": accuracy(logits, b["label"])}

    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optax.sgd(0.01, momentum=0.9),
        mesh,
    )
    state = engine.init_state(
        jax.random.key(0),
        lambda rng: model.init(rng, jnp.zeros((1, image_size, image_size, 3))),
    )

    rng = np.random.RandomState(0)
    host_batch = {
        "image": rng.randn(batch, image_size, image_size, 3).astype(np.float32),
        "label": rng.randint(0, num_classes, size=(batch,)).astype(np.int32),
    }
    gbatch = engine.shard_batch(host_batch)

    # Compile the engine's own step once (AOT), read XLA's FLOP estimate from
    # it, and run that same executable in the timed loop — one compile total.
    compiled = engine.compile_train_step(state, gbatch)
    cost = compiled.cost_analysis()
    step_flops = float(cost.get("flops", 0.0)) if cost else 0.0

    # Warmup, then timed loop. Sync via a scalar device_get —
    # block_until_ready alone can be a no-op on relay-backed platforms.
    state, m = compiled(state, gbatch)
    _ = float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = compiled(state, gbatch)
    _ = float(metrics["loss"])
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    images_per_sec = batch * steps / dt
    flops_per_sec = step_flops * steps / dt
    mfu = flops_per_sec / (peak_flops(jax.devices()[0]) * n_chips) if step_flops else 0.0

    print(
        json.dumps(
            {
                "metric": "images/sec/chip (VGG16, CIFAR-10-shape, bf16)",
                "value": round(images_per_sec / n_chips, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(mfu / 0.60, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
