"""Headline benchmark: VGG16 / CIFAR-10-shape training throughput on TPU.

BASELINE.json metric: images/sec/chip (VGG16, CIFAR-10), north star >= 60% MFU.
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
``vs_baseline`` is measured MFU / 0.60 (the north-star MFU target — the
reference publishes no numbers of its own, BASELINE.md).

MFU methodology (standard analytic convention, as in the PaLM paper / the
scaling book): model FLOPs are counted from layer shapes — 2*M*N*K per
conv/GEMM, backward pass = 2x forward — divided by wall time and the chip's
peak bf16 FLOP/s. XLA's own ``cost_analysis()`` estimate is reported alongside
(``mfu_xla``) for transparency; it systematically undercounts the conv
backward ops, so the analytic number is the headline. Timing is the best of
``BENCH_WINDOWS`` measured windows on an AOT-compiled step (one compile, no
retrace; best-of because the shared chip's interference only ever subtracts).

Perf defaults (measured on v5e, see utils/tpu.py): hardware-RBG PRNG for the
dropout masks (saves ~8% of step time vs threefry), global batch 4096
(MXU-filling for the FC trio on one chip, +15% over 1024; on multi-chip runs
raise BENCH_BATCH proportionally — the batch is sharded over the data axis),
and a per-compile scoped-VMEM bump (tpu_compiler_options, +9%).

Runs on whatever jax.devices() provides (one real TPU chip under the driver;
CPU fallback works for smoke-testing with BENCH_STEPS/BENCH_BATCH overrides).
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_training_pytorch_tpu.models import VGG16
from distributed_training_pytorch_tpu.ops import cross_entropy_loss, accuracy
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss
from distributed_training_pytorch_tpu.utils.tpu import enable_fast_rng, tpu_compiler_options

# bf16 peak TFLOP/s per chip, by PJRT device_kind substring.
PEAK_FLOPS = {
    "v5 lite": 197e12,  # v5e litepod chip (197 bf16 TFLOP/s)
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,
    "cpu": 1e12,  # nominal, for smoke runs
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return 1e12


def vgg16_train_flops_per_image(model: VGG16, image_size: int) -> float:
    """Analytic train-step FLOPs per image: 2*M*N*K per conv/FC, backward = 2x
    forward (standard MFU convention; pooling/activations not counted)."""
    fwd = 0.0
    size, in_ch = image_size, 3
    for feats, layers in zip(model.stage_features, model.stage_layers):
        for _ in range(layers):
            fwd += 2.0 * 9.0 * in_ch * feats * size * size  # 3x3 conv, same pad
            in_ch = feats
        size //= 2  # 2x2 max-pool
    width = in_ch * 7 * 7  # adaptive avg-pool to 7x7, flattened
    for out in (*model.classifier_widths, model.num_classes):
        fwd += 2.0 * width * out
        width = out
    return 3.0 * fwd  # fwd + bwd(2x fwd)


def vit_train_flops_per_image(model, image_size: int) -> float:
    """Analytic ViT train FLOPs per image (2*M*N*K per GEMM; attention counted
    as the two [T,T] matmuls per head group; backward = 2x forward)."""
    p, dm = model.patch_size, model.hidden_dim
    t = (image_size // p) ** 2 + 1  # patches + cls token
    fwd = 2.0 * (image_size // p) ** 2 * (p * p * 3) * dm  # patch embed conv
    per_layer = (
        2.0 * t * dm * 3 * dm  # qkv
        + 2.0 * 2.0 * t * t * dm  # scores + weighted sum
        + 2.0 * t * dm * dm  # out proj
        + 2.0 * 2.0 * t * dm * model.mlp_dim  # mlp in + out
    )
    fwd += model.depth * per_layer + 2.0 * dm * model.num_classes
    return 3.0 * fwd


def lm_train_flops_per_token(model, seq_len: int) -> float:
    """Analytic causal-LM train FLOPs per token: 6*P_matmul + 12*L*T*d
    attention (the standard 6N + attention convention; backward = 2x fwd
    folded into the 6)."""
    dm, L = model.hidden_dim, model.depth
    p_matmul = L * (4 * dm * dm + 2 * dm * model.mlp_dim) + model.vocab_size * dm
    return 6.0 * p_matmul + 12.0 * L * seq_len * dm


def _build_vgg16(num_classes):
    return VGG16(num_classes=num_classes, dtype=jnp.bfloat16)


def _build_vit(num_classes):
    from distributed_training_pytorch_tpu.models import ViTB16

    # BENCH_FLASH: unset/auto -> shape-aware adapter; 1 -> force the Pallas
    # kernel at any T; 0 -> plain XLA attention.
    flash_env = os.environ.get("BENCH_FLASH", "auto")
    use_flash = {"auto": None, "1": True, "0": False}[flash_env]
    return ViTB16(num_classes=num_classes, dtype=jnp.bfloat16, use_flash=use_flash)


def _build_lm(num_classes):
    from distributed_training_pytorch_tpu.models import GPTSmall

    del num_classes  # byte/GPT-2 vocab is part of the model config
    return GPTSmall(dtype=jnp.bfloat16)


def _image_batch(rng, batch, size, num_classes, model):
    del model
    return {
        "image": rng.randn(batch, size, size, 3).astype(np.float32),
        "label": rng.randint(0, num_classes, size=(batch,)).astype(np.int32),
    }


def _token_batch(rng, batch, size, num_classes, model):
    # vocab comes from the built model — one source of truth (a drifted
    # registry constant would silently clamp out-of-range ids under jit)
    del num_classes
    vocab = model.vocab_size
    return {
        "image": rng.randint(0, vocab, size=(batch, size)).astype(np.int32),
        "label": rng.randint(0, vocab, size=(batch, size)).astype(np.int32),
    }


def _image_example(size):
    return jnp.zeros((1, size, size, 3))


def _token_example(size):
    return jnp.zeros((1, size), jnp.int32)


def _supervised_loss(model):
    def criterion(logits, b):
        loss = cross_entropy_loss(logits, b["label"])
        return loss, {"loss": loss, "accuracy": accuracy(logits, b["label"])}

    return make_supervised_loss(model, criterion)


def _lm_fused_loss(model):
    # The training entry's exact loss (one implementation, bench == training).
    from distributed_training_pytorch_tpu.models.transformer_lm import make_fused_lm_loss

    return make_fused_lm_loss(model)


# One source of truth per BENCH_MODEL: builder, flops fn, defaults, metric.
BENCH_MODELS = {
    "vgg16": {
        "build": _build_vgg16,
        "flops": vgg16_train_flops_per_image,
        "batch": 4096,
        "image_size": 32,
        "num_classes": 10,
        "metric": "images/sec/chip (VGG16, CIFAR-10-shape, bf16)",
    },
    "vit": {
        "build": _build_vit,
        "flops": vit_train_flops_per_image,
        "batch": 256,
        "image_size": 224,
        "num_classes": 1000,
        "metric": "images/sec/chip (ViT-B/16, ImageNet-shape, bf16)",
    },
    # size = sequence length; throughput unit is tokens (batch*T items/step).
    "lm": {
        "build": _build_lm,
        "flops": lm_train_flops_per_token,
        "batch": 64,
        "image_size": 1024,
        "num_classes": 50257,
        "metric": "tokens/sec/chip (GPT-2-small, T=1024, bf16, fused tied-CE)",
        "unit": "tokens/sec/chip",
        "make_batch": _token_batch,
        "example_input": _token_example,
        "make_loss": _lm_fused_loss,
        "items_per_row": lambda size: size,
    },
}
for _cfg in BENCH_MODELS.values():
    _cfg.setdefault("unit", "images/sec/chip")
    _cfg.setdefault("make_batch", _image_batch)
    _cfg.setdefault("example_input", _image_example)
    _cfg.setdefault("make_loss", _supervised_loss)
    _cfg.setdefault("items_per_row", lambda size: 1)


def main():
    enable_fast_rng()
    model_name = os.environ.get("BENCH_MODEL", "vgg16")
    if model_name not in BENCH_MODELS:
        raise SystemExit(
            f"unknown BENCH_MODEL {model_name!r} (choose from {sorted(BENCH_MODELS)})"
        )
    cfg = BENCH_MODELS[model_name]
    batch = int(os.environ.get("BENCH_BATCH", str(cfg["batch"])))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    # Several short windows spread over ~1 min: the shared chip's slow phases
    # last tens of seconds, and best-of-windows should sample past them.
    windows = int(os.environ.get("BENCH_WINDOWS", "6"))
    image_size = int(os.environ.get("BENCH_IMAGE_SIZE", str(cfg["image_size"])))
    num_classes = cfg["num_classes"]

    mesh = mesh_lib.create_mesh()
    model, flops_fn = cfg["build"](num_classes), cfg["flops"]

    engine = TrainEngine(
        cfg["make_loss"](model),
        optax.sgd(0.01, momentum=0.9),
        mesh,
    )
    state = engine.init_state(
        jax.random.key(0),
        lambda rng: model.init(rng, cfg["example_input"](image_size)),
    )

    rng = np.random.RandomState(0)
    gbatch = engine.shard_batch(cfg["make_batch"](rng, batch, image_size, num_classes, model))

    # Compile the engine's own step once (AOT), read XLA's FLOP estimate from
    # it, and run that same executable in the timed loop — one compile total.
    # tpu_compiler_options: scoped-VMEM bump, measured +9% (utils/tpu.py).
    compiled = engine.compile_train_step(
        state, gbatch, compiler_options=tpu_compiler_options()
    )
    cost = compiled.cost_analysis()
    xla_step_flops = float(cost.get("flops", 0.0)) if cost else 0.0
    step_flops = flops_fn(model, image_size) * batch * cfg["items_per_row"](image_size)

    # Warmup, then best of `windows` timed windows — the chip is shared behind
    # a relay here and external interference only ever subtracts, so the
    # fastest window is the estimate of sustained capability (standard
    # microbenchmark practice). Sync via a scalar device_get —
    # block_until_ready alone can be a no-op on relay-backed platforms.
    state, m = compiled(state, gbatch)
    _ = float(m["loss"])
    per_step = []
    for w in range(windows):
        if w:
            time.sleep(float(os.environ.get("BENCH_WINDOW_GAP_S", "5")))
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = compiled(state, gbatch)
        _ = float(metrics["loss"])
        per_step.append((time.perf_counter() - t0) / steps)
    dt = min(per_step)

    n_chips = len(jax.devices())
    items = batch * cfg["items_per_row"](image_size)
    images_per_sec = items / dt
    peak = peak_flops(jax.devices()[0]) * n_chips
    mfu = step_flops / dt / peak
    mfu_xla = xla_step_flops / dt / peak if xla_step_flops else 0.0

    print(
        json.dumps(
            {
                "metric": cfg["metric"],
                "value": round(images_per_sec / n_chips, 2),
                "unit": cfg["unit"],
                "vs_baseline": round(mfu / 0.60, 4),
                "mfu": round(mfu, 4),
                "mfu_xla": round(mfu_xla, 4),
                "batch": batch,
                "step_ms": round(dt * 1e3, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
