"""Headline benchmark: VGG16 / CIFAR-10-shape training throughput on TPU.

BASELINE.json metric: images/sec/chip (VGG16, CIFAR-10), north star >= 60% MFU.
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
``vs_baseline`` is measured MFU / 0.60 (the north-star MFU target — the
reference publishes no numbers of its own, BASELINE.md).

MFU methodology (standard analytic convention, as in the PaLM paper / the
scaling book): model FLOPs are counted from layer shapes — 2*M*N*K per
conv/GEMM, backward pass = 2x forward — divided by wall time and the chip's
peak bf16 FLOP/s. That nominal count is the headline (it is the work an
eager executor like the torch reference performs); ``mfu_exec`` (HLO
conv/dot recount of what the compiler kept after folding — see
utils/hlo_flops.py and the r4 itemization in BASELINE.md) and ``mfu_xla``
(``cost_analysis()``, executed matmuls + VPU elementwise) are reported
alongside. Timing is the best of ``BENCH_WINDOWS`` measured windows on an
AOT-compiled step (one compile, no retrace; best-of because the shared
chip's interference only ever subtracts).

Perf defaults (measured on v5e, see utils/tpu.py): hardware-RBG PRNG for the
dropout masks (saves ~8% of step time vs threefry), global batch 4096
(MXU-filling for the FC trio on one chip, +15% over 1024; on multi-chip runs
raise BENCH_BATCH proportionally — the batch is sharded over the data axis),
and a per-compile scoped-VMEM bump (tpu_compiler_options, +9%).

Runs on whatever jax.devices() provides (one real TPU chip under the driver;
CPU fallback works for smoke-testing with BENCH_STEPS/BENCH_BATCH overrides).
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_training_pytorch_tpu import memory as memory_lib
from distributed_training_pytorch_tpu.models import VGG16
from distributed_training_pytorch_tpu.ops import cross_entropy_loss, accuracy
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.telemetry import GoodputMeter
from distributed_training_pytorch_tpu.telemetry import mfu as mfu_lib
from distributed_training_pytorch_tpu.telemetry.provenance import provenance_fields
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss
from distributed_training_pytorch_tpu.utils import hlo_flops
from distributed_training_pytorch_tpu.utils.tpu import enable_fast_rng, tpu_compiler_options

# Peak-FLOPs table + lookup live in telemetry/mfu.py (ISSUE 4) — one source
# of truth shared with the Trainer's per-window MFU reports; re-exported here
# under the historical bench names.
PEAK_FLOPS = mfu_lib.PEAK_FLOPS
peak_flops = mfu_lib.device_peak_flops


def vgg16_train_flops_per_image(model: VGG16, image_size: int) -> float:
    """Analytic train-step FLOPs per image: 2*M*N*K per conv/FC, backward = 2x
    forward (standard MFU convention; pooling/activations not counted)."""
    fwd = 0.0
    size, in_ch = image_size, 3
    for feats, layers in zip(model.stage_features, model.stage_layers, strict=True):
        for _ in range(layers):
            fwd += 2.0 * 9.0 * in_ch * feats * size * size  # 3x3 conv, same pad
            in_ch = feats
        size //= 2  # 2x2 max-pool
    width = in_ch * 7 * 7  # adaptive avg-pool to 7x7, flattened
    for out in (*model.classifier_widths, model.num_classes):
        fwd += 2.0 * width * out
        width = out
    return 3.0 * fwd  # fwd + bwd(2x fwd)


def vit_train_flops_per_image(model, image_size: int) -> float:
    """Analytic ViT train FLOPs per image (2*M*N*K per GEMM; attention counted
    as the two [T,T] matmuls per head group; backward = 2x forward)."""
    p, dm = model.patch_size, model.hidden_dim
    t = (image_size // p) ** 2 + 1  # patches + cls token
    fwd = 2.0 * (image_size // p) ** 2 * (p * p * 3) * dm  # patch embed conv
    per_layer = (
        2.0 * t * dm * 3 * dm  # qkv
        + 2.0 * 2.0 * t * t * dm  # scores + weighted sum
        + 2.0 * t * dm * dm  # out proj
        + 2.0 * 2.0 * t * dm * model.mlp_dim  # mlp in + out
    )
    fwd += model.depth * per_layer + 2.0 * dm * model.num_classes
    return 3.0 * fwd


def resnet_train_flops_per_image(model, image_size: int) -> float:
    """Analytic bottleneck-ResNet train FLOPs per image (2*HW*K^2*Cin*Cout per
    conv; backward = 2x forward; BN/ReLU/pool not counted)."""
    fwd = 0.0
    size = image_size // 2  # 7x7/2 stem
    fwd += 2.0 * size * size * 49 * 3 * model.width
    size //= 2  # 3x3/2 max-pool
    in_ch = model.width
    for stage, num_blocks in enumerate(model.stage_sizes):
        feats = model.width * (2**stage)
        for block in range(num_blocks):
            stride = 2 if stage > 0 and block == 0 else 1
            out_size = size // stride
            fwd += 2.0 * size * size * in_ch * feats  # 1x1 reduce (pre-stride)
            fwd += 2.0 * out_size * out_size * 9 * feats * feats  # 3x3 (strided)
            fwd += 2.0 * out_size * out_size * feats * 4 * feats  # 1x1 expand
            if stride != 1 or in_ch != 4 * feats:  # projection shortcut
                fwd += 2.0 * out_size * out_size * in_ch * 4 * feats
            in_ch, size = 4 * feats, out_size
    fwd += 2.0 * in_ch * model.num_classes
    return 3.0 * fwd


def convnext_train_flops_per_image(model, image_size: int) -> float:
    """Analytic ConvNeXt train FLOPs per image (stem + depthwise 7x7 + the
    dim<->4dim MLP pair per block + 2x2 downsamples; backward = 2x forward)."""
    size = image_size // 4
    fwd = 2.0 * size * size * 16 * 3 * model.dims[0]  # 4x4/4 stem
    for stage, (depth, dim) in enumerate(zip(model.depths, model.dims, strict=True)):
        if stage > 0:
            size //= 2
            fwd += 2.0 * size * size * 4 * model.dims[stage - 1] * dim  # 2x2/2
        per_block = (
            2.0 * size * size * 49 * dim  # depthwise 7x7
            + 2.0 * 2.0 * size * size * dim * 4 * dim  # MLP in + out
        )
        fwd += depth * per_block
    fwd += 2.0 * model.dims[-1] * model.num_classes
    return 3.0 * fwd


def lm_train_flops_per_token(model, seq_len: int) -> float:
    """Analytic causal-LM train FLOPs per token: 6*P_matmul + 12*L*T*d
    attention (the standard 6N + attention convention; backward = 2x fwd
    folded into the 6)."""
    dm, L = model.hidden_dim, model.depth
    p_matmul = L * (4 * dm * dm + 2 * dm * model.mlp_dim) + model.vocab_size * dm
    return 6.0 * p_matmul + 12.0 * L * seq_len * dm


# BENCH_DTYPE (ISSUE 3 satellite): compute dtype of the benched step —
# fp32 | bf16 | fp16, or a comma list ("fp32,bf16,fp16") for a sweep that
# prints ONE json line per dtype. Unset reproduces the historical program
# exactly: model-internal bf16 casts, no precision policy in the engine.
# When set, the model is built with that dtype AND the engine applies the
# matching precision.Policy (fp16 adds dynamic loss scaling), so the timed
# step is the one Trainer(precision=...) runs.
BENCH_DTYPES = {"fp32": jnp.float32, "bf16": jnp.bfloat16, "fp16": jnp.float16}


def _bench_dtype(dtype_name):
    """Model dtype for a BENCH_DTYPE value (None = historical bf16 default)."""
    if dtype_name is None:
        return jnp.bfloat16
    if dtype_name not in BENCH_DTYPES:
        raise SystemExit(
            f"unknown BENCH_DTYPE {dtype_name!r} (choose from {sorted(BENCH_DTYPES)})"
        )
    return BENCH_DTYPES[dtype_name]


def _metric_name(cfg, image_size, dtype_name):
    """The entry's self-describing metric string — ONE implementation for
    the success line and the OOM-net line, so a sweep's structured OOM
    record always joins against its sibling entries' metric strings.
    Metric templates name the historical bf16 dtype; a BENCH_DTYPE override
    renames them."""
    return (
        cfg["metric"].format(size=image_size).replace("bf16", dtype_name or "bf16")
    )


# BENCH_MESH (ISSUE 10 satellite): mesh layout of the benched step — a
# spec like "dp8" / "fsdp4x2" / "tp2x4" / "dp2fsdp2tp2" (grammar:
# parallel.mesh.mesh_config_from_spec; docs/parallelism.md), or a comma
# list for a sweep that prints ONE json line per mesh with `mesh`,
# `mesh_axes`, `batch_replicas`, `per_chip_param_bytes`, and the
# per-replica throughput fields alongside the usual per-chip headline —
# the MULTICHIP_r evidence that fsdp/tensor meshes actually shrink
# per-chip HBM and scale out. Unset reproduces the historical 1-D data
# mesh exactly. A tensor>1 mesh applies parallel.transformer_tp_rules
# (conv models match none of its patterns and take the FSDP fallback).
def _bench_mesh(mesh_spec):
    """Build (and validate) the mesh for a BENCH_MESH value. None = the
    historical default data mesh."""
    if mesh_spec is None:
        return mesh_lib.create_mesh()
    try:
        return mesh_lib.mesh_config_from_spec(mesh_spec).build()
    except ValueError as e:
        raise SystemExit(f"BENCH_MESH: {e}") from e


def _bench_memory(compiled, include_peak=True, predicted=None):
    """Per-step device memory: live/peak bytes from the PJRT allocator where
    the backend exposes them (``memory.live.live_memory_fields`` — the ONE
    memory_stats read shared with trainer telemetry and preflight; TPU has
    it, read after the timed windows so peak covers the real step), else
    XLA's ``bytes accessed`` estimate from the compiled program (CPU smoke
    runs). ``predicted_peak_bytes`` (``compiled.memory_analysis()``, the
    preflight predictor) rides every entry so predicted-vs-measured cannot
    silently drift across rounds.

    ``include_peak=False`` for every sweep run after the first:
    ``peak_bytes`` is a process-lifetime high-water mark with no reset
    (the ``memory.live`` documented caveat), so a later (smaller) dtype's
    peak would silently report the earlier run's — live_bytes stays valid
    per-run."""
    out = memory_lib.live_memory_fields(include_peak=include_peak)
    if not out:
        ba = hlo_flops.bytes_accessed(compiled)
        out = {"hlo_bytes_accessed": int(ba)} if ba else {}
    if predicted is None:  # not already captured by the caller's OOM-net ctx
        predicted = memory_lib.predicted_peak_bytes(compiled)
    if predicted is not None:
        out["predicted_peak_bytes"] = predicted
    return out


# BENCH_PALLAS (ISSUE 17): the unified kernel-policy knob (ops/dispatch.py)
# for the benched model — 1 forces the Pallas hot paths, 0 forces plain,
# unset keeps each model's auto policy (the historical program, bit-exact).
# Parsed by the same pallas_from_env the example entries use; every builder
# receives the resolved tri-state.
def _bench_pallas():
    from distributed_training_pytorch_tpu.ops.dispatch import pallas_from_env

    return pallas_from_env(os.environ.get("BENCH_PALLAS"))


def _build_vgg16(num_classes, image_size, dtype, pallas):
    del image_size
    # Via create_model: VGG16 has no fused-kernel coverage and the factory
    # records that resolution once when the knob is set (ops/dispatch.py).
    from distributed_training_pytorch_tpu.models import create_model

    return create_model("vgg16", num_classes, dtype=dtype, pallas=pallas)


def _build_vit(num_classes, image_size, dtype, pallas):
    del image_size
    from distributed_training_pytorch_tpu.models import ViTB16

    # BENCH_FLASH: unset/auto -> shape-aware adapter; 1 -> force the Pallas
    # kernel at any T; 0 -> plain XLA attention. BENCH_PALLAS overrides it
    # (the unified knob wins over the legacy one, models/vit.py).
    flash_env = os.environ.get("BENCH_FLASH", "auto")
    use_flash = {"auto": None, "1": True, "0": False}[flash_env]
    # BENCH_PAD_SEQ: pad the token stream to this length (0 = off). 256 tiles
    # ViT-B's T=197 onto the 128-lane MXU exactly (models/vit.py pad_seq_to).
    pad_seq = int(os.environ.get("BENCH_PAD_SEQ", "0")) or None
    return ViTB16(
        num_classes=num_classes, dtype=dtype, use_flash=use_flash,
        pad_seq_to=pad_seq, pallas=pallas,
    )


def _build_lm(num_classes, image_size, dtype, pallas):
    from distributed_training_pytorch_tpu.models import GPTSmall

    del num_classes  # byte/GPT-2 vocab is part of the model config
    # image_size = sequence length here; long-context runs stretch max_len
    # with it (the flash kernel auto-routes at T>=512).
    return GPTSmall(dtype=dtype, max_len=max(1024, image_size), pallas=pallas)


def _image_batch(rng, batch, size, num_classes, model):
    del model
    return {
        "image": rng.randn(batch, size, size, 3).astype(np.float32),
        "label": rng.randint(0, num_classes, size=(batch,)).astype(np.int32),
    }


def _token_batch(rng, batch, size, num_classes, model):
    # vocab comes from the built model — one source of truth (a drifted
    # registry constant would silently clamp out-of-range ids under jit)
    del num_classes
    vocab = model.vocab_size
    return {
        "image": rng.randint(0, vocab, size=(batch, size)).astype(np.int32),
        "label": rng.randint(0, vocab, size=(batch, size)).astype(np.int32),
    }


def _image_example(size):
    return jnp.zeros((1, size, size, 3))


def _token_example(size):
    return jnp.zeros((1, size), jnp.int32)


def _supervised_loss(model):
    def criterion(logits, b):
        loss = cross_entropy_loss(logits, b["label"])
        return loss, {"loss": loss, "accuracy": accuracy(logits, b["label"])}

    return make_supervised_loss(model, criterion)


def _lm_fused_loss(model):
    # The training entry's exact loss (one implementation, bench == training).
    from distributed_training_pytorch_tpu.models.transformer_lm import make_fused_lm_loss

    return make_fused_lm_loss(model)


# One source of truth per BENCH_MODEL: builder, flops fn, defaults, metric.
BENCH_MODELS = {
    "vgg16": {
        "build": _build_vgg16,
        "flops": vgg16_train_flops_per_image,
        "batch": 4096,
        "image_size": 32,
        "num_classes": 10,
        "metric": "images/sec/chip (VGG16, CIFAR-10-shape, bf16)",
    },
    "vit": {
        "build": _build_vit,
        "flops": vit_train_flops_per_image,
        # Per-chip batch swept on v5e (r4): 96 and 192 are the optima — 930/
        # 932 img/s vs 751 at 256 (the r3 default); 884@64, 894@80, 740@112,
        # 779@128, 902@160, 753@224. Off-optimum batches push XLA into
        # rematerializing the [B,12,197,197] attention tensors in backward
        # (profile shows .remat fusions); at 96/192 the live-set fits and the
        # recompute disappears. 192 is the default (bigger batch, same
        # per-image efficiency: full bench measured 949 img/s, 50.8% MFU).
        # In a DP pod the global batch is 192 x n_chips.
        "batch": 192,
        "image_size": 224,
        "num_classes": 1000,
        "metric": "images/sec/chip (ViT-B/16, ImageNet-shape, bf16)",
    },
    "resnet50": {
        # BENCH_PALLAS_1X1=1: the bandwidth-bound STAGE-1 1x1 convs (56x56
        # maps — BottleneckBlock gates on input spatial >= 56) run the Pallas
        # GEMM kernel (models.resnet.PallasConv1x1) instead of XLA's conv.
        # r5 probe: kernel 72% vs XLA 45% of the HBM bandwidth floor in
        # isolation, but the full step measures SLOWER (fusion-barrier cost;
        # BASELINE.md "ResNet-50" r5 section) — the flag exists to reproduce
        # that measurement, not as a perf default.
        "build": lambda n, size, dtype, pallas: __import__(
            "distributed_training_pytorch_tpu.models", fromlist=["ResNet50"]
        ).ResNet50(
            num_classes=n, dtype=dtype,
            pallas_1x1=os.environ.get("BENCH_PALLAS_1X1", "0") == "1",
            pallas=pallas,
        ),
        "flops": resnet_train_flops_per_image,
        "batch": 256,
        "image_size": 224,
        "num_classes": 1000,
        "metric": "images/sec/chip (ResNet-50, ImageNet-shape, bf16)",
    },
    "convnext_l": {
        "build": lambda n, size, dtype, pallas: __import__(
            "distributed_training_pytorch_tpu.models", fromlist=["ConvNeXtL"]
        ).ConvNeXtL(num_classes=n, dtype=dtype, pallas=pallas),
        "flops": convnext_train_flops_per_image,
        # r4 sweep: plain-step img/s rises monotonically to microbatch 128
        # (402@32, 441@64, 452@96, 475@128) and cliffs at 192 (405), so the
        # accum-4 config runs microbatch 128 = batch 512. Scoped-VMEM is
        # model-specific again: 98304 KiB is +6% here (503 img/s plain step)
        # while 49152 — the VGG/ViT value — is catastrophic (289).
        "batch": 512,
        "image_size": 224,
        "num_classes": 21841,
        # BASELINE config 5 is defined WITH grad accumulation; the timed
        # executable includes the accum microbatch scan (BENCH_ACCUM=1 to
        # measure the plain step).
        "accum_steps": 4,
        "metric": "images/sec/chip (ConvNeXt-L, ImageNet-21k-shape, bf16, accum 4)",
        "compiler_options": lambda: {"xla_tpu_scoped_vmem_limit_kib": "98304"},
    },
    # size = sequence length; throughput unit is tokens (batch*T items/step).
    "lm": {
        "build": _build_lm,
        "flops": lm_train_flops_per_token,
        "batch": 64,
        "image_size": 1024,
        "num_classes": 50257,
        "metric": "tokens/sec/chip (GPT-2-small, T={size}, bf16, fused tied-CE)",
        "unit": "tokens/sec/chip",
        "make_batch": _token_batch,
        "example_input": _token_example,
        "make_loss": _lm_fused_loss,
        "items_per_row": lambda size: size,
    },
}
for _name, _cfg in BENCH_MODELS.items():
    _cfg.setdefault("unit", "images/sec/chip")
    _cfg.setdefault("make_batch", _image_batch)
    _cfg.setdefault("example_input", _image_example)
    _cfg.setdefault("make_loss", _supervised_loss)
    _cfg.setdefault("items_per_row", lambda size: 1)
    # The scoped-VMEM bump is a VGG16-shape win (+9%); on ResNet-50 it
    # MEASURABLY hurts (-3..5%: the deeper conv stack's weight-prefetch
    # copies spill, v5e sweep None/32768/65536/98304). Per-model option sets.
    _cfg.setdefault(
        "compiler_options", tpu_compiler_options if _name in ("vgg16", "vit", "lm") else dict
    )


def build_bench_setup(model_name: str | None = None, dtype_name: str | None = None,
                      mesh_spec: str | None = None):
    """One source of truth for the executable a ``BENCH_MODEL`` names: build
    the registry model + engine + AOT state + sharded batch + per-model
    compiler options from the same env knobs ``main()`` honors. Used by
    ``main()`` and ``scripts/profile_step.py`` so the profiled program IS the
    timed one.

    ``dtype_name`` is ONE ``BENCH_DTYPE`` value (callers handle the sweep);
    None = the historical program (bf16 model casts, no engine policy).
    ``mesh_spec`` is ONE ``BENCH_MESH`` value; None = the historical 1-D
    data mesh with replicated state."""
    model_name = model_name or os.environ.get("BENCH_MODEL", "vgg16")
    if model_name not in BENCH_MODELS:
        raise SystemExit(
            f"unknown BENCH_MODEL {model_name!r} (choose from {sorted(BENCH_MODELS)})"
        )
    cfg = BENCH_MODELS[model_name]
    batch = int(os.environ.get("BENCH_BATCH", str(cfg["batch"])))
    image_size = int(os.environ.get("BENCH_IMAGE_SIZE", str(cfg["image_size"])))
    # Resolved ONCE here; every consumer (engine, main, run_e2e_records)
    # takes it from the returned dict so the knob cannot drift.
    accum_steps = int(os.environ.get("BENCH_ACCUM", str(cfg.get("accum_steps", 1))))
    mesh = _bench_mesh(mesh_spec)
    replicas = mesh_lib.batch_shard_extent(mesh)
    if batch % replicas:
        knob = (
            f"BENCH_MESH {mesh_spec!r}"
            if mesh_spec is not None
            else f"BENCH_BATCH on the default {replicas}-way data mesh"
        )
        raise SystemExit(
            f"{knob}: batch {batch} is not divisible by the mesh's "
            f"batch-shard extent {replicas} (data x fsdp) — round "
            "BENCH_BATCH or re-plan the mesh"
        )
    # ONE rule-resolution policy with the Trainer (parallel.sharding.
    # default_sharding_rules): the benched program is the trained one.
    from distributed_training_pytorch_tpu.parallel import default_sharding_rules

    sharding_rules = default_sharding_rules(mesh)
    model = cfg["build"](
        cfg["num_classes"], image_size, _bench_dtype(dtype_name), _bench_pallas()
    )
    loss_scale = None
    if dtype_name == "fp16":
        from distributed_training_pytorch_tpu.precision import DynamicScale

        loss_scale = DynamicScale.create()
    engine = TrainEngine(
        cfg["make_loss"](model),
        optax.sgd(0.01, momentum=0.9),
        mesh,
        accum_steps=accum_steps,
        precision=dtype_name,  # None -> inactive fp32 policy (historical)
        loss_scale=loss_scale,
        sharding_rules=sharding_rules,
    )
    state = engine.init_state(
        jax.random.key(0),
        lambda rng: model.init(rng, cfg["example_input"](image_size)),
    )
    rng = np.random.RandomState(0)
    gbatch = engine.shard_batch(
        cfg["make_batch"](rng, batch, image_size, cfg["num_classes"], model)
    )
    return {
        "model_name": model_name,
        "cfg": cfg,
        "batch": batch,
        "image_size": image_size,
        "model": model,
        "engine": engine,
        "state": state,
        "gbatch": gbatch,
        "accum_steps": accum_steps,
        "dtype_name": dtype_name,
        "mesh_spec": mesh_spec,
        "mesh": mesh,
        "compiler_options": cfg["compiler_options"]() or None,
    }


def _time_epochs(trainer, epochs: int, batch: int) -> dict:
    """Shared e2e timing protocol: run ``epochs + 1`` full ``train_epoch``
    passes, discard epoch 0 (compiles), report the best remaining epoch
    (shared-chip interference only subtracts)."""
    import time as _time

    n_images = len(trainer.train_dataloader) * batch
    times = []
    for epoch in range(epochs + 1):
        trainer.train_dataloader.set_epoch(epoch)
        t0 = _time.perf_counter()
        trainer.train_epoch(epoch)  # epoch-metric device_get = sync
        times.append(_time.perf_counter() - t0)
    dt = min(times[1:])
    return {"e2e_images_per_sec": n_images / dt, "e2e_epoch_s": dt, "e2e_images": n_images}


def run_e2e_records(
    model_name: str, batch: int, epochs: int, image_size: int,
    num_classes: int = 1000, accum_steps: int = 1,
) -> dict:
    """End-to-end throughput for the at-scale records input path (BASELINE
    configs 3-5): pack synthetic JPEGs into .rec shards, then drive the FULL
    ``ImageNetTrainer.train_epoch`` hot path — RecordFileSource -> threaded
    decode + random-resized-crop/flip/normalize -> ``device_prefetch`` ->
    jitted step — exactly what ``MODEL=resnet50 ./run.sh`` runs with
    ``IMAGENET_RECORDS`` set. Epoch 0 pays compiles and is discarded."""
    import shutil
    import sys
    import tempfile

    import cv2

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from examples.train_imagenet import ImageNetTrainer

    from distributed_training_pytorch_tpu.data.records import write_shards
    from distributed_training_pytorch_tpu.utils import Logger

    tmp = tempfile.mkdtemp(prefix="bench_e2e_rec_")
    steps = int(os.environ.get("BENCH_E2E_STEPS", "8"))
    n = steps * batch
    rng = np.random.RandomState(0)

    def payloads():
        for i in range(n):
            img = (rng.randn(256, 256, 3) * 40 + 110).clip(0, 255).astype(np.uint8)
            ok, buf = cv2.imencode(".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, 90])
            assert ok
            yield buf.tobytes(), int(rng.randint(0, num_classes))

    write_shards(os.path.join(tmp, "train"), payloads(), num_shards=4)
    # ImageNetTrainer reads these env knobs; save/restore any caller values.
    saved = {k: os.environ.get(k) for k in ("IMAGENET_RECORDS", "NUM_CLASSES")}
    os.environ["IMAGENET_RECORDS"] = os.path.join(tmp, "train-*.rec")
    os.environ["NUM_CLASSES"] = str(num_classes)
    try:
        trainer = ImageNetTrainer(
            model_name=model_name,
            image_size=image_size,
            base_lr=0.1,
            max_epoch=epochs + 1,
            batch_size=batch,
            have_validate=False,
            save_folder=tmp,
            snapshot_path=None,
            progress=False,
            # The config's own accumulation (convnext_l: 4): batch 512
            # without the microbatch split OOMs on one chip.
            accum_steps=accum_steps,
            logger=Logger("bench-e2e-rec", os.path.join(tmp, "log.log")),
        )
        return _time_epochs(trainer, epochs, batch)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)


def run_e2e(batch: int, epochs: int, chain_steps: int = 1) -> dict:
    """End-to-end throughput: the FULL ``Trainer.train_epoch`` hot path —
    ShardedLoader -> native C++ crop/flip (uint8) -> ``device_prefetch`` ->
    on-device normalize -> jitted step — on materialized (synthetic-CIFAR)
    data. This is the loop the reference times implicitly by training
    (``trainer/trainer.py:143-156``); the step microbench above excludes the
    input pipeline. Epoch 0 pays compiles and is discarded; the best
    remaining epoch is reported (interference on the shared relay chip only
    subtracts). ``chain_steps > 1`` runs the trainer's chained-window mode
    (windows of that many steps dispatch as one device program)."""
    import shutil
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from examples.train_cifar10 import Cifar10Trainer

    from distributed_training_pytorch_tpu.utils import Logger

    tmp = tempfile.mkdtemp(prefix="bench_e2e_")
    trainer = Cifar10Trainer(
        data_dir=os.path.join(tmp, "no-such-dir"),  # -> synthetic CIFAR shape
        base_lr=0.1,
        max_epoch=epochs + 1,
        batch_size=batch,
        have_validate=False,
        save_folder=tmp,
        snapshot_path=None,
        progress=False,
        chain_steps=chain_steps,
        # keep stdout to the ONE json line the driver parses
        logger=Logger("bench-e2e", os.path.join(tmp, "log.log")),
    )
    try:
        return _time_epochs(trainer, epochs, batch)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _time_windows(run_once, state, steps, windows, reduce, meter=None):
    """The one window-timing protocol every measurement uses: warm once, then
    ``windows`` timed windows separated by ``BENCH_WINDOW_GAP_S`` (the shared
    chip's slow phases last tens of seconds; spacing windows samples past
    them), each synced via a scalar device_get (``block_until_ready`` alone
    can be a no-op on relay-backed platforms). ``run_once(state) -> (state,
    metrics)`` runs one window of ``steps`` steps. Returns the carried state
    and the best (or ``reduce="median"``: median) per-step seconds.

    ``meter`` (a ``telemetry.GoodputMeter``) attributes the deliberate
    inter-window gap sleeps to ``other`` — harness pacing is not productive
    step time; the caller ticks ``productive_step`` after the return."""
    state, m = run_once(state)
    _ = float(m["loss"])
    per_step = []
    for w in range(windows):
        if w:
            if meter is not None:
                meter.tick("productive_step")
            time.sleep(float(os.environ.get("BENCH_WINDOW_GAP_S", "5")))
            if meter is not None:
                meter.tick("other")
        t0 = time.perf_counter()
        state, m = run_once(state)
        _ = float(m["loss"])
        per_step.append((time.perf_counter() - t0) / steps)
    dt = float(np.median(per_step)) if reduce == "median" else min(per_step)
    return state, dt


def _run_bench(dtype_name: str | None = None, include_peak: bool = True, ctx=None,
               mesh_spec: str | None = None):
    """One full measurement -> one JSON line. ``ctx`` (a dict) is filled with
    the entry's identity and predicted peak as soon as they are known, so the
    sweep loop's OOM net (``main``) can emit a structured line for an entry
    that died mid-measurement."""
    enable_fast_rng()
    # Goodput accounting for the bench run itself (ISSUE 4 satellite,
    # telemetry/goodput.py — the same meter the Trainer carries through
    # checkpoints): compile vs productive-step vs harness-overhead wall time,
    # emitted as bucket fractions in the JSON line so a sweep shows where a
    # config's wall clock went (ConvNeXt-L pays ~10x VGG's compile bill).
    meter = GoodputMeter()
    meter.start()
    setup = build_bench_setup(dtype_name=dtype_name, mesh_spec=mesh_spec)
    meter.tick("other")  # model build + state init + batch staging
    model_name, cfg = setup["model_name"], setup["cfg"]
    batch, image_size = setup["batch"], setup["image_size"]
    if ctx is not None:
        ctx["metric"] = _metric_name(cfg, image_size, dtype_name)
        ctx["batch"] = batch
        if mesh_spec is not None:
            ctx["mesh"] = mesh_spec
    model, engine, state, gbatch = (
        setup["model"], setup["engine"], setup["state"], setup["gbatch"]
    )
    flops_fn = cfg["flops"]
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    # Several short windows spread over ~1 min: the shared chip's slow phases
    # last tens of seconds, and best-of-windows should sample past them.
    windows = int(os.environ.get("BENCH_WINDOWS", "6"))

    # Compile the engine's own step once (AOT), read XLA's FLOP estimate from
    # it, and run that same executable in the timed loop — one compile total.
    # Per-model compiler options (scoped-VMEM bump where it measures faster).
    #
    # BENCH_CHAIN (default on): the window's `steps` train steps are chained
    # on-device (engine.compile_chained_train_steps) so one dispatch runs the
    # whole window back-to-back — the production dispatch regime (local PJRT
    # ~0.1 ms/call). Per-call dispatch through this environment's chip relay
    # costs ~6-8 ms, which is harness artifact, not step time. BENCH_CHAIN=0
    # restores per-step dispatch for comparison.
    chain = os.environ.get("BENCH_CHAIN", "1") != "0"
    opts = setup["compiler_options"]
    step_flops = flops_fn(model, image_size) * batch * cfg["items_per_row"](image_size)
    if chain:
        # One backend compile total: XLA's FLOP estimate comes from the
        # chained executable itself. cost_analysis counts the scan BODY once
        # (verified on v5e: chained flops == single-step flops exactly), so
        # it already IS the per-step figure.
        compiled = engine.compile_chained_train_steps(
            state, gbatch, steps, compiler_options=opts
        )
        cost = hlo_flops.xla_cost_analysis(compiled)
        xla_step_flops = float(cost.get("flops", 0.0))
        # Guard (ADVICE r3): the per-step figure above relies on XLA counting
        # the scan body ONCE (verified on this version: chained == single-step
        # flops exactly). If a future XLA multiplies by trip count, the
        # chained figure lands ~steps x the analytic count — detect that via
        # the analytic anchor (XLA's own count never exceeds ~1.2x analytic;
        # an excess beyond max(steps/2, 2) can only be trip-count
        # multiplication — the floor of 2 keeps a legitimate ~1.2x ratio from
        # tripping the guard at small BENCH_STEPS) and divide back down
        # rather than silently inflating mfu_xla.
        if steps > 1 and step_flops > 0 and xla_step_flops / step_flops > max(steps / 2, 2):
            # never silent (ADVICE r4): this rewrites a measured number
            print(
                f"bench: trip-count guard fired — cost_analysis {xla_step_flops:.3e} "
                f"~ {xla_step_flops / step_flops:.1f}x analytic; dividing by "
                f"steps={steps} (XLA appears to count the chained scan body "
                "per-trip on this version)",
                file=sys.stderr,
            )
            xla_step_flops /= steps
        run_window = lambda st: compiled(st, gbatch)
    else:
        probe = engine.compile_train_step(state, gbatch, compiler_options=opts)
        cost = hlo_flops.xla_cost_analysis(probe)
        xla_step_flops = float(cost.get("flops", 0.0))

        def run_window(st):
            for _ in range(steps):
                st, metrics = probe(st, gbatch)
            return st, metrics

    meter.tick("compile")  # the AOT compile above (XLA, one per run)
    if ctx is not None:
        # Known before the first dispatch: an entry that OOMs in the timed
        # windows still reports the peak the preflight math predicted for it.
        predicted = memory_lib.predicted_peak_bytes(compiled if chain else probe)
        if predicted is not None:
            ctx["predicted_peak_bytes"] = predicted

    # Warmup, then best of `windows` timed windows (the shared relay chip's
    # interference only ever subtracts; BENCH_REDUCE=median reports the
    # median instead — measured ~5% below best-of, the spread being relay
    # noise, not step variance: chained windows pin the device loop).
    reduce = os.environ.get("BENCH_REDUCE", "min")
    state, dt = _time_windows(run_window, state, steps, windows, reduce, meter=meter)
    meter.tick("productive_step")

    # Executed-flops recount from the compiled program — BEFORE the e2e
    # block below may delete the executable (see the mfu comment further
    # down for what the three conventions mean).
    from distributed_training_pytorch_tpu.utils.hlo_flops import executed_matmul_flops

    exec_step_flops = executed_matmul_flops(compiled if chain else probe)
    # Per-step device memory + roofline position (ISSUE 3 satellite): read
    # while the timed executable is alive and AFTER the timed windows, so an
    # allocator peak covers the real step's live set. Arithmetic intensity
    # uses XLA's own executed flops over its bytes-accessed estimate — the
    # pair the bf16/fp32 sweep moves together (docs/performance.md roofline).
    memory = _bench_memory(
        compiled if chain else probe,
        include_peak=include_peak,
        # derived exactly once per entry: the OOM-net ctx captured it right
        # after the AOT compile (same executable, same formula)
        predicted=ctx.get("predicted_peak_bytes") if ctx is not None else None,
    )
    arith_intensity = hlo_flops.arithmetic_intensity(compiled if chain else probe)
    # BENCH_MESH comm fields (ISSUE 11): per-category collective bytes of
    # the TIMED executable via the SAME inventory code path the static
    # audit's comm gate checks (analysis.comm_audit.collective_inventory) —
    # a measured sweep entry and the gate argue about identical numbers.
    # The chained executable is a rolled scan whose body (and so each
    # collective) appears once: a per-step figure by the cost_analysis
    # convention. Read here, while the executable is alive.
    comm_fields = {}
    if setup["mesh_spec"] is not None:
        from distributed_training_pytorch_tpu.analysis.comm_audit import (
            comm_fields as _comm_fields,
        )

        comm_fields = _comm_fields(compiled if chain else probe, setup["mesh"])

    # Host dispatch gap (ISSUE 2 satellite): per-step wall time when every
    # step is dispatched from Python — the regime a Trainer WITHOUT
    # chain_steps pays — minus the chained executable's per-step time
    # (device-resident window). The difference is pure host/dispatch
    # overhead: what Trainer(chain_steps=N) removes from train_epoch. The
    # dispatch loop syncs once per window (like the chained loop), not per
    # step, so the gap measures dispatch latency, not added host syncs.
    # BENCH_DISPATCH_GAP=0 skips the extra single-step compile.
    dispatch = {}
    if chain and os.environ.get("BENCH_DISPATCH_GAP", "1") != "0":
        step_probe = engine.compile_train_step(state, gbatch, compiler_options=opts)
        meter.tick("compile")

        def run_dispatch(st):
            for _ in range(steps):
                st, pm = step_probe(st, gbatch)
            return st, pm

        state, dt_dispatch = _time_windows(
            run_dispatch, state, steps, min(3, windows), reduce, meter=meter
        )
        meter.tick("productive_step")
        dispatch = {
            "step_ms_dispatch": round(dt_dispatch * 1e3, 2),
            "dispatch_gap_ms": round((dt_dispatch - dt) * 1e3, 2),
        }
        del step_probe

    # ViT remat-cliff guard (r4 VERDICT item 6): config 4's 50.8% MFU rests
    # on batch 192 sitting on the good side of XLA's backward-remat threshold
    # (r4 sweep: 932@192 vs 751@256, 753@224 — a +-20% compiler-heuristic
    # cliff a jax/libtpu upgrade is free to move). Probe: time the SAME
    # chained-executable shape as the main measurement (same steps, same
    # best-of reduction — an asymmetric window would bias the ratio by relay
    # dispatch/interference, masking a real shift) at a known-cliff batch;
    # if the default batch's per-image step time no longer beats it by the
    # expected margin, the heuristic moved — warn loudly and ship the probe
    # numbers in the JSON so a regression is a diff in BENCH_r{N}.json, not a
    # silent miss. BENCH_CLIFF_PROBE=0 skips (one extra ~35 s compile).
    # Gated to the calibrated default config: a BENCH_BATCH/BENCH_IMAGE_SIZE
    # override moves the sweep the 224-cliff point came from (and a 384px
    # batch-224 probe would also be a memory hazard).
    cliff_probe = {}
    if (
        model_name == "vit"
        and chain
        and os.environ.get("BENCH_CLIFF_PROBE", "1") != "0"
        and "BENCH_BATCH" not in os.environ
        and "BENCH_IMAGE_SIZE" not in os.environ
    ):
        cliff_batch = int(os.environ.get("BENCH_CLIFF_BATCH", "224"))
        probe_rng = np.random.RandomState(7)
        probe_host = cfg["make_batch"](
            probe_rng, cliff_batch, image_size, cfg["num_classes"], setup["model"]
        )
        probe_gbatch = engine.shard_batch(probe_host)
        probe_exec = engine.compile_chained_train_steps(
            state, probe_gbatch, steps, compiler_options=opts
        )
        meter.tick("compile")
        st, probe_dt = _time_windows(
            lambda s: probe_exec(s, probe_gbatch), state, steps, min(3, windows),
            reduce, meter=meter,
        )
        meter.tick("productive_step")
        del st, probe_exec, probe_gbatch
        per_img_main = dt / batch
        per_img_cliff = probe_dt / cliff_batch
        advantage = per_img_cliff / per_img_main  # healthy r4 sweep: ~1.24
        cliff_probe = {
            "cliff_batch": cliff_batch,
            "cliff_img_per_s": round(cliff_batch / probe_dt, 2),
            "cliff_advantage": round(advantage, 4),
        }
        if advantage < 1.05:
            print(
                f"bench: ViT remat-cliff guard FIRED — batch {batch} is only "
                f"{advantage:.3f}x faster per image than cliff batch "
                f"{cliff_batch} (healthy margin ~1.2x). XLA's backward-"
                "remat threshold likely moved under a compiler upgrade; "
                "re-sweep BENCH_BATCH (r4: optima at 96 and 192).",
                file=sys.stderr,
            )
            cliff_probe["cliff_guard_fired"] = True


    # Checkpoint save stall (ISSUE 5 satellite): the hot-loop stall one save
    # of THIS config's real TrainState costs, synchronous vs async. The sync
    # figure is the full serialize+hash+fsync+rename wall the pre-resilience
    # trainer paid in the step loop; the async figure is just the
    # device->host snapshot (resilience.AsyncCheckpointSaver), with the
    # commit's wall time reported separately (it runs on the background
    # thread in real training — the bench waits for it only to measure it).
    # BENCH JSONs track the stall reduction across rounds. BENCH_SAVE_STALL=0
    # skips (writes ~2x the model+optimizer state to local disk).
    save_stall = {}
    if os.environ.get("BENCH_SAVE_STALL", "1") != "0":
        import shutil
        import tempfile

        from distributed_training_pytorch_tpu.checkpoint import CheckpointManager
        from distributed_training_pytorch_tpu.resilience import measure_save_stall

        ckpt_tmp = tempfile.mkdtemp(prefix="bench_save_stall_")
        try:
            with CheckpointManager(ckpt_tmp, async_save=False) as mgr:
                # One shared implementation with the chaos soak's < 25%
                # stall acceptance check (resilience.measure_save_stall);
                # the meter gets the trainer-identical checkpoint /
                # checkpoint_async attribution.
                stall = measure_save_stall(mgr, state, meter=meter)
            save_stall = {
                "save_stall_ms": round(stall["stall_ms"], 3),
                "save_sync_ms": round(stall["sync_ms"], 2),
                "save_commit_ms": round(stall["commit_ms"], 2),
                "save_stall_ratio": round(stall["stall_ratio"], 4),
            }
        finally:
            shutil.rmtree(ckpt_tmp, ignore_errors=True)

    # Device-time attribution + dispatch-gap audit (ISSUE 6 satellite):
    # BENCH_PROFILE=1 traces ONE extra window of the exact timed executable
    # and reports where its device wall went — `device_busy_frac` /
    # `dispatch_gap_frac` (the mfu vs mfu_exec gap's prime suspect) and the
    # per-category attribution dict (profiling.analyze_trace; fractions sum
    # to 1 with `idle`) — next to the MFU family. Env-gated (default off,
    # like the heavier BENCH_* extras) so default runs stay cheap; runs
    # BEFORE the e2e block below frees the executable.
    profile_fields = {}
    if os.environ.get("BENCH_PROFILE", "0") == "1":
        import tempfile

        from distributed_training_pytorch_tpu import profiling as profiling_lib

        prof_dir = os.environ.get("BENCH_PROFILE_DIR") or tempfile.mkdtemp(
            prefix=f"bench_prof_{model_name}_"
        )
        # The whole traced window sits inside the net: a profiler that fails
        # to start/stop (unwritable BENCH_PROFILE_DIR, a foreign profiler
        # session already active → RuntimeError) must cost only this block —
        # every already-measured field of the entry still gets emitted.
        try:
            with profiling_lib.trace(prof_dir):
                state, pm = run_window(state)
                _ = float(pm["loss"])
                # Tick INSIDE the trace block: only the real steps' wall is
                # productive — stop_trace's on-disk serialization (can rival
                # the window itself for a multi-MB dump) and the analysis
                # below book to "other" at the next tick.
                meter.tick("productive_step")
            profile_report = profiling_lib.analyze_trace(
                prof_dir,
                steps=steps,
                top_k=5,
                flops_by_op=profiling_lib.flops_index(compiled if chain else probe),
            )
            profile_fields = {
                "device_busy_frac": round(profile_report.device_busy_frac, 4),
                "dispatch_gap_frac": round(profile_report.dispatch_gap_frac, 4),
                "categories": {
                    k: round(v, 4) for k, v in profile_report.categories.items() if v
                },
                "profile_trace": prof_dir,
            }
        except (ValueError, FileNotFoundError, OSError, RuntimeError) as e:
            print(f"bench: BENCH_PROFILE failed ({e})", file=sys.stderr)
        finally:
            meter.tick("other")  # stop_trace serialization + analysis (or the failure path)

    # BENCH_E2E=1: also run the input-pipeline-fed epoch loop and report it
    # next to the device-step number (VERDICT r2 item 2; r3 item 5 extends
    # it beyond vgg16 to the records path of configs 3-5).
    # BENCH_TRAINER_LOOP=1 (vgg16): the trainer-loop chained mode — the SAME
    # Trainer.train_epoch path with chain_steps=BENCH_CHAIN_STEPS, measuring
    # whether real training closes the dispatch gap the chained microbench
    # predicts (acceptance: trainer_vs_step within ~5% of 1.0).
    e2e = {}
    trainer_loop = {}
    want_e2e = os.environ.get("BENCH_E2E") == "1"
    want_trainer_loop = (
        os.environ.get("BENCH_TRAINER_LOOP") == "1" and model_name == "vgg16"
    )
    if want_e2e or want_trainer_loop:
        # Free the microbench's device state first: its TrainState + batch +
        # executable would otherwise coexist with the e2e trainer's own
        # (ConvNeXt-L: 2 x ~2.4 GB optimizer states + batch-512 workspaces
        # = ResourceExhausted on one 16 GB chip). dt survives for the ratio.
        del state, gbatch, run_window
        if chain:
            del compiled
        else:
            del probe
        setup.pop("state"), setup.pop("gbatch"), setup.pop("engine")
        import gc

        gc.collect()
    e2e_epochs = int(os.environ.get("BENCH_E2E_EPOCHS", "3"))
    if want_e2e:
        if model_name == "vgg16":
            e2e = run_e2e(batch, epochs=e2e_epochs)
        elif model_name in ("resnet50", "convnext_l", "vit"):
            e2e = run_e2e_records(
                {"vit": "vit_b16"}.get(model_name, model_name),
                batch, e2e_epochs, image_size,
                num_classes=cfg["num_classes"],
                accum_steps=setup["accum_steps"],
            )
        if e2e:
            e2e = {k: round(v, 2) if isinstance(v, float) else v for k, v in e2e.items()}
            e2e["e2e_vs_step"] = round(
                e2e["e2e_images_per_sec"] / (batch * cfg["items_per_row"](image_size) / dt), 4
            )
    if want_trainer_loop:
        # Default 10: must divide the Trainer's log_every default (50) —
        # chained syncs land on window boundaries (ctor-validated).
        chain_steps = int(os.environ.get("BENCH_CHAIN_STEPS", "10"))
        tl = run_e2e(batch, epochs=e2e_epochs, chain_steps=chain_steps)
        trainer_step_ms = batch / tl["e2e_images_per_sec"] * 1e3
        trainer_loop = {
            "trainer_chain_steps": chain_steps,
            "trainer_step_ms": round(trainer_step_ms, 2),
            "trainer_vs_step": round(trainer_step_ms / (dt * 1e3), 4),
        }

    # Close the goodput partition (the e2e epochs above, when enabled, run
    # the full Trainer loop — a separate measurement, booked as harness
    # `other` here). Fractions must sum to 1: same invariant the
    # scripts/telemetry_smoke.py gate enforces for trainer runs.
    meter.stop("other")
    fractions = meter.fractions()
    assert abs(sum(fractions.values()) - 1.0) < 1e-6, fractions
    goodput_fields = {
        "goodput": {k: round(v, 4) for k, v in fractions.items() if v},
        "goodput_wall_s": round(meter.total(), 2),
    }

    n_chips = len(jax.devices())
    items = batch * cfg["items_per_row"](image_size)
    images_per_sec = items / dt
    peak = peak_flops(jax.devices()[0]) * n_chips
    # BENCH_MESH entry fields: the mesh's identity, the measured per-chip
    # param residency (the ZeRO-3 HBM win — shard bytes, not global), and
    # per-replica throughput (telemetry.mfu.throughput_fields: dividing a
    # TP mesh's throughput by raw chip count would misread cooperation as
    # slowdown). predicted_peak_bytes already lands via _bench_memory.
    mesh_fields = {}
    if setup["mesh_spec"] is not None:
        from distributed_training_pytorch_tpu.parallel.sharding import (
            tree_shard_bytes,
        )

        mesh_fields = {
            "mesh": setup["mesh_spec"],
            "mesh_axes": {str(k): int(v) for k, v in setup["mesh"].shape.items()},
            "per_chip_param_bytes": int(tree_shard_bytes(state.params)),
            **comm_fields,  # per-category collective bytes (ISSUE 11)
            **{
                k: round(v, 2) if isinstance(v, float) else v
                for k, v in mfu_lib.throughput_fields(
                    images_per_sec, setup["mesh"]
                ).items()
            },
        }
    # Three FLOP conventions, all reported (r3 VERDICT item 4 itemization):
    #   mfu      — nominal layer-formula count: the work an eager executor
    #              (the torch reference) performs for this model. Headline,
    #              comparable across rounds and to reference-style execution.
    #   mfu_exec — executed MXU flops summed over the optimized HLO's
    #              conv/dot instructions (utils.hlo_flops): what the compiler
    #              kept after folding (VGG16/32px: the replicated-pool
    #              classifier folds 25088->512-wide, executed = 0.70x
    #              nominal). None (omitted) where the HLO convention doesn't
    #              reconcile — see executed_matmul_flops's guard.
    #   mfu_xla  — cost_analysis(): executed matmuls + VPU elementwise.
    # (exec_step_flops computed above, before the e2e block frees the
    # executable.)
    # Grad-accumulation scan: XLA's cost_analysis (and the HLO walk) may
    # count the microbatch scan BODY once, undercounting by ~accum (observed
    # exactly 4x at accum 4 / batch 512; at batch 128 XLA unrolled the scan
    # and counted fully). Pick whichever hypothesis — counted-once vs
    # counted-fully — lands the ratio nearer 1x of the analytic anchor in
    # log space; a plain threshold misfires at accum 2 where a fully-counted
    # ~0.85x ratio sits inside any fixed band.
    accum = setup["accum_steps"]
    if accum > 1:
        import math

        def _rescale(flops):
            if not flops:
                return flops
            ratio = flops / step_flops
            if abs(math.log(ratio * accum)) < abs(math.log(ratio)):
                print(
                    f"bench: accum rescale fired — counted {flops:.3e} is "
                    f"{ratio:.2f}x analytic; multiplying by accum={accum} "
                    "(XLA counted the microbatch scan body once)",
                    file=sys.stderr,
                )
                return flops * accum
            return flops

        xla_step_flops = _rescale(xla_step_flops)
        exec_step_flops = _rescale(exec_step_flops)
    # MFU assembly via telemetry/mfu.py — the same flops/dt/peak ratio the
    # Trainer's per-window telemetry reports (one implementation, ISSUE 4).
    mfu = mfu_lib.mfu_value(step_flops, dt, peak) or 0.0
    mfu_exec = mfu_lib.mfu_value(exec_step_flops or 0.0, dt, peak)
    mfu_xla = mfu_lib.mfu_value(xla_step_flops, dt, peak) or 0.0

    # Provenance stamp (ISSUE 14): git SHA + jax/jaxlib + effective
    # XLA_FLAGS + the program identity — without it, a BENCH_r line is not
    # attributable and run_compare/bench_history cannot tell two configs
    # apart (four flat rounds went undiagnosed partly for this reason).
    provenance = provenance_fields(
        mesh=setup["mesh_spec"],
        dtype=setup["dtype_name"] or "bf16",
        chain_steps=steps if chain else 1,
        batch=batch,
    )

    print(
        json.dumps(
            {
                "metric": _metric_name(cfg, image_size, setup["dtype_name"]),
                "value": round(images_per_sec / n_chips, 2),
                "unit": cfg["unit"],
                "vs_baseline": round(mfu / 0.60, 4),
                "mfu": round(mfu, 4),
                **({"mfu_exec": round(mfu_exec, 4)} if mfu_exec is not None else {}),
                "mfu_xla": round(mfu_xla, 4),
                # LM convention note (r4 VERDICT item 3, measured in
                # BASELINE.md "LM FLOP-counter reconciliation"): cost_analysis
                # assigns the Pallas flash custom-call 0 FLOPs (13% of the
                # analytic count) and counts the fused tied-CE vocab-chunk
                # scan body once (21%), so mfu_xla structurally reads ~0.66x
                # mfu on this config — an accounting convention, not perf.
                # The tied-CE vocab-scan undercount applies to every LM run;
                # the flash custom-call exclusion only once the auto-route
                # picks the kernel (T >= 512 — below that attention runs
                # plain and cost_analysis DOES count its matmuls).
                **(
                    {
                        "mfu_xla_note": (
                            "excludes flash custom-call + tied-CE scan trips; see BASELINE.md"
                            if image_size >= 512
                            else "counts tied-CE vocab scan body once; see BASELINE.md"
                        )
                    }
                    if model_name == "lm"
                    else {}
                ),
                "batch": batch,
                "step_ms": round(dt * 1e3, 2),
                # Compute dtype of the benched step: explicit BENCH_DTYPE, or
                # the historical model-internal-bf16 program when unset.
                "dtype": setup["dtype_name"] or "bf16",
                **mesh_fields,
                **memory,
                **(
                    {"arith_intensity": round(arith_intensity, 2)}
                    if arith_intensity
                    else {}
                ),
                **dispatch,
                **cliff_probe,
                **save_stall,
                **profile_fields,
                **goodput_fields,
                **e2e,
                **trainer_loop,
                "provenance": provenance,
            }
        )
    )


def _bench_serving():
    """BENCH_SERVE=1 (ISSUE 18 satellite 5): the serving-path headline —
    ``serve_p50_ms`` / ``serve_p99_ms`` / ``serve_qps_per_chip``, one JSON
    line each, provenance-stamped like every training headline. Measures the
    FULL request path (HTTP + admission + micro-batching + compiled forward)
    of an LMTiny replica on a ``tp2`` mesh under saturating closed-loop
    clients, so a regression in any serving layer moves the number.

    Knobs: ``BENCH_SERVE_S`` (measure wall, default 5s), ``BENCH_SERVE_CLIENTS``
    (concurrent closed-loop clients, default 8).
    """
    import json as _json
    import threading
    import urllib.request

    from distributed_training_pytorch_tpu.models import LMTiny
    from distributed_training_pytorch_tpu.serving import (
        InferEngine,
        InferenceServer,
        MicroBatcher,
    )

    seq_len, vocab = 16, 64
    duration_s = float(os.environ.get("BENCH_SERVE_S", "5"))
    n_clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "8"))
    # TP-sharded when the host has 2+ chips; single-chip hosts serve dp1.
    mesh_spec = "tp2" if len(jax.devices()) >= 2 else "dp1"
    devices = jax.devices()[: 2 if mesh_spec == "tp2" else 1]
    mesh = mesh_lib.mesh_config_from_spec(mesh_spec).build(devices)
    model = LMTiny(vocab_size=vocab)
    params = model.init(jax.random.key(0), jnp.zeros((1, seq_len), jnp.int32))[
        "params"
    ]
    engine = InferEngine(
        lambda p, tokens: model.apply({"params": p}, tokens), mesh,
        buckets=(1, 2, 4, 8),
    )
    engine.swap_params(params, version="bench")
    engine.warmup(np.zeros((seq_len,), np.int32))

    server = InferenceServer(
        engine,
        batcher=MicroBatcher(buckets=engine.buckets, max_delay_s=0.004),
        window_s=duration_s + 60.0,
        input_dtype="int32",
        process_index=0,
    ).start()
    stop = threading.Event()
    counts = [0] * n_clients
    try:
        def client(i: int) -> None:
            rng = np.random.default_rng(i)
            url = f"http://127.0.0.1:{server.port}/predict"
            while not stop.is_set():
                row = rng.integers(0, vocab, size=(seq_len,)).tolist()
                body = _json.dumps({"tenant": f"c{i}", "inputs": [row]}).encode()
                req = urllib.request.Request(
                    url, data=body, headers={"Content-Type": "application/json"}
                )
                with urllib.request.urlopen(req, timeout=30.0) as resp:
                    resp.read()
                counts[i] += 1

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(n_clients)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        elapsed = time.monotonic() - t0
        win = server.window.snapshot()
        qps_per_chip = sum(counts) / elapsed / len(devices)
    finally:
        server.close()

    provenance = provenance_fields(
        mesh=mesh_spec, dtype="float32", chain_steps=1, batch=max(engine.buckets)
    )
    common = {
        "model": "lm_tiny",
        "clients": n_clients,
        "requests": sum(counts),
        "buckets": list(engine.buckets),
        "provenance": provenance,
    }
    for metric, value, unit in (
        ("serve_p50_ms", round(win["p50_ms"], 2), "ms"),
        ("serve_p99_ms", round(win["p99_ms"], 2), "ms"),
        ("serve_qps_per_chip", round(qps_per_chip, 2), "req/s/chip"),
    ):
        print(json.dumps({"metric": metric, "value": value, "unit": unit, **common}))


def _bench_data():
    """BENCH_DATA=1 (ISSUE 19 satellite 5): the streaming input-path
    headline — ``decode_ms_p50`` / ``records_per_s_per_host`` from a
    loader-only pass over synthetic DTPR1 record shards, plus
    ``data_wait_frac`` from the SAME streaming trainer workload the perf
    gate's ``data-wait-cpu`` ceiling measures (``run_doctor``'s self-test
    harness with ``streaming=True``), one JSON line each,
    provenance-stamped like every training headline.

    Knobs: ``BENCH_DATA_RECORDS`` (corpus size, default 4096),
    ``BENCH_DATA_WORKERS`` (decode pool size, default 4).
    """
    import shutil
    import tempfile

    from distributed_training_pytorch_tpu.data import StreamingLoader
    from distributed_training_pytorch_tpu.data.records import write_shards
    from distributed_training_pytorch_tpu.telemetry import Telemetry
    from distributed_training_pytorch_tpu.telemetry import doctor as doctor_lib

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "scripts"))
    import run_doctor

    n_records = int(os.environ.get("BENCH_DATA_RECORDS", "4096"))
    num_workers = int(os.environ.get("BENCH_DATA_WORKERS", "4"))
    batch = 128
    rng = np.random.default_rng(0)
    images = rng.random((n_records, 8, 8, 1), dtype=np.float32)

    # -- loader-only pass: decode + pool throughput, no training loop ------
    tmp = tempfile.mkdtemp(prefix="bench_data_")
    try:
        write_shards(
            os.path.join(tmp, "bench"),
            ((np.ascontiguousarray(images[i]).tobytes(), int(i % 10))
             for i in range(n_records)),
            num_shards=8,
        )
        loader = StreamingLoader.from_records(
            tmp, batch,
            decode=lambda p: np.frombuffer(p, np.float32).reshape(8, 8, 1),
            shuffle=True, seed=0, num_workers=num_workers,
        )
        t0 = time.monotonic()
        consumed = 0
        for b in loader:
            consumed += len(b["label"])
        elapsed = max(time.monotonic() - t0, 1e-9)
        stats = loader.decode_stats()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # -- trainer pass: steady-state data_wait on the gated workload --------
    tmp = tempfile.mkdtemp(prefix="bench_data_trainer_")
    try:
        trainer = run_doctor._self_test_trainer(
            tmp, streaming=True,
            telemetry=Telemetry(anomaly=None, mfu=False), save_period=None,
        )
        trainer.train()
        steady = doctor_lib.steady_fractions(trainer.goodput.to_state())
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    provenance = provenance_fields(
        mesh=None, dtype="float32", chain_steps=2, batch=batch
    )
    common = {
        "workload": "digits-conv-streaming-b128-chain2",
        "records": n_records,
        "num_workers": num_workers,
        "provenance": provenance,
    }
    for metric, value, unit in (
        ("decode_ms_p50", round(stats["decode_ms_p50"], 3), "ms"),
        ("records_per_s_per_host", round(consumed / elapsed, 1), "rec/s/host"),
        ("data_wait_frac", round(steady["data_wait"], 4), "frac"),
    ):
        print(json.dumps({"metric": metric, "value": value, "unit": unit, **common}))


def main():
    # BENCH_SERVE=1: the serving-path headline instead of the training-step
    # measurement — a separate program (forward-only, latency-bound), so the
    # two benches never contaminate each other's allocator high-water marks.
    if os.environ.get("BENCH_SERVE", "") not in ("", "0"):
        _bench_serving()
        return
    # BENCH_DATA=1: the streaming input-path headline — loader-only decode
    # throughput plus the gated data-wait fraction; same opt-in shape.
    if os.environ.get("BENCH_DATA", "") not in ("", "0"):
        _bench_data()
        return
    # TUNED=1 (ISSUE 17): adopt the committed TUNED.json winner's knobs as
    # DEFAULTS — chain_steps maps to BENCH_STEPS, pallas to BENCH_PALLAS,
    # and xla_flags installs into XLA_FLAGS when unset (tuned_defaults does
    # that, and this runs before the first backend touch). Explicit BENCH_*
    # env always wins; TUNED unset changes nothing anywhere.
    from distributed_training_pytorch_tpu.train import autotune as autotune_lib

    tuned = autotune_lib.tuned_defaults()
    if tuned.get("chain_steps") and "BENCH_STEPS" not in os.environ:
        os.environ["BENCH_STEPS"] = str(tuned["chain_steps"])
    if tuned.get("pallas") is not None and "BENCH_PALLAS" not in os.environ:
        os.environ["BENCH_PALLAS"] = "1" if tuned["pallas"] else "0"
    # BENCH_DTYPE sweep: a comma list runs the whole measurement once per
    # dtype (one json line each — BENCH_r06-style sweeps diff the lines);
    # a single value (or unset) keeps the one-line contract. Every entry is
    # validated BEFORE the first run — a typo in the last entry must fail in
    # milliseconds, not after the earlier entries' multi-minute measurements.
    sweep = [d.strip() for d in os.environ.get("BENCH_DTYPE", "").split(",") if d.strip()]
    for dtype_name in sweep:
        _bench_dtype(dtype_name)
    # BENCH_MESH sweep (ISSUE 10): one json line per mesh layout; composes
    # with the dtype sweep as an outer product (meshes outermost, so a
    # MULTICHIP_r mesh sweep groups each mesh's dtype lines together).
    # Validated up front like the dtype list — a typo'd last mesh must fail
    # in milliseconds, not after the earlier meshes' measurements.
    mesh_sweep = [
        m.strip() for m in os.environ.get("BENCH_MESH", "").split(",") if m.strip()
    ]
    for spec in mesh_sweep:
        _bench_mesh(spec)
    entries = [
        (mesh_spec, dtype_name)
        for mesh_spec in (mesh_sweep or [None])
        for dtype_name in (sweep or [None])
    ]
    failed = False
    for i, (mesh_spec, dtype_name) in enumerate(entries):
        # peak_bytes only on the first run of the process: the allocator's
        # peak is a lifetime high-water mark (see _bench_memory).
        #
        # OOM net (ISSUE 8 satellite): one oversized dtype/model entry must
        # not abort every entry after it — a RESOURCE_EXHAUSTED entry emits
        # a structured {"oom": true} line (with the peak the memory
        # preflight predicted for it, captured before the first dispatch)
        # and the sweep moves on. Any other failure still aborts: a crash
        # that is not an OOM is a bug, not a fit boundary.
        ctx = {}
        try:
            _run_bench(dtype_name, include_peak=(i == 0), ctx=ctx, mesh_spec=mesh_spec)
        except Exception as e:  # noqa: BLE001 — classified below, re-raised if not OOM
            if not memory_lib.is_oom_error(e):
                raise
            failed = True
            print(
                json.dumps(
                    {
                        "metric": ctx.get(
                            "metric", os.environ.get("BENCH_MODEL", "vgg16")
                        ),
                        "dtype": dtype_name or "bf16",
                        **({"mesh": mesh_spec} if mesh_spec else {}),
                        "oom": True,
                        **(
                            {"batch": ctx["batch"]} if "batch" in ctx else {}
                        ),
                        **(
                            {"predicted_peak_bytes": ctx["predicted_peak_bytes"]}
                            if "predicted_peak_bytes" in ctx
                            else {}
                        ),
                        "error": (str(e).splitlines() or [type(e).__name__])[0][:300],
                        "provenance": provenance_fields(
                            mesh=mesh_spec,
                            dtype=dtype_name or "bf16",
                            batch=ctx.get("batch"),
                        ),
                    }
                )
            )
            print(
                f"bench: {dtype_name or 'bf16'} entry OOMed — structured line "
                "emitted, continuing the sweep",
                file=sys.stderr,
            )
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
