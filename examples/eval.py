"""Standalone offline evaluation — capability twin of the reference ``eval.py``.

Loads a saved checkpoint into a fresh VGG16, sweeps every image under
``<data>/test/<label>/``, and reports top-1 / top-k accuracy — the reference's
flow (``eval.py:40-72``: cv2 load + resize + ImageNet normalize, batch-1
forward, sklearn ``top_k_accuracy_score`` k=1 and k=2).

TPU-first differences: evaluation is batched (the reference forwards one image
at a time, ``eval.py:60-61``), runs under jit, and top-k is computed with a
correctly-named k (the reference prints k=2 results under a variable called
``acc_top5``, ``eval.py:70-72`` — SURVEY.md §2e).

Usage::

    python examples/eval.py [checkpoint_dir] [test_data_dir]

Defaults: ``./runs/weights/last`` and ``./data/test``.
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from distributed_training_pytorch_tpu.checkpoint import CheckpointManager
from distributed_training_pytorch_tpu.data import (
    ImageFolderDataSource,
    ShardedLoader,
    eval_transform,
)
from distributed_training_pytorch_tpu.models import VGG16
from distributed_training_pytorch_tpu.ops import top_k_accuracy
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

LABELS = ["cat", "dog", "snake"]
HEIGHT = WIDTH = 224
BATCH = 64


def evaluate(
    checkpoint_dir: str,
    test_path: str,
    labels=None,
    batch=BATCH,
    *,
    model=None,
    height=None,
    width=None,
    mesh=None,
) -> dict:
    labels = labels or LABELS
    height = height or HEIGHT
    width = width or WIDTH
    import optax

    mesh = mesh or mesh_lib.create_mesh()
    model = model or VGG16(num_classes=len(labels))

    def criterion(logits, b):
        mask = b.get("mask")
        return jnp.zeros(()), {
            "top1": top_k_accuracy(logits, b["label"], k=1, weights=mask),
            "top2": top_k_accuracy(logits, b["label"], k=2, weights=mask),
        }

    engine = TrainEngine(make_supervised_loss(model, criterion), optax.sgd(0.0), mesh)
    state = engine.init_state(
        jax.random.key(0), lambda rng: model.init(rng, jnp.zeros((1, height, width, 3)))
    )
    # Restore params from the named checkpoint (``eval.py:47-50`` analog).
    import os

    mgr = CheckpointManager(os.path.dirname(checkpoint_dir.rstrip("/")), async_save=False)
    state, _ = mgr.restore(checkpoint_dir, state, params_only=True)
    mgr.close()

    source = ImageFolderDataSource(test_path, labels, transform=eval_transform(height, width))
    loader = ShardedLoader(
        source, batch, shuffle=False, drop_last=False, pad_final=True, num_workers=8
    )
    sums: dict[str, float] = {}
    total = 0.0
    for b, host_batch in enumerate(loader):
        # Global real-row count: host-independent aggregation weight.
        weight = float(loader.global_real_count(b))
        metrics = engine.eval_step(state, engine.shard_batch(host_batch))
        for k, v in metrics.items():
            sums[k] = sums.get(k, 0.0) + float(v) * weight
        total += weight
    return {k: v / max(total, 1.0) for k, v in sums.items()}


if __name__ == "__main__":
    import os

    checkpoint_dir = sys.argv[1] if len(sys.argv) > 1 else "./runs/weights/last"
    test_path = sys.argv[2] if len(sys.argv) > 2 else "./data/test"
    # EVAL_MODEL picks any zoo member (vgg16|resnet50|vit_b16|convnext_l...);
    # default stays the reference's VGG16. EVAL_LABELS is a comma list.
    labels = [s.strip() for s in os.environ.get("EVAL_LABELS", "").split(",") if s.strip()] or None
    model = None
    if os.environ.get("EVAL_MODEL"):
        from distributed_training_pytorch_tpu.models import create_model

        model = create_model(
            os.environ["EVAL_MODEL"], num_classes=len(labels or LABELS)
        )
        # Whether params nest under InputNormalizer's 'inner' scope (the
        # SHIP_UINT8 trainer default) is read from the CHECKPOINT's own meta
        # (manager.save records params_top_level — ADVICE r4: the restore
        # target must match what was trained, not a mutable env var).
        # Checkpoints predating the meta key fall back to the SHIP_UINT8
        # knob + the trainer's model allowlist.
        wrapped = None
        mgr = CheckpointManager(
            os.path.dirname(checkpoint_dir.rstrip("/")), async_save=False
        )
        try:
            # KeyError: checkpoints without a 'meta' item (orbax raises it,
            # not FileNotFoundError) fall back to the env heuristic too.
            top = mgr.read_meta(checkpoint_dir).get("params_top_level")
            if top is not None:
                wrapped = top == ["inner"]
        except (FileNotFoundError, ValueError, KeyError):
            pass
        finally:
            mgr.close()
        if wrapped is None:
            imagenet_family = os.environ["EVAL_MODEL"] in (
                "resnet50", "vit_b16", "convnext_l", "convnext_tiny",
                "resnet18_slim", "vit_tiny",
            )
            wrapped = imagenet_family and os.environ.get("SHIP_UINT8", "1") != "0"
        if wrapped:
            from distributed_training_pytorch_tpu.data import transforms as _T
            from distributed_training_pytorch_tpu.models.wrappers import InputNormalizer

            model = InputNormalizer(
                inner=model, mean=list(_T.IMAGENET_MEAN), std=list(_T.IMAGENET_STD)
            )
    # EVAL_SIZE overrides the 224x224 default (e.g. 32 for the records-path
    # digits proof's ResNet18Slim checkpoints).
    size = int(os.environ.get("EVAL_SIZE", "0")) or None
    results = evaluate(
        checkpoint_dir, test_path, labels=labels, model=model, height=size, width=size
    )
    print(f"ACCURACY TOP-1: {results['top1']:.4f}")
    print(f"ACCURACY TOP-2: {results['top2']:.4f}")
