"""Materialize a real byte-level LM corpus from in-env text.

The environment is offline (BASELINE.md), so the LM train-to-accuracy proof
(r3 VERDICT item 2) uses genuine text that ships with the image: the Python
standard library's source files plus installed-package documentation — real,
human-written prose and code, ~tens of MB. Deterministic: files are collected
in sorted order, so every run (and every host) builds the identical corpus.

Usage:  python examples/make_lm_corpus.py [out_path] [max_mb]
        (defaults: ./runs/lm_corpus.txt, 24 MB)
The output feeds ``LM_CORPUS=<out_path> MODEL=lm ./run.sh``.
"""

from __future__ import annotations

import os
import sys

# Real text roots, preference order: stdlib source (prose-rich docstrings),
# then package docs/READMEs. Sorted traversal => deterministic corpus.
ROOTS = [
    ("/usr/lib/python3.11", (".py",)),
    ("/opt/venv/lib/python3.12/site-packages/numpy", (".py", ".rst", ".txt")),
    ("/opt/venv/lib/python3.12/site-packages/jax", (".py",)),
]


def collect(max_bytes: int) -> bytes:
    chunks: list[bytes] = []
    total = 0
    for root, exts in ROOTS:
        if total >= max_bytes or not os.path.isdir(root):
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames.sort()
            if "__pycache__" in dirpath or "/test" in dirpath:
                continue
            for name in sorted(filenames):
                if not name.endswith(tuple(exts)):
                    continue
                path = os.path.join(dirpath, name)
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError:
                    continue
                # Text files only: skip anything that does not decode.
                try:
                    data.decode("utf-8")
                except UnicodeDecodeError:
                    continue
                chunks.append(data)
                chunks.append(b"\n\n")
                total += len(data) + 2
                if total >= max_bytes:
                    break
            if total >= max_bytes:
                break
    return b"".join(chunks)[:max_bytes]


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else "./runs/lm_corpus.txt"
    max_mb = float(sys.argv[2]) if len(sys.argv) > 2 else 24.0
    data = collect(int(max_mb * 1e6))
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {len(data):,} bytes of real in-env text to {out}")


if __name__ == "__main__":
    main()
