"""Materialize a real byte-level LM corpus from in-env text.

The environment is offline (BASELINE.md), so the LM train-to-accuracy proof
(r3 VERDICT item 2) uses genuine text that ships with the image: the Python
standard library's source files plus installed-package documentation — real,
human-written prose and code, ~tens of MB. Deterministic: files are collected
in sorted order, so every run (and every host) builds the identical corpus.

Usage:  python examples/make_lm_corpus.py [out_path] [max_mb]
        (defaults: ./runs/lm_corpus.txt, 24 MB)
The output feeds ``LM_CORPUS=<out_path> MODEL=lm ./run.sh``.
"""

from __future__ import annotations

import os
import sys

def _roots() -> list[tuple[str, tuple[str, ...]]]:
    """Real text roots, preference order: stdlib source (prose-rich
    docstrings), then installed-package docs. Derived from the running
    interpreter (sysconfig / site), not hardcoded image paths — portable
    across hosts. Sorted traversal => deterministic corpus."""
    import site
    import sysconfig

    roots: list[tuple[str, tuple[str, ...]]] = []
    stdlib = sysconfig.get_paths().get("stdlib")
    if stdlib:
        roots.append((stdlib, (".py",)))
    site_dirs: list[str] = []
    try:
        site_dirs = site.getsitepackages()
    except AttributeError:  # some embedded interpreters
        pass
    for d in site_dirs:
        for pkg, exts in (("numpy", (".py", ".rst", ".txt")), ("jax", (".py",))):
            p = os.path.join(d, pkg)
            if os.path.isdir(p):
                roots.append((p, exts))
    return roots


def collect(max_bytes: int) -> bytes:
    chunks: list[bytes] = []
    total = 0
    for root, exts in _roots():
        if total >= max_bytes or not os.path.isdir(root):
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            # prune skipped subtrees in place so os.walk never descends
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__" and not d.startswith("test")
            )
            for name in sorted(filenames):
                if not name.endswith(tuple(exts)):
                    continue
                path = os.path.join(dirpath, name)
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError:
                    continue
                # Text files only: skip anything that does not decode.
                try:
                    data.decode("utf-8")
                except UnicodeDecodeError:
                    continue
                chunks.append(data)
                chunks.append(b"\n\n")
                total += len(data) + 2
                if total >= max_bytes:
                    break
            if total >= max_bytes:
                break
    return b"".join(chunks)[:max_bytes]


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else "./runs/lm_corpus.txt"
    max_mb = float(sys.argv[2]) if len(sys.argv) > 2 else 24.0
    data = collect(int(max_mb * 1e6))
    # A near-empty corpus "succeeds" here but fails obscurely in train_lm
    # (0 windows) — fail loudly at the source instead.
    minimum = min(int(max_mb * 1e6) // 4, 1_000_000)
    if len(data) < minimum:
        raise SystemExit(
            f"collected only {len(data):,} bytes (< {minimum:,}) — no usable "
            "text roots found on this host (checked stdlib + site-packages)"
        )
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {len(data):,} bytes of real in-env text to {out}")


if __name__ == "__main__":
    main()
