"""Offline LM evaluation + sampling — the ``eval.py`` analog for the causal-LM
family (beyond the reference's vision-only scope).

Loads a ``train_lm.py`` checkpoint, reports byte-level validation NLL /
perplexity over a corpus, and prints greedy + sampled continuations of a
prompt through the KV-cache decode path (``models.transformer_lm.generate``).

Usage::

    python examples/eval_lm.py [checkpoint_dir] [corpus_file]

Env knobs: ``SEQ_LEN`` (must match training, default 256), ``LM_SIZE``
(``tiny`` | ``small``), ``EVAL_BATCH`` (default 64), ``PROMPT`` (text to
continue; default a corpus prefix), ``GEN_STEPS`` (default 64),
``TEMPERATURE`` (default 0.8; 0 = greedy only).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_pytorch_tpu.checkpoint import CheckpointManager
from distributed_training_pytorch_tpu.models import GPTSmall, LMTiny
from distributed_training_pytorch_tpu.models.transformer_lm import generate
from distributed_training_pytorch_tpu.train import TrainState


def build_model(size: str, seq_len: int, moe_every: int = 0):
    factory = {"tiny": LMTiny, "small": GPTSmall}[size]
    return factory(
        vocab_size=256, dtype=jnp.bfloat16, max_len=max(seq_len, 128), moe_every=moe_every
    )


def load_params(checkpoint_dir: str, size: str, seq_len: int, moe_every: int = 0):
    """(model, params) from a train_lm checkpoint — shared by evaluate/sample.
    ``moe_every`` must match the training run (the param tree differs)."""
    model = build_model(size, seq_len, moe_every)
    abstract = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, seq_len), jnp.int32)), jax.random.key(0)
    )
    target = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), abstract["params"]),
        opt_state=(),
        model_state={},
        rng=jax.random.key(0),
    )
    mgr = CheckpointManager(os.path.dirname(checkpoint_dir) or ".", async_save=False)
    state, _ = mgr.restore(checkpoint_dir, target, params_only=True)
    mgr.close()
    return model, state.params


def evaluate(checkpoint_dir: str, corpus: str, *, size="small", seq_len=256, batch=64,
             moe_every=0, loaded=None):
    """Returns {"nll": mean byte NLL, "ppl": perplexity, "n_windows": N}."""
    from examples.train_lm import load_windows

    windows = load_windows(seq_len, path=corpus)
    model, params = loaded or load_params(checkpoint_dir, size, seq_len, moe_every)

    @jax.jit
    def batch_nll(params, toks):
        logits = model.apply({"params": params}, toks[:, :-1])
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, toks[:, 1:, None], axis=-1)[..., 0]
        return jnp.sum(nll), nll.size

    total, count, n_windows = 0.0, 0, 0
    # Full batches, then the tail (each batch size compiles once; the tail
    # adds at most one extra compile). Dropping the tail silently — or an
    # empty corpus scoring nll=0 — would fabricate results.
    for i in range(0, len(windows), batch):
        chunk = windows[i : i + batch]
        s, n = batch_nll(params, jnp.asarray(chunk))
        total += float(s)
        count += int(n)
        n_windows += len(chunk)
    if count == 0:
        raise ValueError(f"no evaluation windows (corpus too short for SEQ_LEN={seq_len})")
    nll = total / count
    return {"nll": nll, "ppl": float(np.exp(nll)), "n_windows": n_windows}


def sample(checkpoint_dir: str, prompt_text: bytes, *, size="small", seq_len=256,
           gen_steps=64, temperature=0.8, moe_every=0, loaded=None,
           timings: dict | None = None):
    model, params = loaded or load_params(checkpoint_dir, size, seq_len, moe_every)
    prompt = jnp.asarray(np.frombuffer(prompt_text, np.uint8)[None, :], jnp.int32)
    out = {}
    variables = {"params": params}
    key0 = jax.random.key(0)
    greedy = np.asarray(
        generate(model, variables, prompt, gen_steps, key0)
    )  # first call pays the decode-path compile
    if timings is not None:
        import time as _time

        # The np.asarray above already forced the warm-up to completion (the
        # one reliable sync on relay-backed platforms, where
        # block_until_ready can be a no-op), so the window below times only
        # the second generate call.
        t0 = _time.perf_counter()
        greedy = np.asarray(generate(model, variables, prompt, gen_steps, key0))
        dt = _time.perf_counter() - t0
        # The scan runs p-1 prompt-prefill steps PLUS gen_steps generation
        # steps, all single-token cached decodes — count them all.
        decode_steps = prompt.shape[1] - 1 + gen_steps
        timings["decode_tok_per_s"] = decode_steps / dt
        timings["decode_steps"] = decode_steps
    out["greedy"] = bytes(greedy[0].astype(np.uint8))
    if temperature > 0:
        out[f"t={temperature}"] = bytes(
            np.asarray(generate(model, variables, prompt, gen_steps,
                                jax.random.key(1), temperature=temperature))[0].astype(np.uint8)
        )
    return out


def decode_benchmark(model, params, *, prompt_len=32, gen_steps=128,
                     batches=(1, 8, 32, 128)) -> list[dict]:
    """Batched KV-cache decode throughput (r4 VERDICT item 8): time greedy
    ``generate`` at several decode batch sizes and report aggregate tok/s and
    per-stream rate. One compile per batch size (shape change); the timed
    window is the second call. Single-token decode is HBM-bandwidth-bound
    (every step streams the full param set), so aggregate tok/s should rise
    nearly linearly with batch until the cache/weights traffic saturates —
    this measures where, instead of claiming it."""
    import time as _time

    variables = {"params": params}
    rows = []
    base = jnp.arange(prompt_len, dtype=jnp.int32)[None, :] % 200 + 32
    for b in batches:
        prompt = jnp.broadcast_to(base, (b, prompt_len))
        key = jax.random.key(0)
        np.asarray(generate(model, variables, prompt, gen_steps, key))  # compile+warm
        t0 = _time.perf_counter()
        np.asarray(generate(model, variables, prompt, gen_steps, key))
        dt = _time.perf_counter() - t0
        steps = prompt_len - 1 + gen_steps  # prefill + generation, all cached
        rows.append({
            "batch": b,
            "tok_per_s": b * steps / dt,
            "tok_per_s_per_stream": steps / dt,
            "step_ms": dt / steps * 1e3,
        })
    return rows


if __name__ == "__main__":
    ckpt = sys.argv[1] if len(sys.argv) > 1 else "./runs/lm/weights/last"
    corpus = sys.argv[2] if len(sys.argv) > 2 else os.environ.get("LM_CORPUS", "")
    size = os.environ.get("LM_SIZE", "small")
    seq_len = int(os.environ.get("SEQ_LEN", "256"))
    moe_every = int(os.environ.get("MOE_EVERY", "0"))  # must match training
    loaded = load_params(ckpt, size, seq_len, moe_every)  # restore once
    if corpus:
        results = evaluate(ckpt, corpus, size=size, seq_len=seq_len,
                           batch=int(os.environ.get("EVAL_BATCH", "64")), loaded=loaded)
        print(f"VALIDATION: nll={results['nll']:.4f} ppl={results['ppl']:.2f} "
              f"({results['n_windows']} windows)")
    # Generation runs for dense AND MoE checkpoints (the MoE decode path
    # is capacity-free and parity-tested).
    prompt = os.environ.get("PROMPT", "").encode() or b"the "
    timings: dict = {}
    for name, text in sample(
        ckpt, prompt, size=size, seq_len=seq_len,
        gen_steps=int(os.environ.get("GEN_STEPS", "64")),
        temperature=float(os.environ.get("TEMPERATURE", "0.8")), loaded=loaded,
        timings=timings,
    ).items():
        print(f"--- {name} ---")
        print(text.decode("utf-8", errors="replace"))
    if timings:
        # Sequential KV-cache decode rate, batch 1, compile excluded
        # (serving throughput scales with decode batch; this is the
        # latency-floor number).
        print(f"DECODE: {timings['decode_tok_per_s']:.1f} tok/s "
              f"(greedy, batch 1, {timings['decode_steps']} single-token steps)")
    # DECODE_BATCHES="1,8,32,128": measure batched decode throughput instead
    # of claiming it scales (BASELINE.md decode table). DECODE_GEN_STEPS sets
    # the timing window independently of the sampling GEN_STEPS — the
    # per-step rate is window-length sensitive (dispatch amortization), so
    # table rows must come from a fixed window.
    if os.environ.get("DECODE_BATCHES"):
        batches = tuple(int(x) for x in os.environ["DECODE_BATCHES"].split(","))
        model, params = loaded
        for row in decode_benchmark(
            model, params, gen_steps=int(os.environ.get("DECODE_GEN_STEPS", "128")),
            batches=batches,
        ):
            print(
                f"DECODE_BATCH {row['batch']:4d}: {row['tok_per_s']:9.1f} tok/s "
                f"aggregate, {row['tok_per_s_per_stream']:7.1f} tok/s/stream, "
                f"{row['step_ms']:.2f} ms/step"
            )
