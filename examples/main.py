"""Entry point — capability twin of the reference ``main.py``.

Wires the logger, distributed setup, the example trainer with the reference's
configuration (labels [cat, dog, snake], 224x224, 300 epochs, global batch 16,
validate every 5 epochs saving best by ("accuracy", "geq"), save dir ./runs,
no snapshot — ``main.py:5-22``), trains, and tears down (``main.py:24-26``).
"""

import sys

sys.path.insert(0, ".")  # allow `python examples/main.py` from the repo root

from distributed_training_pytorch_tpu.utils import Logger
from examples.example_trainer import ExampleTrainer

if __name__ == "__main__":
    logger = Logger("VGG16", "./runs/logfile.log")

    # Analog of ExampleTrainer.ddp_setup(backend="nccl") (``main.py:7``): a
    # no-op single-process; reads COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID
    # on multi-host pods (see run.sh).
    ExampleTrainer.distributed_setup()

    trainer = ExampleTrainer(
        train_path="./data/train",
        val_path="./data/val",
        labels=["cat", "dog", "snake"],
        height=224,
        width=224,
        max_epoch=300,
        batch_size=16,
        pin_memory=True,  # accepted for parity; async prefetch makes it moot
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=5,
        save_folder="./runs",
        snapshot_path=None,
        logger=logger,
    )

    trainer.train()

    ExampleTrainer.destroy_process()
