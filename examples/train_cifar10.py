"""VGG16 / CIFAR-10 training — the BASELINE.json north-star config.

``./run.sh`` runs this on TPU: VGG16 (bf16 activations) on CIFAR-10 with
data-parallel sharding over every available chip, targeting GPU-DDP top-1
parity at >= 60% MFU (BASELINE.md). Reads the standard ``cifar-10-batches-py``
pickle directory (pure numpy — no torchvision dependency); if absent, falls
back to a synthetic CIFAR-shaped set so the pipeline is still exercisable.

Env knobs: ``CIFAR10_DIR`` (default ./data/cifar-10-batches-py), ``EPOCHS``
(default 100), ``BATCH`` (global, default 1024), ``BASE_LR`` (default 0.1,
linearly scaled by BATCH/256), ``SAVE_DIR`` (default ./runs/cifar10),
``DTYPE`` (fp32|bf16|fp16 mixed-precision policy — docs/mixed_precision.md),
``PALLAS`` (1|0 kernel-policy knob, unset = per-model auto — ops/dispatch.py),
``TUNED`` (1 adopts the committed TUNED.json winner's knobs as defaults —
docs/performance.md "Autotuning").
"""

from __future__ import annotations

import os
import pickle
import sys

sys.path.insert(0, ".")

from distributed_training_pytorch_tpu.ops.dispatch import pallas_from_env
from distributed_training_pytorch_tpu.train.autotune import tuned_defaults

# TUNED=1 (mirrors DTYPE/CHAIN_STEPS; docs/performance.md "Autotuning"):
# adopt the committed TUNED.json winner's knobs as DEFAULTS — resolved here,
# before the first jax use, so a tuned xla_flags win installs into XLA_FLAGS
# in time for backend init. Explicit env knobs still override; unset TUNED
# (the default) changes nothing anywhere.
TUNED = tuned_defaults()

import jax.numpy as jnp
import numpy as np
import optax

from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.ops import accuracy, cross_entropy_loss, warmup_cosine_lr
from distributed_training_pytorch_tpu.parallel import mesh_from_env
from distributed_training_pytorch_tpu.trainer import Trainer
from distributed_training_pytorch_tpu.utils import Logger

CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


def load_cifar10(data_dir: str):
    """Read the canonical CIFAR-10 python pickles -> (train_x, train_y, test_x,
    test_y) as uint8 NHWC / int32. Synthetic fallback when the dir is absent."""
    if os.path.isdir(data_dir):
        def read(name):
            with open(os.path.join(data_dir, name), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            y = np.asarray(d[b"labels"], np.int32)
            return x, y

        xs, ys = zip(*(read(f"data_batch_{i}") for i in range(1, 6)), strict=True)
        test_x, test_y = read("test_batch")
        return np.concatenate(xs), np.concatenate(ys), test_x, test_y
    print(f"WARNING: {data_dir} not found — using synthetic CIFAR-shaped data")
    rng = np.random.RandomState(0)
    y = rng.randint(0, 10, size=(50000,)).astype(np.int32)
    x = (rng.randn(50000, 32, 32, 3) * 40 + 120 + y[:, None, None, None] * 8).clip(0, 255)
    ty = rng.randint(0, 10, size=(10000,)).astype(np.int32)
    tx = (rng.randn(10000, 32, 32, 3) * 40 + 120 + ty[:, None, None, None] * 8).clip(0, 255)
    return x.astype(np.uint8), y, tx.astype(np.uint8), ty


class Cifar10Transform:
    """Standard CIFAR recipe: pad-4 random crop + horizontal flip + normalize,
    deterministic per (epoch, index) like data.transforms.Compose."""

    def __init__(self, seed: int = 0, train: bool = True):
        self.seed = seed
        self.train = train

    def __call__(self, img: np.ndarray, *, epoch: int = 0, index: int = 0) -> np.ndarray:
        from distributed_training_pytorch_tpu.data.transforms import philox_key

        out = img.astype(np.float32) / 255.0
        if self.train:
            rng = np.random.Generator(np.random.Philox(key=philox_key(self.seed, epoch, index)))
            padded = np.pad(out, ((4, 4), (4, 4), (0, 0)), mode="reflect")
            dy, dx = rng.integers(0, 9, size=2)
            out = padded[dy : dy + 32, dx : dx + 32]
            if rng.random() < 0.5:
                out = out[:, ::-1]
        return np.ascontiguousarray((out - CIFAR_MEAN) / CIFAR_STD)


# DTYPE (mirrors CHAIN_STEPS): fp32|bf16|fp16 — sets the trainer's mixed-
# precision policy AND the model compute dtype together (fp16 auto-enables
# dynamic loss scaling; docs/mixed_precision.md). Unset keeps this entry's
# historical program: bf16 model-internal casts under the default (inactive)
# fp32 policy. Model dtype resolves via precision.model_dtype_for_entry
# against the trainer's RESOLVED policy, so an explicit precision= ctor
# override agrees with build_model even when the env knob is unset.
DTYPE = os.environ.get("DTYPE") or None

# PALLAS (mirrors DTYPE/CHAIN_STEPS/MESH): 1 forces the fused Pallas kernel
# paths, 0 forces plain XLA, unset = per-model auto — for VGG16 every
# resolution lands on plain (no fused-kernel coverage for 3x3 convs) and the
# no-op is recorded as a kernel_dispatch event rather than ignored silently
# (ops/dispatch.py). A kept TUNED.json pallas verdict is the auto default.
PALLAS = pallas_from_env(default=TUNED.get("pallas"))


class Cifar10Trainer(Trainer):
    def __init__(self, data_dir: str, base_lr: float, **kw):
        data = load_cifar10(data_dir)
        self.train_x, self.train_y, self.test_x, self.test_y = data
        self.base_lr = base_lr
        kw.setdefault("precision", DTYPE)  # env default; callers may override
        super().__init__(**kw)

    def _transform(self, train: bool):
        # Prefer the native C++ batch augmenter (one GIL-free call per batch)
        # with uint8 output — normalization runs on device (InputNormalizer),
        # so the H2D link carries 1 byte/px instead of 4. Python per-record
        # fallback normalizes host-side. Both are deterministic per
        # (seed, epoch, record) — see data/native.py.
        from distributed_training_pytorch_tpu.data import native

        if native.available():
            return native.NativeCropFlipU8(pad=4, seed=self.seed, train=train)
        return Cifar10Transform(seed=self.seed, train=train)

    @property
    def _device_normalize(self) -> bool:
        from distributed_training_pytorch_tpu.data import native

        return native.available()

    def build_train_dataset(self):
        return ArrayDataSource(
            transform=self._transform(train=True),
            image=self.train_x,
            label=self.train_y,
        )

    def build_val_dataset(self):
        return ArrayDataSource(
            transform=self._transform(train=False),
            image=self.test_x,
            label=self.test_y,
        )

    def build_model(self):
        from distributed_training_pytorch_tpu.models import create_model
        from distributed_training_pytorch_tpu.precision import model_dtype_for_entry

        # create_model consumes the pallas knob for VGG16 (no fused-kernel
        # coverage) and records the plain resolution — the knob is uniform
        # across entries, never silently dropped.
        model = create_model(
            "vgg16",
            num_classes=10,
            dtype=model_dtype_for_entry(
                self.precision, DTYPE is not None or self.precision_requested, jnp.bfloat16
            ),
            pallas=PALLAS,
        )
        if self._device_normalize:
            from distributed_training_pytorch_tpu.models import InputNormalizer

            model = InputNormalizer(model, mean=tuple(CIFAR_MEAN), std=tuple(CIFAR_STD))
        return model

    # mask-weighted metrics below satisfy the padded-validation contract
    # (trainer.validate warns when this is not declared)
    criterion_uses_mask = True

    def build_criterion(self):
        def criterion(logits, batch):
            mask = batch.get("mask")
            loss = cross_entropy_loss(logits, batch["label"], weights=mask)
            return loss, {
                "ce_loss": loss,
                "accuracy": accuracy(logits, batch["label"], weights=mask),
            }

        return criterion

    def build_optimizer(self, schedule):
        return optax.chain(optax.add_decayed_weights(5e-4), optax.sgd(schedule, momentum=0.9))

    def build_scheduler(self):
        steps_per_epoch = max(1, len(self.train_y) // self.batch_size)
        # Linear LR scaling with global batch (Goyal et al. recipe) + cosine.
        lr = self.base_lr * self.batch_size / 256.0
        return warmup_cosine_lr(lr, self.max_epoch, steps_per_epoch, warmup_epochs=5)


if __name__ == "__main__":
    Trainer.distributed_setup()
    save_dir = os.environ.get("SAVE_DIR", "./runs/cifar10")
    trainer = Cifar10Trainer(
        data_dir=os.environ.get("CIFAR10_DIR", "./data/cifar-10-batches-py"),
        base_lr=float(os.environ.get("BASE_LR", "0.1")),
        max_epoch=int(os.environ.get("EPOCHS", "100")),
        batch_size=int(os.environ.get("BATCH", "1024")),
        # explicit CHAIN_STEPS wins; a kept TUNED.json chain_steps is the
        # default under TUNED=1; otherwise the historical 1.
        chain_steps=int(os.environ.get("CHAIN_STEPS")
                        or TUNED.get("chain_steps") or 1),
        # MESH (the CHAIN_STEPS/DTYPE convention): a mesh spec like
        # "fsdp4x2" or "dp2fsdp2tp2" trains sharded end to end
        # (docs/parallelism.md); unset = the historical pure-DP program.
        mesh=mesh_from_env(),
        # TELEMETRY=1 (mirrors DTYPE/CHAIN_STEPS): telemetry subsystem —
        # docs/observability.md. Unset = historical program.
        telemetry=os.environ.get("TELEMETRY") == "1" or None,
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=5,
        save_folder=save_dir,
        snapshot_path=os.environ.get("SNAPSHOT") or None,
        logger=Logger("cifar10-vgg16", os.path.join(save_dir, "logfile.log")),
    )
    trainer.train()
    Trainer.destroy_process()
