"""VGG16 on real data, end to end — the accuracy-clause run.

The reference's whole purpose is train-to-accuracy (``main.py:9-24`` drives the
epochs; ``eval.py:69-72`` measures top-1/top-k of the produced checkpoint).
This entry reproduces that loop on the only real image corpus reachable
offline (sklearn digits — see ``digits_data.py``): materialize the image
folders, train the reference-parity :class:`ExampleTrainer` stack (VGG16,
SGD 0.9-momentum + 1e-4 wd, MultiStepLR), save best/last checkpoints, then
evaluate the *saved checkpoint* with ``examples/eval.py``'s ``evaluate()`` and
print the measured top-1 — the number recorded in BASELINE.md.

Digits-specific deviations from the reference recipe (both documented, both
dataset-appropriate, exactly as the reference's own pipeline is tuned to its
3-class photo task):

* the train transform drops the orientation-destroying ops (rotate90, h/v
  flip — a mirrored "2" or rotated "6" is not a valid digit) and keeps the
  photometric ones;
* base lr defaults to 0.02 (env ``DIGITS_LR``): VGG16 has no BatchNorm, and
  the reference's 0.1 assumes its batch-16 photo config.

Env knobs: ``DIGITS_DIR`` (default ./data/digits), ``EPOCHS`` (default 150),
``BATCH`` (global, default 128), ``DIGITS_LR``, ``SAVE_DIR`` (default
./runs/digits), ``DTYPE`` (fp32|bf16|fp16 mixed-precision policy, default
fp32 — docs/mixed_precision.md), ``TELEMETRY`` (1 = event log + goodput +
train-health stats + MFU — docs/observability.md), ``MESH`` (a mesh spec
like ``fsdp4x2`` or ``dp2fsdp2tp2`` — sharded FSDP/TP training,
docs/parallelism.md; unset = pure DP), ``PALLAS`` (1|0 kernel-policy knob,
unset = per-model auto — ops/dispatch.py; a no-op recorded as such for
VGG16).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, ".")

from distributed_training_pytorch_tpu.data import ImageFolderDataSource
from distributed_training_pytorch_tpu.data.transforms import (
    Compose,
    clahe,
    normalize,
    random_brightness_contrast,
    random_gamma,
    resize,
)
from distributed_training_pytorch_tpu.ops import multistep_lr
from distributed_training_pytorch_tpu.ops.dispatch import pallas_from_env
from distributed_training_pytorch_tpu.parallel import mesh_from_env
from distributed_training_pytorch_tpu.trainer import Trainer
from distributed_training_pytorch_tpu.utils import Logger
from examples.digits_data import LABELS, SIZE, materialize
from examples.example_trainer import ExampleTrainer


def digits_train_transform(height: int, width: int, *, seed: int = 0, p: float = 0.5):
    """The reference train pipeline minus orientation ops (see module doc)."""
    return Compose(
        [
            resize(height, width),
            clahe(p),
            random_brightness_contrast(p),
            random_gamma(p),
            normalize(),
        ],
        seed=seed,
    )


class DigitsTrainer(ExampleTrainer):
    base_lr = float(os.environ.get("DIGITS_LR", "0.02"))
    # PALLAS (mirrors DTYPE/CHAIN_STEPS/MESH): kernel-policy knob, resolved
    # at the entry and passed down as a constructor-level value — the
    # library never reads env (ops/dispatch.py). Unset = historical program.
    pallas = pallas_from_env()

    def build_train_dataset(self):
        return ImageFolderDataSource(
            self.train_path,
            self.labels,
            transform=digits_train_transform(self.height, self.width, seed=self.seed),
        )

    def build_scheduler(self):
        steps_per_epoch = max(1, len(self.train_dataset) // self.batch_size)
        return multistep_lr(
            self.base_lr, [50, 100, 200], gamma=0.1, steps_per_epoch=steps_per_epoch
        )


def parse_curve(logfile: str) -> list[dict]:
    """Per-epoch (train loss, val accuracy) pairs from the run's logfile —
    the training curve recorded in-repo alongside the final number."""
    import re

    curve: dict[int, dict] = {}
    epoch = None
    with open(logfile) as f:
        for line in f:
            m = re.search(r"Epoch (\d+)/", line)
            if m:
                epoch = int(m.group(1))
            if "TOTAL GLOBAL TRAINING LOSS" in line and epoch is not None:
                lm = re.search(r"ce_loss = ([0-9.eE+-]+)", line)
                if lm:
                    curve.setdefault(epoch, {"epoch": epoch})["train_ce"] = float(
                        lm.group(1)
                    )
            if "VALIDATE RESULTS" in line and epoch is not None:
                am = re.search(r"accuracy = ([0-9.eE+-]+)", line)
                if am:
                    curve.setdefault(epoch, {"epoch": epoch})["val_acc"] = float(
                        am.group(1)
                    )
    return [curve[k] for k in sorted(curve)]


if __name__ == "__main__":
    data_dir = os.environ.get("DIGITS_DIR", "./data/digits")
    save_dir = os.environ.get("SAVE_DIR", "./runs/digits")
    counts = materialize(data_dir)
    print(f"digits corpus: {counts}")

    Trainer.distributed_setup()
    trainer = DigitsTrainer(
        train_path=os.path.join(data_dir, "train"),
        val_path=os.path.join(data_dir, "test"),
        labels=LABELS,
        height=SIZE,
        width=SIZE,
        max_epoch=int(os.environ.get("EPOCHS", "150")),
        batch_size=int(os.environ.get("BATCH", "128")),
        chain_steps=int(os.environ.get("CHAIN_STEPS", "1")),
        # MESH (the CHAIN_STEPS/DTYPE convention): a mesh spec like
        # "fsdp4x2" or "dp2fsdp2tp2" trains sharded end to end
        # (docs/parallelism.md); unset = the historical pure-DP program.
        mesh=mesh_from_env(),
        # DTYPE (mirrors CHAIN_STEPS): fp32|bf16|fp16 mixed-precision policy;
        # the model's activation dtype follows via ExampleTrainer.build_model
        # (docs/mixed_precision.md). Default fp32 = reference parity.
        precision=os.environ.get("DTYPE") or None,
        # TELEMETRY=1 (mirrors DTYPE/CHAIN_STEPS): events JSONL under
        # SAVE_DIR/telemetry, goodput buckets, on-device train-health stats,
        # per-window MFU (docs/observability.md). Unset = historical program.
        telemetry=os.environ.get("TELEMETRY") == "1" or None,
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=int(os.environ.get("SAVE_PERIOD", "25")),
        # The chip sits behind a thin relay here: a full-state d2h snapshot
        # costs minutes, so `last` is saved on the validation cadence rather
        # than the reference's every-epoch default.
        last_save_period=int(os.environ.get("SAVE_PERIOD", "25")),
        save_folder=save_dir,
        snapshot_path=os.environ.get("SNAPSHOT") or None,
        logger=Logger("digits-vgg16", os.path.join(save_dir, "logfile.log")),
    )
    trainer.train()

    # Offline eval of the SAVED checkpoint via the eval twin (ref eval.py flow).
    from examples.eval import evaluate

    results = {}
    for name in ("best", "last"):
        ckpt = os.path.join(save_dir, "weights", name)
        if os.path.isdir(ckpt):
            results[name] = evaluate(
                ckpt,
                os.path.join(data_dir, "test"),
                labels=LABELS,
                model=trainer.model,
                height=SIZE,
                width=SIZE,
            )
            print(
                f"[{name}] ACCURACY TOP-1: {results[name]['top1']:.4f}  "
                f"TOP-2: {results[name]['top2']:.4f}"
            )
    summary = {
        "corpus": "sklearn digits (real, offline stand-in for CIFAR-10)",
        "train_images": counts["train"],
        "test_images": counts["test"],
        "epochs": trainer.max_epoch,
        "batch": trainer.batch_size,
        "base_lr": DigitsTrainer.base_lr,
        "results": results,
        "curve": parse_curve(os.path.join(save_dir, "logfile.log")),
    }
    with open(os.path.join(save_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print("summary ->", os.path.join(save_dir, "summary.json"))
    Trainer.destroy_process()
