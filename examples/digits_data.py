"""Materialize the sklearn `digits` corpus as an image-folder tree.

The only *real* image-classification corpus reachable in this offline
environment (network egress is blocked — CIFAR-10 cannot be downloaded; see
BASELINE.md). 1,797 genuine 8x8 grayscale handwritten digits (UCI Optical
Recognition of Handwritten Digits) are upscaled to 32x32 RGB PNGs and laid out
exactly like the reference's dataset tree (``dataset/example_dataset.py:24-30``:
``<root>/<split>/<label>/*.png``), so the full reference flow — ImageFolder
scan, native decode, augment, train, checkpoint, offline ``eval.py`` — runs on
real data end to end.

Split: stratified 80/20 train/test with a fixed seed (1,438 / 359).
"""

from __future__ import annotations

import os

import numpy as np

LABELS = [str(d) for d in range(10)]
SIZE = 32


def materialize(root: str, *, seed: int = 0) -> dict:
    """Write ``<root>/{train,test}/<digit>/*.png``; no-op if already present.

    Returns counts ``{"train": n, "test": n}``.
    """
    import cv2
    from sklearn.datasets import load_digits

    marker = os.path.join(root, ".complete")
    if os.path.exists(marker):
        counts = {}
        for split in ("train", "test"):
            counts[split] = sum(
                len(os.listdir(os.path.join(root, split, lb))) for lb in LABELS
            )
        return counts

    data = load_digits()
    images = data.images  # [1797, 8, 8] float in [0, 16]
    targets = data.target.astype(np.int64)

    rng = np.random.RandomState(seed)
    counts = {"train": 0, "test": 0}
    for digit in range(10):
        idx = np.flatnonzero(targets == digit)
        rng.shuffle(idx)
        n_test = max(1, int(round(0.2 * len(idx))))
        splits = {"test": idx[:n_test], "train": idx[n_test:]}
        for split, members in splits.items():
            d = os.path.join(root, split, str(digit))
            os.makedirs(d, exist_ok=True)
            for i in members:
                img = np.clip(images[i] * (255.0 / 16.0), 0, 255).astype(np.uint8)
                img = cv2.resize(img, (SIZE, SIZE), interpolation=cv2.INTER_NEAREST)
                cv2.imwrite(
                    os.path.join(d, f"{i:04d}.png"),
                    np.repeat(img[:, :, None], 3, axis=2),
                )
            counts[split] += len(members)
    with open(marker, "w") as f:
        f.write("ok\n")
    return counts


if __name__ == "__main__":
    import sys

    root = sys.argv[1] if len(sys.argv) > 1 else "./data/digits"
    print(materialize(root))
