"""Causal-LM training entry — the long-context family's example surface.

Beyond the reference's scope (vision-only); demonstrates the decoder stack
(flash attention on TPU, optional MoE blocks) through the same nine-hook
Trainer the vision configs use. Input is a byte-level corpus file split into
fixed windows (``LM_CORPUS``); without one, a synthetic structured byte stream
keeps the entry smoke-runnable anywhere.

Launch: ``MODEL=lm ./run.sh``. Env knobs: ``LM_CORPUS`` (text/bytes file —
build a real one offline with ``examples/make_lm_corpus.py``), ``SEQ_LEN``
(default 256), ``EPOCHS``, ``BATCH``, ``BASE_LR``, ``MOE_EVERY`` (0 = dense),
``SAVE_DIR``, ``SNAPSHOT``, ``PROFILE_DIR``, ``LM_SIZE`` (``tiny`` | ``small``
= GPT-2-small shape), ``SAVE_PERIOD`` / ``LAST_SAVE_PERIOD`` (epochs between
periodic / `last` saves — raise both when the checkpoint path is slow, e.g.
a chip behind a relay where a GPT-small save costs minutes), ``DTYPE``
(fp32|bf16|fp16 mixed-precision policy — docs/mixed_precision.md),
``PALLAS`` (1|0 kernel-policy knob: forces the flash-attention path on/off;
unset = the historical auto — ops/dispatch.py, docs/performance.md
"Autotuning").
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.models import GPTSmall, LMTiny
from distributed_training_pytorch_tpu.ops import warmup_cosine_lr
from distributed_training_pytorch_tpu.ops.dispatch import pallas_from_env
from distributed_training_pytorch_tpu.parallel import mesh_from_env
from distributed_training_pytorch_tpu.trainer import Trainer
from distributed_training_pytorch_tpu.utils import Logger
from distributed_training_pytorch_tpu.utils.tpu import enable_fast_rng


def load_windows(seq_len: int, path: str | None = None) -> np.ndarray:
    """[N, seq_len+1] int32 byte windows (input = [:-1], target = [1:]).
    ``path`` overrides the LM_CORPUS env (offline eval passes it directly)."""
    path = path if path is not None else os.environ.get("LM_CORPUS")
    if path:
        if not os.path.exists(path):
            # A typo'd path must not silently train on synthetic data.
            raise FileNotFoundError(f"LM_CORPUS={path!r} does not exist")
        data = np.frombuffer(open(path, "rb").read(), dtype=np.uint8)
    else:
        print("WARNING: LM_CORPUS unset — synthetic structured byte stream")
        rng = np.random.RandomState(0)
        # Repeating motifs + noise: learnable next-byte structure.
        motifs = [rng.randint(0, 255, size=(m,)) for m in (5, 9, 13)]
        parts = [motifs[rng.randint(3)] for _ in range(60000)]
        data = np.concatenate(parts).astype(np.uint8)
    if len(data) < seq_len + 1:
        raise ValueError(
            f"corpus has {len(data)} bytes — too short for SEQ_LEN={seq_len} "
            "(need at least seq_len + 1)"
        )
    # One vectorized strided pass (a per-window Python loop costs tens of
    # seconds and a large transient at GB-corpus scale).
    windows = np.lib.stride_tricks.sliding_window_view(data, seq_len + 1)[::seq_len]
    return windows.astype(np.int32)


# DTYPE (mirrors CHAIN_STEPS): fp32|bf16|fp16 — mixed-precision policy +
# model compute dtype together (fp16 auto-enables dynamic loss scaling;
# docs/mixed_precision.md). Unset keeps the historical program: bf16
# model-internal casts under the default (inactive) fp32 policy. Model dtype
# resolves against the trainer's RESOLVED policy (model_dtype_for_entry) so
# an explicit precision= ctor override agrees with build_model.
DTYPE = os.environ.get("DTYPE") or None

# PALLAS (mirrors DTYPE/CHAIN_STEPS/MESH): 1 forces the Pallas flash-attention
# path, 0 forces the plain einsum path, unset = the historical auto (flash on
# TPU above the sequence-length floor). Every resolution is recorded as a
# kernel_dispatch event (ops/dispatch.py).
PALLAS = pallas_from_env()


class LMTrainer(Trainer):
    def __init__(self, seq_len: int, base_lr: float, size: str, moe_every: int, **kw):
        self.seq_len = seq_len
        self.base_lr = base_lr
        self.size = size
        self.moe_every = moe_every
        self.windows = load_windows(seq_len)
        kw.setdefault("precision", DTYPE)  # env default; callers may override
        super().__init__(**kw)

    # tokens ride the loader's "image" slot; targets are the shifted window.
    def build_train_dataset(self):
        w = self.windows[: int(len(self.windows) * 0.95)]
        return ArrayDataSource(image=w[:, :-1], label=w[:, 1:])

    def build_val_dataset(self):
        w = self.windows[int(len(self.windows) * 0.95) :]
        return ArrayDataSource(image=w[:, :-1], label=w[:, 1:])

    def build_model(self):
        from distributed_training_pytorch_tpu.precision import model_dtype_for_entry

        factory = {"tiny": LMTiny, "small": GPTSmall}[self.size]
        return factory(
            vocab_size=256,
            dtype=model_dtype_for_entry(
                self.precision, DTYPE is not None or self.precision_requested, jnp.bfloat16
            ),
            moe_every=self.moe_every,
            max_len=max(self.seq_len, 128),
            pallas=PALLAS,
        )

    criterion_uses_mask = True

    def build_criterion(self):
        def criterion(logits, batch):
            targets = batch["label"]  # [B, T]
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
            per_example = jnp.mean(nll, axis=-1)  # [B]
            mask = batch.get("mask")
            if mask is None:
                loss = jnp.mean(per_example)
            else:
                loss = jnp.sum(per_example * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            return loss, {"nll": loss, "ppl": jnp.exp(loss)}

        return criterion

    def build_loss_fn(self):
        """Fused tied-head CE by default (FUSED_CE=0 for the naive path): the
        model returns final hidden states and ``tied_cross_entropy`` streams
        the vocab in chunks — the [B, T, 256]/[B, T, 50257] float32 logits
        never materialize (doubles the trainable batch for GPT-small on v5e:
        B=32 -> 64 at T=1024, same tok/s)."""
        if os.environ.get("FUSED_CE", "1") == "0":
            if self.moe_every > 0:
                # the naive criterion path cannot see the routers' sown aux
                # losses — training MoE without them collapses routing, so
                # the toggle is ignored rather than silently degrading
                self.log(
                    "FUSED_CE=0 ignored: MoE models need the fused loss "
                    "(router aux losses ride it)",
                    "warning",
                )
            else:
                return super().build_loss_fn()
        from distributed_training_pytorch_tpu.models.transformer_lm import make_fused_lm_loss

        return make_fused_lm_loss(self.model)

    def build_scheduler(self):
        steps_per_epoch = max(1, len(self.train_dataset) // self.batch_size)
        return warmup_cosine_lr(self.base_lr, self.max_epoch, steps_per_epoch, warmup_epochs=1)

    def build_optimizer(self, schedule):
        return optax.adamw(schedule, weight_decay=0.1, b1=0.9, b2=0.95)

    def build_example_input(self):
        return jnp.zeros((1, self.seq_len), jnp.int32)


if __name__ == "__main__":
    enable_fast_rng()
    Trainer.distributed_setup()
    save_dir = os.environ.get("SAVE_DIR", "./runs/lm")
    trainer = LMTrainer(
        seq_len=int(os.environ.get("SEQ_LEN", "256")),
        base_lr=float(os.environ.get("BASE_LR", "3e-4")),
        size=os.environ.get("LM_SIZE", "small"),
        moe_every=int(os.environ.get("MOE_EVERY", "0")),
        max_epoch=int(os.environ.get("EPOCHS", "10")),
        batch_size=int(os.environ.get("BATCH", "256")),
        chain_steps=int(os.environ.get("CHAIN_STEPS", "1")),
        # MESH (the CHAIN_STEPS/DTYPE convention): a mesh spec like
        # "fsdp4x2" or "dp2fsdp2tp2" trains sharded end to end
        # (docs/parallelism.md); unset = the historical pure-DP program.
        mesh=mesh_from_env(),
        # TELEMETRY=1 (mirrors DTYPE/CHAIN_STEPS): telemetry subsystem —
        # docs/observability.md. Unset = historical program.
        telemetry=os.environ.get("TELEMETRY") == "1" or None,
        have_validate=True,
        save_best_for=("nll", "leq"),
        save_period=int(os.environ.get("SAVE_PERIOD", "1")),
        last_save_period=int(os.environ.get("LAST_SAVE_PERIOD", "1")),
        save_folder=save_dir,
        snapshot_path=os.environ.get("SNAPSHOT") or None,
        logger=Logger("lm", os.path.join(save_dir, "logfile.log")),
        profile_dir=os.environ.get("PROFILE_DIR") or None,
    )
    trainer.train()
    Trainer.destroy_process()
