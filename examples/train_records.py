"""Records-path train-to-accuracy — the PRODUCTION input pipeline, proven.

The reference's whole purpose is train -> checkpoint -> offline-eval accuracy
(``main.py:9-24`` drives the epochs; ``eval.py:69-72`` scores the produced
checkpoint). The two earlier convergence proofs (``train_digits.py`` 99.4%
top-1, ``train_lm.py`` ppl 2.64) run through the ImageFolder and LM-window
sources; this entry proves the *at-scale* path BASELINE configs 3-5 actually
use, end to end on real data:

    real images -> packed ``.rec`` shards (``data.records.pack_image_folder``)
    -> ``NativeRecordTrainSource``: native C++ decode+resize (uint8)
       + native deterministic crop augmentation (uint8)
    -> uint8 over the host->device link (1 byte/px)
    -> ``models.InputNormalizer`` normalizes inside the jitted step
    -> ``Trainer`` (checkpoints, validation, preemption handling)
    -> offline ``examples/eval.py`` of the SAVED checkpoint, through the
       independent ImageFolder eval pipeline — so a label misalignment or
       augmentation bug anywhere in the records path shows up as a top-1 gap.

Corpus: the sklearn digits tree (``digits_data.py`` — the only real image
corpus reachable offline), packed once into 4 train + 2 test shards. Model:
``ResNet18Slim`` (bottleneck ResNet, BN statistics over the global batch) —
a compact member of the ImageNet family whose full-size siblings consume this
exact pipeline. Augmentation is crop-only (``hflip=False``: a mirrored digit
is not a valid digit, same reasoning as ``train_digits.py``).

Env knobs: ``DIGITS_DIR`` (default ./data/digits), ``RECORDS_DIR`` (default
<DIGITS_DIR>/records), ``EPOCHS`` (default 60), ``BATCH`` (global, default
128), ``RECORDS_LR`` (default 0.1, x BATCH/256), ``SAVE_DIR`` (default
./runs/records_digits), ``DTYPE`` (fp32|bf16|fp16 mixed-precision policy —
docs/mixed_precision.md), ``PALLAS`` (1|0 kernel-policy knob: forces the
fused conv1x1+BN+act Pallas path on/off for the ResNet; unset = the
historical auto — ops/dispatch.py, docs/performance.md "Autotuning").
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, ".")

import jax.numpy as jnp
import optax

from distributed_training_pytorch_tpu.data import (
    NativeRecordFileSource,
    NativeRecordTrainSource,
    pack_image_folder,
)
from distributed_training_pytorch_tpu.data import transforms as T
from distributed_training_pytorch_tpu.models import InputNormalizer, ResNet18Slim
from distributed_training_pytorch_tpu.ops import accuracy, cross_entropy_loss, warmup_cosine_lr
from distributed_training_pytorch_tpu.ops.dispatch import pallas_from_env
from distributed_training_pytorch_tpu.parallel import mesh_from_env
from distributed_training_pytorch_tpu.trainer import Trainer
from distributed_training_pytorch_tpu.utils import Logger
from examples.digits_data import LABELS, SIZE, materialize
from examples.train_digits import parse_curve


def pack_digits(digits_dir: str, records_dir: str) -> dict:
    """One-time folder-tree -> record-shards conversion (marker-gated)."""
    marker = os.path.join(records_dir, ".complete")
    if not os.path.exists(marker):
        for split, shards in (("train", 4), ("test", 2)):
            pack_image_folder(
                os.path.join(digits_dir, split),
                LABELS,
                os.path.join(records_dir, split),
                num_shards=shards,
            )
        with open(marker, "w") as f:
            f.write("ok\n")
    return {
        split: os.path.join(records_dir, f"{split}-*.rec") for split in ("train", "test")
    }


# DTYPE (mirrors CHAIN_STEPS): fp32|bf16|fp16 — mixed-precision policy +
# model compute dtype together (fp16 auto-enables dynamic loss scaling;
# docs/mixed_precision.md). Unset keeps the historical program: bf16
# model-internal casts under the default (inactive) fp32 policy. Model dtype
# resolves against the trainer's RESOLVED policy (model_dtype_for_entry) so
# an explicit precision= ctor override agrees with build_model.
DTYPE = os.environ.get("DTYPE") or None

# PALLAS (mirrors DTYPE/CHAIN_STEPS/MESH): 1 forces the fused conv1x1+BN+act
# Pallas path in the ResNet's projection shortcuts, 0 forces plain XLA,
# unset = the historical auto. Every resolution is recorded as a
# kernel_dispatch event (ops/dispatch.py).
PALLAS = pallas_from_env()


class RecordsDigitsTrainer(Trainer):
    criterion_uses_mask = True

    def __init__(self, train_pattern: str, val_pattern: str, base_lr: float, **kw):
        self.train_pattern = train_pattern
        self.val_pattern = val_pattern
        self.base_lr = base_lr
        kw.setdefault("precision", DTYPE)  # env default; callers may override
        super().__init__(**kw)

    def build_train_dataset(self):
        return NativeRecordTrainSource(
            self.train_pattern, SIZE, SIZE, pad=4, seed=self.seed, hflip=False
        )

    def build_val_dataset(self):
        # Val ships pre-normalized float32 (native decode+resize+normalize in
        # one C++ call); InputNormalizer's static-dtype dispatch passes float
        # through — mixed uint8-train / f32-val traces one impl each.
        return NativeRecordFileSource(self.val_pattern, height=SIZE, width=SIZE)

    def build_model(self):
        from distributed_training_pytorch_tpu.precision import model_dtype_for_entry

        return InputNormalizer(
            inner=ResNet18Slim(
                num_classes=len(LABELS),
                dtype=model_dtype_for_entry(
                self.precision, DTYPE is not None or self.precision_requested, jnp.bfloat16
            ),
                pallas=PALLAS,
            ),
            mean=list(T.IMAGENET_MEAN),
            std=list(T.IMAGENET_STD),
        )

    def build_criterion(self):
        def criterion(logits, batch):
            mask = batch.get("mask")
            loss = cross_entropy_loss(logits, batch["label"], weights=mask)
            return loss, {
                "ce_loss": loss,
                "accuracy": accuracy(logits, batch["label"], weights=mask),
            }

        return criterion

    def build_scheduler(self):
        steps_per_epoch = max(1, len(self.train_dataset) // self.batch_size)
        lr = self.base_lr * self.batch_size / 256.0  # Goyal et al. scaling
        return warmup_cosine_lr(lr, self.max_epoch, steps_per_epoch, warmup_epochs=5)

    def build_optimizer(self, schedule):
        return optax.chain(
            optax.add_decayed_weights(1e-4), optax.sgd(schedule, momentum=0.9)
        )


if __name__ == "__main__":
    digits_dir = os.environ.get("DIGITS_DIR", "./data/digits")
    records_dir = os.environ.get("RECORDS_DIR", os.path.join(digits_dir, "records"))
    save_dir = os.environ.get("SAVE_DIR", "./runs/records_digits")
    counts = materialize(digits_dir)
    patterns = pack_digits(digits_dir, records_dir)
    print(f"digits corpus: {counts}; records under {records_dir}")

    Trainer.distributed_setup()
    trainer = RecordsDigitsTrainer(
        train_pattern=patterns["train"],
        val_pattern=patterns["test"],
        base_lr=float(os.environ.get("RECORDS_LR", "0.1")),
        max_epoch=int(os.environ.get("EPOCHS", "60")),
        batch_size=int(os.environ.get("BATCH", "128")),
        chain_steps=int(os.environ.get("CHAIN_STEPS", "1")),
        # MESH (the CHAIN_STEPS/DTYPE convention): a mesh spec like
        # "fsdp4x2" or "dp2fsdp2tp2" trains sharded end to end
        # (docs/parallelism.md); unset = the historical pure-DP program.
        mesh=mesh_from_env(),
        # TELEMETRY=1 (mirrors DTYPE/CHAIN_STEPS): telemetry subsystem —
        # docs/observability.md. Unset = historical program.
        telemetry=os.environ.get("TELEMETRY") == "1" or None,
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=int(os.environ.get("SAVE_PERIOD", "10")),
        # full-state d2h snapshots cost minutes behind the relay (see
        # train_digits.py) — save `last` on the validation cadence
        last_save_period=int(os.environ.get("SAVE_PERIOD", "10")),
        save_folder=save_dir,
        snapshot_path=os.environ.get("SNAPSHOT") or None,
        logger=Logger("records-digits", os.path.join(save_dir, "logfile.log")),
    )
    trainer.train()

    # Offline eval of the SAVED checkpoint through the INDEPENDENT ImageFolder
    # eval pipeline (examples/eval.py) — cross-checks the records packing,
    # native decode, and augmentation against untouched loose files.
    from examples.eval import evaluate

    results = {}
    for name in ("best", "last"):
        ckpt = os.path.join(save_dir, "weights", name)
        if os.path.isdir(ckpt):
            results[name] = evaluate(
                ckpt,
                os.path.join(digits_dir, "test"),
                labels=LABELS,
                model=trainer.model,
                height=SIZE,
                width=SIZE,
            )
            print(
                f"[{name}] ACCURACY TOP-1: {results[name]['top1']:.4f}  "
                f"TOP-2: {results[name]['top2']:.4f}"
            )
    summary = {
        "description": (
            "Third train-to-accuracy proof (r4 VERDICT item 1): the at-scale "
            "records input path — RecordFileSource shards, native C++ "
            "decode/augment, uint8 ship, on-device normalize — trained to "
            "accuracy and offline-evaluated through the independent "
            "ImageFolder eval pipeline."
        ),
        "pipeline": "pack_image_folder -> NativeRecordTrainSource (native decode+crop, uint8) -> InputNormalizer -> Trainer -> checkpoint -> examples/eval.py (ImageFolder path)",
        "model": "ResNet18Slim (bottleneck ResNet, bf16 activations, global-batch BN)",
        "corpus": "sklearn digits (real), packed into 4 train + 2 test .rec shards",
        "train_images": counts["train"],
        "test_images": counts["test"],
        "epochs": trainer.max_epoch,
        "batch": trainer.batch_size,
        "base_lr": trainer.base_lr,
        "results": results,
        "curve": parse_curve(os.path.join(save_dir, "logfile.log")),
    }
    with open(os.path.join(save_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print("summary ->", os.path.join(save_dir, "summary.json"))
    Trainer.destroy_process()
