"""ImageNet-scale training entry — BASELINE.json configs 3-5.

One entry for the three scale-out configs (the reference has a single config
in ``main.py:9-22``; these extend its capability surface per BASELINE.md):

=============  ==============================  =========================================
``MODEL=``     BASELINE config                 recipe
``resnet50``   3: ResNet-50 / ImageNet-1k      SGD momentum, 5-epoch warmup + cosine
``vit_b16``    4: ViT-B/16 / ImageNet-1k       AdamW, cosine, patch-embed + MHA
``convnext_l`` 5: ConvNeXt-L / ImageNet-21k    AdamW, bf16 + gradient accumulation
=============  ==============================  =========================================

Data comes from sharded record files (``data.records`` — pack a folder tree
once with ``python -m distributed_training_pytorch_tpu.data.records`` or
``pack_image_folder``); loose-file ImageFolder scans do not scale to 1.2M+
images. When ``IMAGENET_RECORDS`` is unset, a synthetic in-memory set with the
right shapes runs instead, so every config is smoke-runnable anywhere
(``STEPS_PER_EPOCH`` caps an epoch for timed runs).

Launch: ``MODEL=convnext_l ./run.sh`` (single host) or with the coordinator
env for pods (see run.sh). Env knobs: ``IMAGENET_RECORDS`` (glob or dir of
.rec shards), ``VAL_RECORDS``, ``EPOCHS``, ``BATCH`` (global), ``ACCUM``
(grad-accum microsteps; default 4 for convnext_l else 1), ``BASE_LR``,
``IMAGE_SIZE`` (default 224), ``NUM_CLASSES`` (default 1000; 21841 for
convnext_l), ``SAVE_DIR``, ``SNAPSHOT``, ``PROFILE_DIR``, ``DTYPE``
(fp32|bf16|fp16 mixed-precision policy — docs/mixed_precision.md),
``PALLAS`` (1|0 kernel-policy knob: flash attention for ViT, fused
GEMM+epilogues for ResNet/ConvNeXt; unset = per-model auto —
ops/dispatch.py, docs/performance.md "Autotuning").
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, ".")

import jax.numpy as jnp
import numpy as np
import optax

from distributed_training_pytorch_tpu.data import ArrayDataSource, RecordFileSource
from distributed_training_pytorch_tpu.data import transforms as T
from distributed_training_pytorch_tpu.models import create_model
from distributed_training_pytorch_tpu.ops import accuracy, cross_entropy_loss, warmup_cosine_lr
from distributed_training_pytorch_tpu.ops.dispatch import pallas_from_env
from distributed_training_pytorch_tpu.parallel import mesh_from_env
from distributed_training_pytorch_tpu.trainer import Trainer
from distributed_training_pytorch_tpu.utils import Logger
from distributed_training_pytorch_tpu.utils.tpu import enable_fast_rng

RECIPES = {
    "resnet50": dict(num_classes=1000, optimizer="sgd", base_lr=0.1, accum=1, wd=1e-4),
    "vit_b16": dict(num_classes=1000, optimizer="adamw", base_lr=1e-3, accum=1, wd=0.05),
    "convnext_l": dict(num_classes=21841, optimizer="adamw", base_lr=1e-3, accum=4, wd=0.05),
    # CPU-smokeable stand-in for the convnext_l recipe (same optimizer/accum
    # path; ConvNeXt-L itself takes too long to compile on a CPU host).
    "convnext_tiny": dict(num_classes=21841, optimizer="adamw", base_lr=1e-3, accum=4, wd=0.05),
}


def _ship_uint8() -> bool:
    """SHIP_UINT8=1 (default): the host pipeline stays uint8 end-to-end and
    normalization runs on device (models.wrappers.InputNormalizer, fused by
    XLA into the first conv) — the host->device link carries 4x fewer bytes
    than pre-normalized float32 and the host skips a float pass (measured
    2.7x records-path E2E, BASELINE.md). Same math, same augmentation
    stream; SHIP_UINT8=0 restores host-side normalize.

    NOTE: the wrapper nests the model's params under an ``inner`` scope, so
    the CHECKPOINT TREE depends on this knob — keep it consistent across a
    run's save/resume/eval (snapshots from builds before r4, or from
    SHIP_UINT8=0, restore only with SHIP_UINT8=0)."""
    return os.environ.get("SHIP_UINT8", "1") != "0"


def train_transform(image_size: int, seed: int, ship_uint8: bool = True) -> T.Compose:
    """Random-resized-crop + flip (+ normalize unless shipping uint8),
    Philox-keyed per (epoch, index) — the at-scale analog of the reference's
    albumentations pipeline (``dataset/example_dataset.py:35-46``)."""
    ops = [
        T.random_resized_crop(image_size, image_size),
        T.horizontal_flip(),
    ]
    if not ship_uint8:
        ops.append(T.normalize())
    return T.Compose(ops, seed=seed)


def eval_transform(image_size: int) -> T.Compose:
    return T.eval_transform(image_size, image_size)


def synthetic_source(n: int, image_size: int, num_classes: int, transform, seed: int):
    """Class-separable synthetic images, uint8 — shapes/dtypes of the real
    pipeline without the corpus."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, num_classes, size=(n,)).astype(np.int32)
    x = (rng.randn(n, image_size, image_size, 3) * 40 + 110 + (y % 13)[:, None, None, None] * 9)
    return ArrayDataSource(transform=transform, image=x.clip(0, 255).astype(np.uint8), label=y)


class _LimitedSource:
    """Length-capping view over a source — ``STEPS_PER_EPOCH`` for timed runs
    without touching the underlying corpus."""

    def __init__(self, source, max_records: int):
        self.source = source
        self.transform = getattr(source, "transform", None)
        self._len = min(len(source), max_records)
        # Forward the loader's whole-batch fast path: hiding a source's
        # load_batch would silently drop native decode+augment (the capped
        # row indices are valid for the underlying source unchanged).
        if hasattr(source, "load_batch"):
            self.load_batch = source.load_batch

    def __len__(self):
        return self._len

    def __getitem__(self, index):
        return self.source[index]


# DTYPE (mirrors CHAIN_STEPS): fp32|bf16|fp16 — mixed-precision policy +
# model compute dtype together (fp16 auto-enables dynamic loss scaling;
# docs/mixed_precision.md). Unset keeps the historical program: bf16
# model-internal casts under the default (inactive) fp32 policy. Model dtype
# resolves against the trainer's RESOLVED policy (model_dtype_for_entry) so
# an explicit precision= ctor override agrees with build_model.
DTYPE = os.environ.get("DTYPE") or None

# PALLAS (mirrors DTYPE/CHAIN_STEPS/MESH): 1 forces the fused Pallas paths
# (ViT flash attention, ResNet conv1x1_bn_act, ConvNeXt dense+gelu), 0
# forces plain XLA, unset = per-model auto (the historical defaults). Every
# resolution is recorded as a kernel_dispatch event (ops/dispatch.py).
PALLAS = pallas_from_env()


class ImageNetTrainer(Trainer):
    criterion_uses_mask = True

    def __init__(self, model_name: str, image_size: int, base_lr: float, **kw):
        self.model_name = model_name
        self.image_size = image_size
        self.base_lr = base_lr
        self.recipe = RECIPES[model_name]
        self.num_classes = int(os.environ.get("NUM_CLASSES", self.recipe["num_classes"]))
        self.train_records = os.environ.get("IMAGENET_RECORDS")
        self.val_records = os.environ.get("VAL_RECORDS")
        kw.setdefault("precision", DTYPE)  # env default; callers may override
        super().__init__(**kw)

    def build_train_dataset(self):
        tfm = train_transform(self.image_size, seed=self.seed, ship_uint8=_ship_uint8())
        if self.train_records:
            from distributed_training_pytorch_tpu.data import NativeRecordTrainSource, native

            if (
                _ship_uint8()
                and native.available()
                and os.environ.get("RECORDS_NATIVE", "1") != "0"
            ):
                # The full native batch path: decode + random-resized-crop +
                # flip FUSED in one C++ call per batch, uint8 to the device
                # (InputNormalizer). Falls through to the per-record Python
                # pipeline when the native lib (or uint8 ship) is off.
                source = NativeRecordTrainSource(
                    self.train_records, self.image_size, self.image_size,
                    aug="rrc", seed=self.seed,
                )
            else:
                source = RecordFileSource(self.train_records, transform=tfm)
        else:
            self.log("IMAGENET_RECORDS unset — synthetic ImageNet-shaped data", "warning")
            source = synthetic_source(8192, self.image_size, self.num_classes, tfm, seed=0)
        cap = os.environ.get("STEPS_PER_EPOCH")
        if cap:
            source = _LimitedSource(source, int(cap) * self.batch_size)
        return source

    def build_val_dataset(self):
        if self.val_records:
            # Native batch path: record payloads decode+resize+normalize in
            # one C++ call (data/records.NativeRecordFileSource); falls back
            # to the per-record Python pipeline without the native lib.
            from distributed_training_pytorch_tpu.data import NativeRecordFileSource

            return NativeRecordFileSource(
                self.val_records, height=self.image_size, width=self.image_size
            )
        tfm = eval_transform(self.image_size)
        return synthetic_source(1024, self.image_size, self.num_classes, tfm, seed=1)

    def build_model(self):
        from distributed_training_pytorch_tpu.precision import model_dtype_for_entry

        model = create_model(
            self.model_name,
            num_classes=self.num_classes,
            dtype=model_dtype_for_entry(
                self.precision, DTYPE is not None or self.precision_requested, jnp.bfloat16
            ),
            pallas=PALLAS,
        )
        if _ship_uint8():
            from distributed_training_pytorch_tpu.models.wrappers import InputNormalizer

            model = InputNormalizer(
                inner=model, mean=list(T.IMAGENET_MEAN), std=list(T.IMAGENET_STD)
            )
        return model

    def build_criterion(self):
        def criterion(logits, batch):
            mask = batch.get("mask")
            loss = cross_entropy_loss(logits, batch["label"], weights=mask)
            return loss, {
                "ce_loss": loss,
                "accuracy": accuracy(logits, batch["label"], weights=mask),
            }

        return criterion

    def build_scheduler(self):
        steps_per_epoch = max(1, len(self.train_dataset) // self.batch_size)
        if self.recipe["optimizer"] == "sgd":
            lr = self.base_lr * self.batch_size / 256.0  # Goyal et al. scaling
        else:
            lr = self.base_lr * self.batch_size / 4096.0  # AdamW convention
        return warmup_cosine_lr(lr, self.max_epoch, steps_per_epoch, warmup_epochs=5)

    def build_optimizer(self, schedule):
        if self.recipe["optimizer"] == "sgd":
            return optax.chain(
                optax.add_decayed_weights(self.recipe["wd"]),
                optax.sgd(schedule, momentum=0.9),
            )
        return optax.adamw(schedule, weight_decay=self.recipe["wd"], b1=0.9, b2=0.999)


if __name__ == "__main__":
    enable_fast_rng()
    Trainer.distributed_setup()
    model_name = os.environ.get("MODEL", "resnet50").lower()
    if model_name not in RECIPES:
        raise SystemExit(f"MODEL={model_name!r}: choose from {sorted(RECIPES)}")
    recipe = RECIPES[model_name]
    save_dir = os.environ.get("SAVE_DIR", f"./runs/{model_name}")
    trainer = ImageNetTrainer(
        model_name=model_name,
        image_size=int(os.environ.get("IMAGE_SIZE", "224")),
        base_lr=float(os.environ.get("BASE_LR", str(recipe["base_lr"]))),
        max_epoch=int(os.environ.get("EPOCHS", "90")),
        batch_size=int(os.environ.get("BATCH", "1024")),
        chain_steps=int(os.environ.get("CHAIN_STEPS", "1")),
        # MESH (the CHAIN_STEPS/DTYPE convention): a mesh spec like
        # "fsdp4x2" or "dp2fsdp2tp2" trains sharded end to end
        # (docs/parallelism.md); unset = the historical pure-DP program.
        mesh=mesh_from_env(),
        # TELEMETRY=1 (mirrors DTYPE/CHAIN_STEPS): telemetry subsystem —
        # docs/observability.md. Unset = historical program.
        telemetry=os.environ.get("TELEMETRY") == "1" or None,
        accum_steps=int(os.environ.get("ACCUM", str(recipe["accum"]))),
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=1,
        save_folder=save_dir,
        snapshot_path=os.environ.get("SNAPSHOT") or None,
        logger=Logger(f"imagenet-{model_name}", os.path.join(save_dir, "logfile.log")),
        profile_dir=os.environ.get("PROFILE_DIR") or None,
    )
    trainer.train()
    Trainer.destroy_process()
