"""Concrete example trainer — capability twin of ``example_trainer.py``.

Binds the framework to VGG16 image-folder classification: implements all nine
hooks with the reference's hyperparameters (VGG16, ``example_trainer.py:51-52``;
cross-entropy criterion, ``:55-58``; SGD lr 0.1 momentum 0.9 wd 1e-4, ``:62``;
MultiStepLR milestones [50, 100, 200] gamma 0.1, ``:66``; train/val
augmentation, via the dataset transforms).

Deliberate fix (SURVEY.md §2e): ``build_val_dataset`` reads ``val_path`` — the
reference validates on its *training* data (``example_trainer.py:48``).
"""

from __future__ import annotations

import optax

from distributed_training_pytorch_tpu.data import (
    ImageFolderDataSource,
    eval_transform,
    train_transform,
)
from distributed_training_pytorch_tpu.models import VGG16
from distributed_training_pytorch_tpu.ops import accuracy, cross_entropy_loss, multistep_lr
from distributed_training_pytorch_tpu.trainer import Trainer


class ExampleTrainer(Trainer):
    # kernel-policy knob (ops/dispatch.py); entries set it from the PALLAS
    # env (pallas_from_env). None = the historical program.
    pallas = None

    def __init__(
        self,
        train_path: str,
        val_path: str,
        labels: list[str],
        height: int,
        width: int,
        **trainer_kwargs,
    ):
        self.train_path = train_path
        self.val_path = val_path
        self.labels = labels
        self.height = height
        self.width = width
        super().__init__(**trainer_kwargs)

    # -- data ---------------------------------------------------------------

    def build_train_dataset(self):
        return ImageFolderDataSource(
            self.train_path,
            self.labels,
            transform=train_transform(self.height, self.width, seed=self.seed),
        )

    def build_val_dataset(self):
        return ImageFolderDataSource(
            self.val_path,
            self.labels,
            transform=eval_transform(self.height, self.width),
        )

    # -- model / objective ----------------------------------------------------

    def build_model(self):
        # VGG16(in_channels=3, out_channels=len(labels), init_weights=True)
        # analog (``example_trainer.py:51-52``); Kaiming init is the model's
        # default initializer. Activations follow the trainer's precision
        # policy (model_dtype is float32 under the default fp32 policy —
        # reference-parity; Trainer(precision="bf16") switches compute to
        # bf16 with fp32 master weights, docs/mixed_precision.md).
        if self.pallas is not None:
            # VGG16 has no fused-kernel coverage — create_model consumes the
            # knob and records the plain no-op (ops/dispatch.py) instead of
            # dropping it silently. The None default keeps the historical
            # constructor path untouched.
            from distributed_training_pytorch_tpu.models import create_model

            return create_model(
                "vgg16", num_classes=len(self.labels),
                dtype=self.model_dtype, pallas=self.pallas,
            )
        return VGG16(num_classes=len(self.labels), dtype=self.model_dtype)

    # mask-weighted metrics below satisfy the padded-validation contract
    # (trainer.validate warns when this is not declared)
    criterion_uses_mask = True

    def build_criterion(self):
        def criterion(logits, batch):
            mask = batch.get("mask")
            loss = cross_entropy_loss(logits, batch["label"], weights=mask)
            return loss, {
                "ce_loss": loss,
                "accuracy": accuracy(logits, batch["label"], weights=mask),
            }

        return criterion

    def build_optimizer(self, schedule):
        # SGD lr=schedule momentum=0.9 weight_decay=1e-4 (``example_trainer.py:62``);
        # decoupled ordering matches torch (wd added to grad before momentum).
        return optax.chain(
            optax.add_decayed_weights(1e-4),
            optax.sgd(schedule, momentum=0.9),
        )

    def build_scheduler(self):
        # MultiStepLR milestones [50, 100, 200] epochs, gamma 0.1
        # (``example_trainer.py:66``) — converted to per-step boundaries.
        # (Datasets are built before this hook, so no re-scan is needed.)
        steps_per_epoch = max(1, len(self.train_dataset) // self.batch_size)
        return multistep_lr(0.1, [50, 100, 200], gamma=0.1, steps_per_epoch=steps_per_epoch)
