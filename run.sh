#!/usr/bin/env bash
# Launcher — capability twin of the reference ``run.sh`` (torchrun + NCCL env,
# run.sh:1-14), rebuilt for TPU pods.
#
# On a single TPU host/slice this is just `./run.sh` — jax discovers every
# local chip and shards over them (no NCCL_* tuning: XLA's latency-hiding
# scheduler owns collective scheduling, SURVEY.md §2d).
#
# On a multi-host pod, run once per host with the coordinator env set —
# the analog of torchrun's --master_addr/--node_rank contract (run.sh:9-14):
#
#   COORDINATOR_ADDRESS=<host0-ip>:1234 NUM_PROCESSES=<n-hosts> PROCESS_ID=<i> ./run.sh
#
# (On Cloud TPU pods these are auto-detected from TPU metadata; the vars are
# only needed for manual rendezvous.)
set -euo pipefail
cd "$(dirname "$0")"

# North-star config (BASELINE.md): VGG16 / CIFAR-10, bf16, DP over all chips.
exec python examples/train_cifar10.py "$@"
