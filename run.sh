#!/usr/bin/env bash
# Launcher — capability twin of the reference ``run.sh`` (torchrun + NCCL env,
# run.sh:1-14), rebuilt for TPU pods.
#
# On a single TPU host/slice this is just `./run.sh` — jax discovers every
# local chip and shards over them (no NCCL_* tuning: XLA's latency-hiding
# scheduler owns collective scheduling, SURVEY.md §2d).
#
# On a multi-host pod, run once per host with the coordinator env set —
# the analog of torchrun's --master_addr/--node_rank contract (run.sh:9-14):
#
#   COORDINATOR_ADDRESS=<host0-ip>:1234 NUM_PROCESSES=<n-hosts> PROCESS_ID=<i> ./run.sh
#
# (On Cloud TPU pods these are auto-detected from TPU metadata; the vars are
# only needed for manual rendezvous.)
set -euo pipefail
cd "$(dirname "$0")"

# MODEL selects the BASELINE config:
#   (unset) / vgg16  -> config 1-2: VGG16 / CIFAR-10 (the north star)
#   digits           -> accuracy run on real data (sklearn digits; offline
#                       CIFAR-10 stand-in — trains, checkpoints, then evals
#                       the saved checkpoint and prints measured top-1)
#   resnet50         -> config 3:   ResNet-50 / ImageNet-1k
#   vit_b16          -> config 4:   ViT-B/16  / ImageNet-1k
#   convnext_l       -> config 5:   ConvNeXt-L / ImageNet-21k (bf16 + grad-accum)
#   lm               -> causal-LM entry (long-context family; LM_SIZE=tiny|small)
MODEL="${MODEL:-vgg16}"
if [ "$MODEL" = "vgg16" ]; then
  exec python examples/train_cifar10.py "$@"
fi
if [ "$MODEL" = "digits" ]; then
  exec python examples/train_digits.py "$@"
fi
if [ "$MODEL" = "lm" ]; then
  exec python examples/train_lm.py "$@"
fi
exec python examples/train_imagenet.py "$@"
