"""Mixed-precision dtype policies (ISSUE 3 tentpole).

The reference trains fp32-everywhere (SURVEY.md §0 "no mixed precision"); on
TPU the MXU's native dtype is bf16, so fp32-everywhere leaves the largest
single-knob perf/memory win unused. A :class:`Policy` names the three dtypes
of the standard mixed-precision recipe (the jmp / t5x convention):

* ``param_dtype``   — what the master weights and optimizer state are stored
  in. Always fp32 in the named presets: the optimizer update happens in full
  precision, so bf16/fp16 rounding never accumulates across steps.
* ``compute_dtype`` — what the forward/backward matmuls run in. The engine
  casts params and float inputs to this dtype at the loss-fn boundary INSIDE
  the compiled step; gradients flow back through the cast and arrive in
  ``param_dtype`` (the cast's transpose accumulates), so the grads/optimizer
  path never sees the low-precision dtype.
* ``output_dtype``  — what the loss is cast to before (scaled) ``jax.grad``
  sees it; fp32 so loss-scale arithmetic and metric accumulation are exact.

The ``"fp32"`` preset is the identity policy: the engine detects it
statically and traces the exact pre-precision program (bit-exactness with
unpoliced runs is test-enforced, ``tests/test_precision.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["Policy", "get_policy", "compute_dtype", "model_dtype_for_entry"]


def _cast_floating(tree: Any, dtype: Any) -> Any:
    """Cast floating leaves to ``dtype``; integer/bool leaves (labels, uint8
    images awaiting on-device normalize) pass through untouched."""

    def cast(x):
        if jnp.issubdtype(getattr(x, "dtype", jnp.int32), jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


@dataclasses.dataclass(frozen=True)
class Policy:
    """``(param_dtype, compute_dtype, output_dtype)`` — see module docstring.

    Hashable and static: the engine branches on :attr:`active` at trace time,
    so the fp32 preset contributes zero ops to the compiled step.
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32
    name: str = "fp32"

    @property
    def active(self) -> bool:
        """True when this policy inserts any cast at the loss-fn boundary."""
        return not (
            self.param_dtype == self.compute_dtype == self.output_dtype == jnp.float32
        )

    def cast_params(self, params: Any) -> Any:
        """Master (``param_dtype``) weights -> ``compute_dtype`` activations'
        view, applied once at the loss-fn boundary. Grads of the uncast params
        come back in ``param_dtype`` through the cast's transpose."""
        return _cast_floating(params, self.compute_dtype)

    def cast_inputs(self, batch: Any) -> Any:
        """Float batch leaves -> ``compute_dtype`` (ints/uint8 untouched)."""
        return _cast_floating(batch, self.compute_dtype)

    def cast_output(self, loss: jax.Array) -> jax.Array:
        return loss.astype(self.output_dtype)


# Named presets. fp16 REQUIRES loss scaling (its ~6e-5..65504 dynamic range
# underflows small gradients without it) — the Trainer ctor enforces that.
_PRESETS = {
    "fp32": Policy(jnp.float32, jnp.float32, jnp.float32, name="fp32"),
    "bf16": Policy(jnp.float32, jnp.bfloat16, jnp.float32, name="bf16"),
    "fp16": Policy(jnp.float32, jnp.float16, jnp.float32, name="fp16"),
}
_ALIASES = {"float32": "fp32", "bfloat16": "bf16", "float16": "fp16", "half": "fp16"}


def get_policy(spec: "str | Policy | None") -> Policy:
    """``None`` | preset name | :class:`Policy` -> :class:`Policy`."""
    if spec is None:
        return _PRESETS["fp32"]
    if isinstance(spec, Policy):
        return spec
    if isinstance(spec, str):
        key = _ALIASES.get(spec.lower(), spec.lower())
        if key in _PRESETS:
            return _PRESETS[key]
        raise ValueError(
            f"unknown precision {spec!r} (choose from {sorted(_PRESETS)} or pass a Policy)"
        )
    raise TypeError(f"precision must be a str, Policy, or None, got {type(spec)}")


def compute_dtype(spec: "str | Policy | None") -> Any:
    """The compute dtype a precision spec names — the dtype to build models
    with (``models/*`` all take ``dtype=``) so model-internal casts agree
    with the policy's boundary casts."""
    return get_policy(spec).compute_dtype


def model_dtype_for_entry(policy, explicit: bool, legacy_dtype=None) -> Any:
    """Model dtype for an example entry with a ``DTYPE`` env knob — ONE
    resolution rule shared by every entry (a per-entry copy once let an
    explicit ``Trainer(precision=...)`` override disagree with the env).

    The trainer's RESOLVED policy wins whenever it is active (bf16/fp16 —
    however it was set, env knob or explicit ctor arg), so the model's
    internal casts always match the engine's boundary casts. Under the
    inactive fp32 policy, ``explicit`` says whether ANYONE asked for fp32
    (env knob set or ``precision=`` passed — ``trainer.precision_requested``)
    — then the model is float32; a fully unset knob keeps ``legacy_dtype``,
    the entry's historical program (bf16 model-internal casts for the
    throughput entries)."""
    policy = get_policy(policy)
    if policy.active:
        return policy.compute_dtype
    if explicit:
        return jnp.float32
    return legacy_dtype if legacy_dtype is not None else jnp.float32
