"""Mixed-precision subsystem (ISSUE 3): dtype policies + loss scaling.

``Policy`` names the (param, compute, output) dtype triple applied at the
loss-fn boundary inside the compiled step (``precision.policy``);
``NoOpScale``/``DynamicScale`` implement loss scaling as pytree state carried
in ``TrainState`` (``precision.loss_scale``). Wire-up: ``Trainer(precision=
"bf16")`` / ``TrainEngine(precision=..., loss_scale=...)``; see
``docs/mixed_precision.md``.
"""

from distributed_training_pytorch_tpu.precision.policy import (  # noqa: F401
    Policy,
    compute_dtype,
    get_policy,
    model_dtype_for_entry,
)
from distributed_training_pytorch_tpu.precision.loss_scale import (  # noqa: F401
    DynamicScale,
    NoOpScale,
    is_dynamic,
    resolve_loss_scale,
)
