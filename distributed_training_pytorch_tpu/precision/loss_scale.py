"""Loss scaling — fp16's gradient-underflow countermeasure, as pytree state.

fp16 grads underflow to zero below ~6e-5; multiplying the loss by a large
scale S shifts the whole gradient distribution up into representable range,
and dividing the grads by S afterwards recovers the true values. Both scale
states here live INSIDE :class:`~distributed_training_pytorch_tpu.train.state.
TrainState` (``state.loss_scale``) so the entire grow/backoff/skip protocol
runs in the compiled step with zero extra host syncs, survives
crash-consistent checkpoint/resume (``checkpoint/manager.py`` serializes it
as its own composite item), and rides through chained windows
(``TrainEngine.train_steps_chained`` carries it in the scan state).

* :class:`NoOpScale` — the identity protocol (bf16/fp32 runs that want the
  scale-state plumbing without the arithmetic). Zero pytree leaves: a state
  carrying it checkpoints identically to one carrying ``None``.
* :class:`DynamicScale` — torch.amp.GradScaler's protocol: on a step with
  non-finite grads the update is SKIPPED and the scale backs off by
  ``backoff_factor``; after ``growth_interval`` consecutive finite steps it
  grows by ``growth_factor``. All factors are powers of two by default, so
  scaling/unscaling is exact in floating point.

The skip itself is the engine's unified non-finite guard — the same
conditional apply ``nan_policy="skip"`` uses — so an overflow-skip and a
nan-skip are ONE event counted once (``metrics["nonfinite"]``), never twice.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

__all__ = ["NoOpScale", "DynamicScale", "is_dynamic", "resolve_loss_scale"]


@struct.dataclass
class NoOpScale:
    """Identity loss scale: no state (zero pytree leaves), no arithmetic."""

    def scale_loss(self, loss: jax.Array) -> jax.Array:
        return loss

    def unscale_grads(self, grads):
        return grads

    def adjust(self, grads_finite: jax.Array) -> "NoOpScale":
        del grads_finite
        return self


@struct.dataclass
class DynamicScale:
    """Dynamic loss scale state (one fp32 + two int32 scalars).

    ``scale``/``growth_counter``/``skipped_steps`` are pytree leaves carried
    in ``TrainState``; the protocol constants are static (part of the jit
    cache key — changing them retraces, which is correct: they are baked
    into the compiled update).

    Build instances with :meth:`create` (canonicalizes the leaves to device
    scalars); ``skipped_steps`` counts overflow-skips cumulatively for
    observability (the Trainer emits it to TensorBoard).
    """

    scale: jax.Array
    growth_counter: jax.Array
    skipped_steps: jax.Array
    growth_interval: int = struct.field(pytree_node=False, default=2000)
    growth_factor: float = struct.field(pytree_node=False, default=2.0)
    backoff_factor: float = struct.field(pytree_node=False, default=0.5)
    min_scale: float = struct.field(pytree_node=False, default=1.0)
    max_scale: float = struct.field(pytree_node=False, default=float(2.0**24))

    @classmethod
    def create(
        cls,
        initial_scale: float = 2.0**15,
        *,
        growth_interval: int = 2000,
        growth_factor: float = 2.0,
        backoff_factor: float = 0.5,
        min_scale: float = 1.0,
        max_scale: float = 2.0**24,
    ) -> "DynamicScale":
        """torch.amp defaults: init 2^15 (the largest power of two below
        fp16's 65504 max — a bigger init would overflow the loss cotangent at
        the output cast before the first backoff could react), x2 growth
        every 2000 clean steps, /2 backoff on overflow."""
        if initial_scale <= 0:
            raise ValueError(f"initial_scale must be > 0, got {initial_scale}")
        return cls(
            scale=jnp.asarray(initial_scale, jnp.float32),
            growth_counter=jnp.asarray(0, jnp.int32),
            skipped_steps=jnp.asarray(0, jnp.int32),
            growth_interval=int(growth_interval),
            growth_factor=float(growth_factor),
            backoff_factor=float(backoff_factor),
            min_scale=float(min_scale),
            max_scale=float(max_scale),
        )

    def scale_loss(self, loss: jax.Array) -> jax.Array:
        return loss * self.scale.astype(loss.dtype)

    def unscale_grads(self, grads):
        inv = 1.0 / self.scale  # powers of two: the reciprocal is exact
        return jax.tree.map(lambda g: g * inv.astype(g.dtype), grads)

    def adjust(self, grads_finite: jax.Array) -> "DynamicScale":
        """One protocol step, fully on device: grow after ``growth_interval``
        consecutive finite steps, back off (and count the skip) on overflow."""
        finite = grads_finite.astype(jnp.bool_)
        counter = self.growth_counter + 1
        grow = finite & (counter >= self.growth_interval)
        new_scale = jnp.where(
            finite,
            jnp.where(
                grow,
                jnp.minimum(self.scale * self.growth_factor, self.max_scale),
                self.scale,
            ),
            jnp.maximum(self.scale * self.backoff_factor, self.min_scale),
        )
        new_counter = jnp.where(grow | ~finite, 0, counter).astype(jnp.int32)
        new_skipped = self.skipped_steps + jnp.where(finite, 0, 1).astype(jnp.int32)
        return self.replace(
            scale=new_scale, growth_counter=new_counter, skipped_steps=new_skipped
        )


def is_dynamic(scale_state) -> bool:
    """Static (trace-time) test the engine branches on: only a DynamicScale
    carries scale arithmetic and the grow/backoff update into the step."""
    return isinstance(scale_state, DynamicScale)


def resolve_loss_scale(spec, policy):
    """Trainer-knob resolution: ``None`` = auto (dynamic iff the policy
    computes in fp16), ``"dynamic"``/``"none"`` by name, or an instance."""
    if spec is None:
        if policy.compute_dtype == jnp.float16:
            return DynamicScale.create()
        return None
    if isinstance(spec, str):
        key = spec.lower()
        if key == "dynamic":
            return DynamicScale.create()
        if key in ("none", "noop", "no_op"):
            return NoOpScale()
        raise ValueError(
            f"unknown loss_scale {spec!r} (use 'dynamic', 'none', None, or an instance)"
        )
    if isinstance(spec, (NoOpScale, DynamicScale)):
        return spec
    raise TypeError(
        f"loss_scale must be a str, NoOpScale, DynamicScale, or None, got {type(spec)}"
    )
