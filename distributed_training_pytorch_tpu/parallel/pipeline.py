"""Pipeline parallelism — single-program collective-permute schedules.

Not present in the reference (its only strategy is DDP data parallelism,
``trainer/trainer.py:52``); built TPU-first to complete the parallelism matrix
(dp / fsdp / tp / sp / pp / ep). The design is the single-program collective-
permute pipeline (the TPU-idiomatic formulation — no per-stage processes, no
send/recv threads as in GPU PP runtimes):

* the mesh gets a ``pipe`` axis of size ``S``; the trunk is a stack of
  ``S * n_virtual`` homogeneous *virtual* stages, virtual stage ``k`` living
  on device ``k % S`` (``n_virtual`` chunks per device — the Megatron-style
  interleaved placement). Stage parameters are one stacked
  ``[S * n_virtual, ...]`` pytree sharded so each device holds its chunks;
* one jitted program runs ``n_micro * n_virtual + S - 1`` ticks of a
  ``lax.scan``; each tick every device applies one virtual stage to its
  current activation and passes the result to its ring successor with a
  single ``lax.ppermute`` — XLA overlaps the permute with the next tick's
  compute. Chunk transitions (…device S-1 chunk c -> device 0 chunk c+1…)
  ride the same ring edge, so interleaving adds no new communication
  patterns;
* the classic pipeline bubble shrinks from GPipe's ``(S-1)/(M+S-1)`` to
  ``((S-1)/v) / (M + (S-1)/v)`` with ``v = n_virtual`` chunks per device
  (each tick now costs ``1/v`` of a device's layer budget) — see
  :func:`bubble_fraction`; a schedule test asserts the v=2 bubble beats
  GPipe at M=8/S=4;
* microbatches are *sharded* over the ``pipe`` axis (device ``d`` holds the
  feed for microbatches ``m % S == d``) and delivered to stage 0 just in
  time through a one-slot rotating ring buffer — per-device feed memory is
  ``M/S`` microbatches and per-tick feed traffic is one microbatch, the same
  order as the activation ring itself. ``feed="replicated"`` keeps the old
  broadcast feed for microbatch counts not divisible by ``S``;
* heterogeneous ends: ``first=(params, fn)`` (e.g. an embedding) runs over
  the feed shards *before* the ring — data-parallel across the pipe group,
  not replicated — and ``last=(params, fn)`` (e.g. the LM head) runs over a
  ``psum_scatter`` of the emitted outputs, again ``1/S`` of the work per
  device. ``embed -> blocks -> head`` therefore pipelines in one call;
* autodiff through the scan + ppermute yields the reverse-schedule backward
  for free; ``remat=True`` wraps each stage application in
  ``jax.checkpoint`` so the backward recomputes stage activations instead of
  stashing every tick's residuals (the memory lever 1F1B buys on GPU
  runtimes, expressed the XLA way).

Composability: the ``pipe`` axis is orthogonal to ``data``/``tensor``/``seq``,
so each stage body may itself be data-parallel or TP-sharded.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# Partial-manual shard_map (`axis_names`): the compat shim maps it onto the
# old experimental API's complementary `auto=` parameter on pre-0.6 JAX.
from distributed_training_pytorch_tpu.compat import pcast, shard_map

from distributed_training_pytorch_tpu.parallel.mesh import PIPE_AXIS

__all__ = [
    "PIPE_AXIS",
    "pipeline_apply",
    "stack_stage_params",
    "bubble_fraction",
    "schedule_stats",
]


def stack_stage_params(params_list) -> Any:
    """Stack per-stage parameter pytrees into one ``[n_stages, ...]`` pytree
    (what :func:`pipeline_apply` consumes; shard the leading axis over
    ``pipe``). With ``n_virtual > 1`` pass all ``S * n_virtual`` virtual
    stages in network order — virtual stage ``k`` is chunk ``k // S`` on
    device ``k % S``."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def bubble_fraction(n_micro: int, n_stages: int, n_virtual: int = 1) -> float:
    """Idle fraction of the schedule: ``1 - useful_ticks / total_ticks``.

    Every device is busy for exactly ``n_micro * n_virtual`` of the
    ``n_micro * n_virtual + n_stages - 1`` ticks, and with ``v`` chunks per
    device a tick costs ``1/v`` of the per-device layer budget — so in
    stage-time units the bubble is ``((S-1)/v) / (M + (S-1)/v)``, GPipe's
    ``(S-1)/(M+S-1)`` at ``v=1``, strictly smaller for ``v>1``.
    """
    total = n_micro * n_virtual + n_stages - 1
    return 1.0 - (n_micro * n_virtual) / total


def schedule_stats(n_micro: int, n_stages: int, n_virtual: int = 1) -> dict:
    """Count the tick grid (device x tick) of the schedule — the *measured*
    counterpart of :func:`bubble_fraction` (the two must agree; tested).

    Simulates the same activation logic as the compiled program: device ``d``
    is active at tick ``t`` iff ``0 <= t - d < n_micro * n_virtual``.
    """
    M, S, v = n_micro, n_stages, n_virtual
    total_ticks = M * v + S - 1
    active = sum(
        1 for d in range(S) for t in range(total_ticks) if 0 <= t - d < M * v
    )
    total = S * total_ticks
    return {
        "total_ticks": total_ticks,
        "device_ticks": total,
        "active_device_ticks": active,
        "bubble_fraction": 1.0 - active / total,
    }


def _identity_end(params, x):
    return x


def pipeline_apply(
    stage_params: Any,
    microbatches: jax.Array,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    *,
    axis: str = PIPE_AXIS,
    n_virtual: int = 1,
    feed: str = "auto",
    first: tuple[Any, Callable] | None = None,
    last: tuple[Any, Callable] | None = None,
    remat: bool = False,
    extra_manual_axes: tuple[str, ...] = (),
    stage_param_specs: Any | None = None,
) -> jax.Array:
    """Run ``microbatches`` through the pipelined (virtual-)stage stack.

    Args:
      stage_params: pytree whose leaves lead with ``[S * n_virtual, ...]``
        (``S = mesh.shape[axis]``), virtual stage ``k`` = chunk ``k // S`` on
        device ``k % S``.
      microbatches: ``[n_micro, micro_batch, ...]`` inputs for the first
        stage (token ids / images when ``first`` is given, else trunk
        activations).
      stage_fn: ``(stage_params_slice, x) -> y`` with ``y.shape == x.shape``
        (homogeneous trunk — activation shapes can't change across a ring).
      mesh: mesh containing ``axis``. Note ``create_mesh`` builds canonical
        axes only (``mesh.AXIS_ORDER``); a non-canonical ``axis`` name needs
        a hand-built ``jax.sharding.Mesh``.
      n_virtual: chunks per device (Megatron-style interleaving); ``> 1``
        requires ``n_micro % S == 0`` and shrinks the bubble (see
        :func:`bubble_fraction`).
      feed: ``"sharded"`` (microbatch feed sharded over ``axis``; needs
        ``n_micro % S == 0``), ``"replicated"``, or ``"auto"`` (sharded when
        divisible).
      first: optional ``(params, fn)`` applied to each feed microbatch before
        the ring (embedding et al.) — runs sharded over the pipe group under
        ``feed="sharded"``; with a replicated feed every device applies it to
        every microbatch (S-fold redundant, like any replicated compute).
        ``fn(params, mb) -> x0`` may change the trailing shape; all ring
        activations take ``x0``'s shape.
      last: optional ``(params, fn)`` applied to each emitted output after
        the ring, sharded over the pipe group when ``n_micro % S == 0``
        (LM head et al.).
      remat: wrap each stage application in ``jax.checkpoint`` — backward
        recomputes stage activations instead of stashing every tick's
        residuals (activation-memory lever; schedule unchanged).
      extra_manual_axes: additional mesh axes made MANUAL inside the ring
        region (e.g. ``("expert",)``). Nested ``shard_map`` is rejected by
        Shardy ("axis already bound by a parent manual_computation"), so a
        stage body that needs hand-written collectives over another axis —
        the ``moe.manual_expert_ffn_local`` workaround for the
        data x expert x pipe GSPMD CHECK — declares that axis here and uses
        ``jax.lax.psum``/``all_to_all`` over it directly. Activations are
        treated as replicated over these axes; stage params shard per
        ``stage_param_specs``.
      stage_param_specs: pytree matching ONE stage's params whose leaves are
        ``PartitionSpec``s over the non-stage dims (e.g. ``P("expert")`` for
        a ``[E, d, h]`` expert slab, ``P()`` for replicated leaves). Required
        exactly when ``extra_manual_axes`` shards any stage param; the stage
        fn then receives LOCAL slabs.

    Returns ``[n_micro, micro_batch, ...]`` outputs of the last virtual
    stage (after ``last`` if given), replicated over ``axis``.
    Differentiable (reverse pipeline via autodiff).
    """
    S = mesh.shape[axis]
    v = int(n_virtual)
    if v < 1:
        raise ValueError(f"n_virtual must be >= 1, got {v}")
    M = microbatches.shape[0]
    if M < 1:
        raise ValueError("need at least one microbatch")
    VS = S * v
    lead = jax.tree.leaves(stage_params)[0].shape[0]
    if lead != VS:
        raise ValueError(
            f"stage_params lead with {lead} stages but mesh axis {axis!r} "
            f"has {S} devices x {v} virtual chunks = {VS}"
        )
    if v > 1 and M % S:
        raise ValueError(
            f"interleaved schedule (n_virtual={v}) needs n_micro % {S} == 0, "
            f"got n_micro={M} — the chunk round-robin advances in groups of S"
        )
    if feed == "auto":
        feed = "sharded" if M % S == 0 else "replicated"
    if feed not in ("sharded", "replicated"):
        raise ValueError(f"feed must be sharded/replicated/auto, got {feed!r}")
    if feed == "sharded" and M % S:
        raise ValueError(f"sharded feed needs n_micro % {S} == 0, got {M}")

    first_params, first_fn = first if first is not None else ({}, _identity_end)
    last_params, last_fn = last if last is not None else ({}, _identity_end)
    sfn = jax.checkpoint(stage_fn) if remat else stage_fn
    T = M * v + S - 1
    Mq = M // S  # feed rows per device (sharded mode)

    # Reshape stacked params [VS, ...] -> [v, S, ...] so P(None, axis) lands
    # chunk c of device d at leaf[c, 0] — virtual stage c*S + d, matching the
    # placement contract in the docstring.
    chunked = jax.tree.map(lambda x: x.reshape((v, S) + x.shape[1:]), stage_params)
    if feed == "sharded":
        # Strided layout: row [q, d] is microbatch q*S + d, so the rotating
        # one-slot feed ring below always finds microbatch m on device m % S.
        micro_in = microbatches.reshape((Mq, S) + microbatches.shape[1:])
        micro_spec = P(None, axis)
    else:
        micro_in = microbatches
        micro_spec = P()

    def body(local_chunks, local_micro, first_p, last_p):
        # Inside shard_map: local_chunks leaves are [v, 1, ...] (this device's
        # chunks); local_micro is [Mq, 1, mb, ...] (sharded) or [M, mb, ...]
        # (replicated).
        chunks = jax.tree.map(lambda x: x[:, 0], local_chunks)
        d = jax.lax.axis_index(axis)
        is_first = d == 0
        is_last = d == S - 1
        ring = [(i, (i + 1) % S) for i in range(S)]  # activation: d -> d+1
        feed_ring = [(i, (i - 1) % S) for i in range(S)]  # feed slot: d -> d-1

        if feed == "sharded":
            local_feed = jax.vmap(lambda m: first_fn(first_p, m))(local_micro[:, 0])
        else:
            local_feed = jax.vmap(lambda m: first_fn(first_p, m))(local_micro)
        act_shape = local_feed.shape[1:]
        act_dtype = local_feed.dtype

        def tick(carry, t):
            ring_in, slot, outputs = carry
            if feed == "sharded":
                # Refill every S ticks: device d loads the feed that must
                # reach device 0 at tick t+d (locally resident exactly then),
                # and the one-slot ring rotates it one hop per tick.
                qidx = jnp.clip((t + d) // VS, 0, Mq - 1)
                refill = jax.lax.dynamic_index_in_dim(local_feed, qidx, 0, keepdims=False)
                slot = jnp.where(t % S == 0, refill, slot)
                feed_now = slot
            else:
                m_t = (t // VS) * S + t % S  # device 0's feed schedule
                feed_now = jax.lax.dynamic_index_in_dim(
                    local_feed, jnp.clip(m_t, 0, M - 1), 0, keepdims=False
                )

            # Device-local schedule: active for M*v consecutive ticks from
            # t = d; chunk round-robin advances every S ticks.
            tau = t - d
            c = jnp.clip(tau // S, 0, M * v - 1) % v
            params_c = jax.tree.map(
                lambda x: jax.lax.dynamic_index_in_dim(x, c, 0, keepdims=False), chunks
            )
            use_feed = jnp.logical_and(is_first, c == 0)
            x = jnp.where(use_feed, feed_now, ring_in)
            y = sfn(params_c, x)

            # Device S-1, chunk v-1 emits microbatch m = (e//VS)*S + e%VS at
            # e = t - (VS - 1); the strided residency means e%VS < S exactly
            # on emission ticks.
            e = t - (VS - 1)
            r = jnp.clip(e, 0, M * v - 1) % VS
            m_out = (jnp.clip(e, 0, M * v - 1) // VS) * S + r
            emit = jnp.logical_and(
                is_last, jnp.logical_and(e >= 0, jnp.logical_and(r < S, m_out < M))
            )
            idx = jnp.clip(m_out, 0, M - 1)
            cur = jax.lax.dynamic_slice_in_dim(outputs, idx, 1, 0)
            outputs = jax.lax.dynamic_update_slice_in_dim(
                outputs, jnp.where(emit, y[None], cur), idx, 0
            )

            sent = jax.lax.ppermute(y, axis, ring)
            if feed == "sharded":
                slot = jax.lax.ppermute(slot, axis, feed_ring)
            return (sent, slot, outputs), None

        # pcast-to-varying: the carry becomes device-varying after one tick
        # (each stage holds different activations), so the init must carry the
        # same varying-over-`axis` type or scan rejects the carry signature.
        def _vary(x):
            return pcast(x, axis, to="varying")

        init = (
            _vary(jnp.zeros(act_shape, act_dtype)),
            _vary(jnp.zeros(act_shape, act_dtype)),
            _vary(jnp.zeros((M,) + act_shape, act_dtype)),
        )
        (_, _, outputs), _ = jax.lax.scan(tick, init, jnp.arange(T))

        # Valid only on the last device; zero elsewhere so the psum below (or
        # the psum_scatter in the sharded-head path) recovers them exactly.
        outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
        if last is not None and M % S == 0:
            # Sharded head: scatter the emitted outputs over the pipe group
            # (only the last device contributes, so the sum IS its value) and
            # apply `last` to M/S microbatches per device. The result stays
            # sharded — out_specs reassembles it without an in-body gather.
            mine = jax.lax.psum_scatter(
                outputs.reshape((Mq, S) + outputs.shape[1:]),
                axis,
                scatter_dimension=1,
                tiled=False,
            )
            done = jax.vmap(lambda m: last_fn(last_p, m))(mine)
            return done[:, None]  # [Mq, 1(sharded->S), mb, ...]
        outputs = jax.lax.psum(outputs, axis)
        if last is not None:
            outputs = jax.vmap(lambda m: last_fn(last_p, m))(outputs)
        return outputs

    sharded_head = last is not None and M % S == 0
    if stage_param_specs is not None:
        chunk_specs = jax.tree.map(
            lambda spec: P(None, axis, *spec), stage_param_specs
        )
    else:
        chunk_specs = jax.tree.map(lambda _: P(None, axis), chunked)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(chunk_specs, micro_spec, P(), P()),
        # Plain path: the closing psum establishes replication. Sharded-head
        # path: outputs stay sharded over `axis` on dim 1, reassembled below.
        out_specs=P(None, axis) if sharded_head else P(),
        # Manual over the pipe axis ONLY (plus any extra_manual_axes a stage
        # body needs hand-written collectives over): every other mesh axis
        # stays automatic, so stage bodies compose with the rest of the
        # matrix — activations sharded over `data`, MoE weights over
        # `expert`, TP over `model` — with GSPMD inserting those collectives
        # inside each tick while the ring ppermute stays hand-scheduled. On a
        # pipe-only mesh this is identical to full manual.
        axis_names=frozenset({axis, *extra_manual_axes}),
    )
    out = fn(chunked, micro_in, first_params, last_params)
    if sharded_head:
        # [Mq, S, mb, ...] with row [q, r] = microbatch q*S + r.
        out = out.reshape((M,) + out.shape[2:])
    return out
