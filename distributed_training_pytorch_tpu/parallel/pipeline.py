"""Pipeline parallelism — GPipe-style microbatch schedule over a ``pipe`` axis.

Not present in the reference (its only strategy is DDP data parallelism,
``trainer/trainer.py:52``); built TPU-first to complete the parallelism matrix
(dp / fsdp / tp / sp / pp / ep). The design is the single-program collective-
permute pipeline (the TPU-idiomatic formulation — no per-stage processes, no
send/recv threads as in GPU PP runtimes):

* the mesh gets a ``pipe`` axis; stage ``s`` of a stack of homogeneous stages
  lives on the devices with ``axis_index(pipe) == s`` — stage parameters are
  simply a stacked ``[n_stages, ...]`` pytree sharded on its leading axis;
* one jitted program runs ``n_micro + n_stages - 1`` ticks of a ``lax.scan``;
  each tick every stage applies itself to its current activation and passes
  the result to its successor with a single ``lax.ppermute`` ring shift —
  XLA overlaps the permute with the next tick's compute;
* the classic pipeline "bubble" appears as masked ticks at the ends; autodiff
  through the scan + ppermute yields the reverse-schedule backward for free.

Composability: the ``pipe`` axis is orthogonal to ``data``/``tensor``/``seq``,
so each stage body may itself be data-parallel or TP-sharded. Stages must be
*homogeneous* (same function, stacked params) — the standard constraint of
SPMD pipelining; put distinct embed/head layers outside the pipelined trunk.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 ships shard_map at top level; the experimental path warns
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

PIPE_AXIS = "pipe"

__all__ = ["PIPE_AXIS", "pipeline_apply", "stack_stage_params"]


def stack_stage_params(params_list) -> Any:
    """Stack per-stage parameter pytrees into one ``[n_stages, ...]`` pytree
    (what :func:`pipeline_apply` consumes; shard the leading axis over
    ``pipe``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(
    stage_params: Any,
    microbatches: jax.Array,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    *,
    axis: str = PIPE_AXIS,
) -> jax.Array:
    """Run ``microbatches`` through the pipelined stage stack.

    Args:
      stage_params: pytree whose leaves lead with ``[n_stages, ...]``; sharded
        (or shardable) over the mesh's ``axis``.
      microbatches: ``[n_micro, micro_batch, ...]`` activations for stage 0.
      stage_fn: ``(stage_params_slice, x) -> y`` with ``y.shape == x.shape``
        (homogeneous stages — activation shapes can't change across a ring).
      mesh: mesh containing ``axis``.

    Returns ``[n_micro, micro_batch, ...]`` outputs of the last stage,
    replicated over ``axis``. Differentiable (reverse pipeline via autodiff).
    """
    n_stages = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    if n_micro < 1:
        raise ValueError("need at least one microbatch")
    first = jax.tree.leaves(stage_params)[0]
    if first.shape[0] != n_stages:
        raise ValueError(
            f"stage_params lead with {first.shape[0]} stages but mesh axis "
            f"{axis!r} has {n_stages} devices"
        )

    def body(local_params, micro):
        # Inside shard_map: local_params leaves are [1, ...] (this stage's
        # slice); micro is the full [n_micro, mb, ...] (replicated on `axis`).
        params = jax.tree.map(lambda x: x[0], local_params)
        stage = jax.lax.axis_index(axis)
        is_first = stage == 0
        is_last = stage == n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            inbuf, outputs = carry
            # Stage 0 ingests microbatch t (clamped in the drain phase);
            # other stages consume what their predecessor sent last tick.
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            feed = jax.lax.dynamic_index_in_dim(micro, feed_idx, 0, keepdims=False)
            x = jnp.where(is_first, feed, inbuf)
            y = stage_fn(params, x)
            # Last stage emits microbatch t - (n_stages - 1).
            out_idx = t - (n_stages - 1)
            write = jnp.logical_and(is_last, jnp.logical_and(out_idx >= 0, out_idx < n_micro))
            idx = jnp.clip(out_idx, 0, n_micro - 1)
            cur = jax.lax.dynamic_slice_in_dim(outputs, idx, 1, 0)
            outputs = jax.lax.dynamic_update_slice_in_dim(
                outputs, jnp.where(write, y[None], cur), idx, 0
            )
            # Ring-shift activations to the successor stage.
            sent = jax.lax.ppermute(y, axis, perm)
            return (sent, outputs), None

        # pcast-to-varying: the carry becomes device-varying after one tick
        # (each stage holds different activations), so the init must carry the
        # same varying-over-`axis` type or scan rejects the carry signature.
        def _vary(x):
            return jax.lax.pcast(x, axis, to="varying")

        init = (
            _vary(jnp.zeros(micro.shape[1:], micro.dtype)),
            _vary(jnp.zeros_like(micro)),
        )
        (_, outputs), _ = jax.lax.scan(
            tick, init, jnp.arange(n_micro + n_stages - 1)
        )
        # Valid only on the last stage; replicate across the pipe axis.
        outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
        return jax.lax.psum(outputs, axis)

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),  # the closing psum establishes replication over `axis`
    )
    return fn(stage_params, microbatches)
