"""Expert parallelism — a GShard-style Mixture-of-Experts FFN layer.

Not in the reference (data parallelism is its only strategy); built to
complete the parallelism matrix (dp / fsdp / tp / sp / pp / ep) the TPU way:
no per-expert processes or host-side routing — the layer is ordinary jittable
einsum algebra over an experts dimension, and *expert parallelism is purely a
sharding annotation*: stacked expert weights ``[E, ...]`` and the dispatched
``[E, capacity, d]`` activations carry ``PartitionSpec('expert', ...)``, and
XLA's SPMD partitioner inserts the all-to-all between the token-sharded and
expert-sharded layouts (the GShard formulation).

Routing: top-k softmax gating with fixed per-expert capacity. Tokens beyond
an expert's capacity are dropped for that choice (their other choice and the
residual path still carry them) — deterministic, order-based priority, first
choice before second. ``capacity_factor`` sizes the buffers.

Aux losses follow Switch/GShard: ``load_balance_loss`` (mean gate fraction x
mean dispatch fraction per expert, scaled by E) and ``router_z_loss``.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import PartitionSpec as P

EXPERT_AXIS = "expert"

__all__ = ["EXPERT_AXIS", "MoEMlp", "load_balance_loss", "router_z_loss"]


def _constrain(x: jax.Array, spec: P) -> jax.Array:
    """Sharding constraint that is a no-op outside jit / without a mesh."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x


def router_z_loss(logits: jax.Array) -> jax.Array:
    """Encourages small router logits (numerical health; ST-MoE eq. 5)."""
    z = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    return jnp.mean(z**2)


def load_balance_loss(gates: jax.Array, dispatch_mask: jax.Array) -> jax.Array:
    """Switch-Transformer load-balance loss: E * sum_e f_e * p_e where f_e is
    the fraction of tokens dispatched to expert e (first choice) and p_e the
    mean gate probability."""
    num_experts = gates.shape[-1]
    f = jnp.mean(dispatch_mask.astype(jnp.float32), axis=0)  # [E]
    p = jnp.mean(gates.astype(jnp.float32), axis=0)  # [E]
    return num_experts * jnp.sum(f * p)


class MoEMlp(nn.Module):
    """Mixture-of-experts FFN: ``[..., d] -> [..., d]``.

    Attributes:
      num_experts: E, ideally a multiple of the mesh's ``expert`` axis size.
      hidden_dim: per-expert FFN hidden width.
      top_k: experts per token (1 = Switch, 2 = GShard default).
      capacity_factor: per-expert buffer = ceil(tokens * top_k / E * factor).
      dtype: activation dtype (params stay float32).

    Sow'd metrics (``.sow('intermediates', ...)``): ``load_balance_loss`` and
    ``router_z_loss`` — add them to the training objective via the criterion.
    """

    num_experts: int
    hidden_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        orig_shape = x.shape
        d = orig_shape[-1]
        tokens = x.reshape(-1, d)  # [S, d]
        s = tokens.shape[0]
        e = self.num_experts
        capacity = max(1, int(np.ceil(s * self.top_k / e * self.capacity_factor)))

        # --- router (float32 for stable softmax) ---------------------------
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32)
        )  # [S, E]
        gates = jax.nn.softmax(logits, axis=-1)

        # --- top-k choice with order-based capacity assignment -------------
        # Process choices in priority order: choice 0 of every token claims
        # capacity before any choice 1 (GShard's policy), so dropping is
        # deterministic and independent of later choices.
        remaining = gates
        dispatch = jnp.zeros((s, e, capacity), jnp.bool_)
        combine = jnp.zeros((s, e, capacity), jnp.float32)
        used = jnp.zeros((e,), jnp.int32)  # slots claimed so far per expert
        gate_sum = jnp.zeros((s,), jnp.float32)
        first_choice_mask = None
        for _ in range(self.top_k):
            choice = jnp.argmax(remaining, axis=-1)  # [S]
            onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)  # [S, E]
            if first_choice_mask is None:
                first_choice_mask = onehot
            # Position of each token within its chosen expert's buffer.
            pos_in_expert = jnp.cumsum(onehot, axis=0) - onehot  # [S, E]
            pos = jnp.sum(pos_in_expert * onehot, axis=-1) + used[choice]  # [S]
            keep = pos < capacity
            gate = jnp.sum(gates * onehot, axis=-1) * keep  # [S]
            slot = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity, dtype=jnp.float32)
            contrib = onehot[:, :, None].astype(jnp.float32) * slot[:, None, :]
            contrib = contrib * keep[:, None, None]
            dispatch = jnp.logical_or(dispatch, contrib > 0)
            combine = combine + gate[:, None, None] * contrib
            gate_sum = gate_sum + gate
            used = used + jnp.sum(onehot * keep[:, None], axis=0)
            remaining = remaining * (1.0 - onehot)  # mask the taken expert

        # Renormalize kept gates (standard top-k MoE: weights sum to 1 over
        # the token's surviving choices).
        combine = combine / jnp.maximum(gate_sum, 1e-9)[:, None, None]

        self.sow(
            "intermediates",
            "load_balance_loss",
            load_balance_loss(gates, first_choice_mask),
        )
        self.sow("intermediates", "router_z_loss", router_z_loss(logits))

        # --- expert computation (expert-sharded) ---------------------------
        w_in = self.param(
            "w_in",
            nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal"),
            (e, d, self.hidden_dim),
            jnp.float32,
        )
        w_out = self.param(
            "w_out",
            nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal"),
            (e, self.hidden_dim, d),
            jnp.float32,
        )
        w_in = _constrain(w_in, P(EXPERT_AXIS)).astype(self.dtype)
        w_out = _constrain(w_out, P(EXPERT_AXIS)).astype(self.dtype)

        # dispatch: [S, E, C] x [S, d] -> [E, C, d]; the resharding from
        # token-sharded to expert-sharded IS the all-to-all.
        expert_in = jnp.einsum(
            "sec,sd->ecd", dispatch.astype(self.dtype), tokens.astype(self.dtype)
        )
        expert_in = _constrain(expert_in, P(EXPERT_AXIS))
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, w_in))
        expert_out = jnp.einsum("ech,ehd->ecd", h, w_out)
        expert_out = _constrain(expert_out, P(EXPERT_AXIS))

        out = jnp.einsum("sec,ecd->sd", combine.astype(self.dtype), expert_out)
        return out.reshape(orig_shape).astype(self.dtype)
