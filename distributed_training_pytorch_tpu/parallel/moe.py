"""Expert parallelism — a GShard-style Mixture-of-Experts FFN layer.

Not in the reference (data parallelism is its only strategy); built to
complete the parallelism matrix (dp / fsdp / tp / sp / pp / ep) the TPU way:
no per-expert processes or host-side routing — the layer is ordinary jittable
einsum algebra over an experts dimension, and *expert parallelism is purely a
sharding annotation*: stacked expert weights ``[E, ...]`` and the dispatched
``[E, capacity, d]`` activations carry ``PartitionSpec('expert', ...)``, and
XLA's SPMD partitioner inserts the all-to-all between the token-sharded and
expert-sharded layouts (the GShard formulation).

Routing: top-k softmax gating with fixed per-expert capacity. Tokens beyond
an expert's capacity are dropped for that choice (their other choice and the
residual path still carry them) — deterministic, order-based priority, first
choice before second. ``capacity_factor`` sizes the buffers.

Two dispatch implementations with identical routing semantics (parity-tested):

* ``dispatch_impl="einsum"`` — GShard one-hot dispatch/combine tensors
  ``[S/G, E, C]``; O((S/G)^2)-ish construction per group, all dense algebra.
  Best at small group sizes (the one-hots stay tiny and everything fuses).
* ``dispatch_impl="sort"`` — argsort/cummax ranking + scatter-add into the
  ``[E, C, d]`` buffers and gather back; memory and compute O(S·k + E·C·d)
  per group, no quadratic one-hots. Best at large group sizes. The measured
  single-chip crossover is recorded in BASELINE.md (``bench.py`` moe mode).
* ``dispatch_impl="auto"`` (default) — picks per call site from the static
  group size: ``sort`` at >= :data:`SORT_DISPATCH_MIN_GROUP` tokens/group
  (the measured ~4k crossover), ``einsum`` below. Group size is shape-derived,
  so the choice is made at trace time — no runtime branch under jit.

Inference: ``__call__(x, decode=True)`` routes capacity-free — every token
computes its top-k experts by direct weight gather (no buffers, no drops), the
standard MoE decode policy; identical parameters, so training checkpoints
serve decode unchanged.

Aux losses follow Switch/GShard: ``load_balance_loss`` (mean gate fraction x
mean dispatch fraction per expert, scaled by E) and ``router_z_loss``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from distributed_training_pytorch_tpu import compat
from distributed_training_pytorch_tpu.parallel.mesh import DATA_AXIS, EXPERT_AXIS

__all__ = [
    "EXPERT_AXIS",
    "MoEMlp",
    "SORT_DISPATCH_MIN_GROUP",
    "load_balance_loss",
    "manual_expert_ffn_local",
    "manual_expert_mlp",
    "router_z_loss",
]

# Measured einsum/sort crossover (single v5e chip, fwd+bwd, E=8 k=2 d=512
# h=1024 bf16 — BASELINE.md "MoE dispatch crossover"): einsum wins at 1k
# tokens/group (20.4 vs 23.1 ms), ties at 4k, loses 2x at 16k (40.4 vs
# 20.3 ms). "auto" flips to sort at this group size.
SORT_DISPATCH_MIN_GROUP = 4096


def _constrain(x: jax.Array, axes: tuple, *, activation: bool = False) -> jax.Array:
    """Constrain dims to mesh axes, skipping axes the ambient mesh lacks.

    No ambient mesh (plain apply outside jit, tests) -> no-op. With a mesh,
    genuine spec errors (e.g. expert count not divisible by the axis) DO
    propagate — silently dropping the constraint would run fully replicated
    while the user believes expert parallelism is active.

    ``activation=True`` marks dispatch/combine activation constraints, which
    are belt-and-braces: the expert-sharded WEIGHT constraints alone already
    make GSPMD shard the expert einsums. Inside a partial-manual region (a
    ``shard_map`` manual over e.g. ``pipe``, as ``pipeline_apply`` builds),
    activation constraints trip an XLA SPMD-partitioner CHECK
    (spmd_partitioner_util.cc "partition_group_list ... num_devices_per_group",
    bisected on jax 0.9/CPU) — so they are skipped there, and expert layout
    flows from the weights."""
    mesh = compat.get_abstract_mesh()
    mesh_axes = getattr(mesh, "axis_names", ()) if mesh is not None else ()
    if not mesh_axes:
        return x
    if activation and compat.manual_axes_of(mesh):
        return x
    spec = P(*[a if (a is not None and a in mesh_axes) else None for a in axes])
    return jax.lax.with_sharding_constraint(x, spec)


def router_z_loss(logits: jax.Array) -> jax.Array:
    """Encourages small router logits (numerical health; ST-MoE eq. 5)."""
    z = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    return jnp.mean(z**2)


def load_balance_loss(gates: jax.Array, dispatch_mask: jax.Array) -> jax.Array:
    """Switch-Transformer load-balance loss: E * sum_e f_e * p_e where f_e is
    the fraction of tokens dispatched to expert e (first choice) and p_e the
    mean gate probability."""
    num_experts = gates.shape[-1]
    f = jnp.mean(dispatch_mask.astype(jnp.float32), axis=0)  # [E]
    p = jnp.mean(gates.astype(jnp.float32), axis=0)  # [E]
    return num_experts * jnp.sum(f * p)


def _route_group(group_gates, *, num_experts, capacity, top_k):
    """GShard order-based-capacity top-k routing for ONE group:
    ``[sg, E]`` gates -> ``(dispatch, combine, first_choice)`` with
    dispatch/combine ``[sg, E, C]``. Choices claim capacity in priority
    order (choice 0 of every token before any choice 1), so dropping is
    deterministic; kept gates renormalize to sum 1 per token."""
    e, sg = num_experts, group_gates.shape[0]
    remaining = group_gates
    dispatch = jnp.zeros((sg, e, capacity), jnp.bool_)
    combine = jnp.zeros((sg, e, capacity), jnp.float32)
    used = jnp.zeros((e,), jnp.int32)
    gate_sum = jnp.zeros((sg,), jnp.float32)
    first_choice = None
    for _ in range(top_k):
        choice = jnp.argmax(remaining, axis=-1)  # [sg]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)  # [sg, E]
        if first_choice is None:
            first_choice = onehot
        pos_in_expert = jnp.cumsum(onehot, axis=0) - onehot  # [sg, E]
        pos = jnp.sum(pos_in_expert * onehot, axis=-1) + used[choice]
        keep = pos < capacity
        gate = jnp.sum(group_gates * onehot, axis=-1) * keep
        slot = jax.nn.one_hot(
            jnp.clip(pos, 0, capacity - 1), capacity, dtype=jnp.float32
        )
        contrib = onehot[:, :, None].astype(jnp.float32) * slot[:, None, :]
        contrib = contrib * keep[:, None, None]
        dispatch = jnp.logical_or(dispatch, contrib > 0)
        combine = combine + gate[:, None, None] * contrib
        gate_sum = gate_sum + gate
        used = used + jnp.sum(onehot * keep[:, None], axis=0)
        remaining = remaining * (1.0 - onehot)
    combine = combine / jnp.maximum(gate_sum, 1e-9)[:, None, None]
    return dispatch, combine, first_choice


class MoEMlp(nn.Module):
    """Mixture-of-experts FFN: ``[..., d] -> [..., d]``.

    Attributes:
      num_experts: E, ideally a multiple of the mesh's ``expert`` axis size.
      hidden_dim: per-expert FFN hidden width.
      top_k: experts per token (1 = Switch, 2 = GShard default).
      capacity_factor: per-expert buffer = ceil(group_tokens * top_k / E * factor).
      num_groups: routing groups (GShard's G). Dispatch/combine one-hots are
        O(S^2 * top_k / G); at training scale set this to the data-shard count
        so each shard routes its own tokens (buffers then shard over ``data``
        and stay O((S/G)^2)). Capacity is per group. S must divide by G.
      dtype: activation dtype (params stay float32).

    Sow'd metrics (``.sow('intermediates', ...)``): ``load_balance_loss`` and
    ``router_z_loss`` — add them to the training objective via the criterion.
    """

    num_experts: int
    hidden_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    num_groups: int = 1
    dispatch_impl: str = "auto"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, *, decode: bool = False) -> jax.Array:
        orig_shape = x.shape
        d = orig_shape[-1]
        tokens = x.reshape(-1, d)  # [S, d]
        s = tokens.shape[0]
        e = self.num_experts
        g = self.num_groups
        if self.dispatch_impl not in ("auto", "einsum", "sort"):
            raise ValueError(
                f"dispatch_impl must be auto|einsum|sort, got {self.dispatch_impl!r}"
            )

        # --- router (float32 for stable softmax) ---------------------------
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32)
        )  # [S, E]
        gates = jax.nn.softmax(logits, axis=-1)

        # --- expert weights (expert-sharded) --------------------------------
        w_in = self.param(
            "w_in",
            nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal"),
            (e, d, self.hidden_dim),
            jnp.float32,
        )
        w_out = self.param(
            "w_out",
            nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal"),
            (e, self.hidden_dim, d),
            jnp.float32,
        )
        w_in = _constrain(w_in, (EXPERT_AXIS,)).astype(self.dtype)
        w_out = _constrain(w_out, (EXPERT_AXIS,)).astype(self.dtype)

        if decode:
            # Capacity-free inference routing: gather each token's top-k
            # expert weights and apply them directly — no buffers, no drops,
            # so per-step behavior matches training-renormalized gating
            # whenever training had capacity headroom. S is tiny at decode
            # (one token per sequence), so the [S, k, d, h] gather is cheap.
            gate_vals, choice = jax.lax.top_k(gates, self.top_k)  # [S, k]
            weights = gate_vals / jnp.maximum(
                gate_vals.sum(-1, keepdims=True), 1e-9
            )
            tk = tokens.astype(self.dtype)
            # jnp.take, not w_in[choice]: callers may pass host (numpy)
            # params outside jit, and numpy fancy-indexing rejects tracers.
            h = jax.nn.gelu(jnp.einsum("sd,skdh->skh", tk, jnp.take(w_in, choice, axis=0)))
            y = jnp.einsum("skh,skhd->skd", h, jnp.take(w_out, choice, axis=0))
            out = jnp.einsum("sk,skd->sd", weights.astype(self.dtype), y)
            return out.reshape(orig_shape).astype(self.dtype)

        if s % g:
            raise ValueError(f"{s} tokens not divisible by num_groups={g}")
        sg = s // g
        capacity = max(1, int(np.ceil(sg * self.top_k / e * self.capacity_factor)))
        # Resolve "auto" from the static group size (known at trace time).
        impl = self.dispatch_impl
        if impl == "auto":
            impl = "sort" if sg >= SORT_DISPATCH_MIN_GROUP else "einsum"

        # --- per-group top-k routing with order-based capacity --------------
        # Choices claim capacity in priority order (choice 0 of every token in
        # the group before any choice 1 — GShard policy) so dropping is
        # deterministic. Routing is vmapped over groups: one-hot buffers stay
        # O((S/G)^2) per group and shard over `data` with the groups.
        # (_route_group at module level — shared with manual_expert_mlp.)
        def route(group_gates):
            return _route_group(
                group_gates, num_experts=e, capacity=capacity, top_k=self.top_k
            )

        # Same routing semantics, scatter/gather instead of one-hot algebra:
        # rank each (choice, token) entry within its expert by a stable sort
        # (choice-major flattening preserves the GShard priority order), drop
        # ranks past capacity into a trash row, scatter-add into the [E, C, d]
        # buffers, and gather back weighted for the combine. No [sg, E, C]
        # tensors anywhere — O(sg*k) routing + O(E*C*d) buffers per group.
        n_flat = self.top_k * sg
        token_idx = jnp.tile(jnp.arange(sg), self.top_k)  # choice-major

        def route_sort(group_gates, group_tokens):
            gate_vals, choice = jax.lax.top_k(group_gates, self.top_k)  # [sg, k]
            ex_flat = choice.T.reshape(-1)  # [k*sg], choice-major
            order = jnp.argsort(ex_flat, stable=True)
            sorted_ex = ex_flat[order]
            arange = jnp.arange(n_flat)
            run_begin = jnp.where(
                jnp.concatenate([jnp.ones((1,), bool), sorted_ex[1:] != sorted_ex[:-1]]),
                arange,
                0,
            )
            pos_sorted = arange - jax.lax.cummax(run_begin)
            pos = jnp.zeros((n_flat,), jnp.int32).at[order].set(pos_sorted)
            keep = pos < capacity
            keep_tk = keep.reshape(self.top_k, sg).T  # [sg, k]
            gate_kept = gate_vals * keep_tk
            weight_tk = gate_kept / jnp.maximum(gate_kept.sum(-1, keepdims=True), 1e-9)
            rows = jnp.where(keep, ex_flat * capacity + pos, e * capacity)  # trash row
            buf = jnp.zeros((e * capacity + 1, d), self.dtype)
            buf = buf.at[rows].add(group_tokens.astype(self.dtype)[token_idx])
            expert_in = buf[:-1].reshape(e, capacity, d)
            first_choice = jax.nn.one_hot(choice[:, 0], e, dtype=jnp.int32)
            return expert_in, rows, weight_tk.T.reshape(-1), first_choice

        def combine_sort(expert_out, rows, w_flat):
            flat = expert_out.reshape(e * capacity, d)
            picked = flat[jnp.clip(rows, 0, e * capacity - 1)]
            picked = picked * (rows < e * capacity)[:, None]
            contrib = picked * w_flat.astype(self.dtype)[:, None]
            return jnp.zeros((sg, d), self.dtype).at[token_idx].add(contrib)

        grouped_gates = gates.reshape(g, sg, e)
        # The reshard from token-sharded [G over data] to expert-sharded IS
        # the all-to-all (inserted by the SPMD partitioner at the constraint).
        grouped_tokens = tokens.reshape(g, sg, d)
        grouped_tokens = _constrain(grouped_tokens, (DATA_AXIS,), activation=True)

        if impl == "sort":
            expert_in, rows, w_flat, first_choice = jax.vmap(route_sort)(
                grouped_gates, grouped_tokens
            )
        else:
            dispatch, combine, first_choice = jax.vmap(route)(grouped_gates)
            # dispatch: [G, sg, E, C] x [G, sg, d] -> [G, E, C, d]
            expert_in = jnp.einsum(
                "gsec,gsd->gecd",
                dispatch.astype(self.dtype),
                grouped_tokens.astype(self.dtype),
            )

        self.sow(
            "intermediates",
            "load_balance_loss",
            load_balance_loss(gates, first_choice.reshape(s, e)),
        )
        self.sow("intermediates", "router_z_loss", router_z_loss(logits))

        # --- expert computation (expert-sharded) ---------------------------
        expert_in = _constrain(expert_in, (DATA_AXIS, EXPERT_AXIS), activation=True)
        h = jax.nn.gelu(jnp.einsum("gecd,edh->gech", expert_in, w_in))
        expert_out = jnp.einsum("gech,ehd->gecd", h, w_out)
        expert_out = _constrain(expert_out, (DATA_AXIS, EXPERT_AXIS), activation=True)

        if impl == "sort":
            out = jax.vmap(combine_sort)(expert_out, rows, w_flat)
        else:
            out = jnp.einsum("gsec,gecd->gsd", combine.astype(self.dtype), expert_out)
        return out.reshape(orig_shape).astype(self.dtype)


def manual_expert_mlp(
    params,
    x: jax.Array,
    *,
    num_experts: int,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    num_groups: int = 1,
    mesh=None,
    data_axis: str = DATA_AXIS,
    expert_axis: str = EXPERT_AXIS,
    exchange: str = "auto",
    dtype: Any = jnp.float32,
) -> jax.Array:
    """MoE FFN forward with expert parallelism expressed MANUALLY — the
    workaround for the data x expert x pipe composition (r4 VERDICT item 7).

    :class:`MoEMlp` expresses expert parallelism as sharding constraints and
    lets GSPMD insert the token<->expert all-to-all. Inside
    ``pipeline_apply``'s partial-manual region that path trips an upstream
    XLA SPMD-partitioner CHECK (``spmd_partitioner_util.cc``
    ``AllReduceAlongShardingDims``, repro: ``scripts/repro_triple_check.py``
    — a process-fatal CHECK, so it cannot live in pytest). This function
    sidesteps the partitioner entirely: a nested ``shard_map`` manual over
    ``(data, expert)`` whose body does the MoE exchange by hand, two
    formulations (``exchange=``):

    * ``"all_to_all"`` — the canonical GShard exchange: token groups shard
      jointly over ``(data, expert)``; each shard routes its own groups
      (:func:`_route_group`, the exact semantics of the einsum path), the
      ``[G_local, E, C, d]`` dispatch buffers swap experts<->groups with one
      ``jax.lax.all_to_all`` over ``expert``, the local slab runs its FFN,
      a second all_to_all returns the outputs, combine is local. Comm per
      device: 2 x buffer/n_exp. NOT usable inside an enclosing manual region
      whose free axis (``pipe``) sits between ``data`` and ``expert`` in the
      mesh order — Shardy rejects the joint dim sharding ("manual axis
      'expert' after free axis 'pipe'").
    * ``"psum"`` — groups shard over ``data`` only; routing replicates over
      the expert members, each applies its LOCAL expert slice of dispatch/
      combine, and one ``psum`` over ``expert`` sums the partial outputs
      (the :func:`manual_expert_ffn_local` formulation, runnable here
      un-nested for parity testing). Comm per device: one [tokens, d]
      all-reduce; prefer all_to_all.
    * ``"auto"`` (default) — all_to_all.

    NESTING: this function cannot run inside an enclosing ``shard_map``
    (pipeline_apply) at all — Shardy rejects both re-binding a parent's
    manual axis and an inner mesh differing from the context mesh — and
    raises a ValueError pointing at the supported composition:
    ``pipeline_apply(extra_manual_axes=("expert",), stage_param_specs=...)``
    with :func:`manual_expert_ffn_local` stage bodies.

    ``params``: an :class:`MoEMlp` ``variables["params"]`` tree (``router``
    Dense kernel/bias, ``w_in``, ``w_out``) — training checkpoints swap
    between the two implementations unchanged. ``x``: ``[..., d]``; token
    count must divide by ``num_groups``; ``num_groups`` by
    ``data_size * expert_size`` (all_to_all) or ``data_size`` (psum);
    ``num_experts`` by ``expert_size``. Differentiable; aux losses are not
    sow'd on this path (compute them from a separate router call if needed).
    """
    from distributed_training_pytorch_tpu.compat import shard_map

    # Inside a traced context the shard_map must receive the ambient ABSTRACT
    # mesh (it carries e.g. pipe's Manual axis type from an enclosing
    # pipeline_apply region); the concrete mesh arg is the fallback for
    # un-nested use outside set_mesh.
    ctx = compat.get_abstract_mesh()
    if ctx is not None and getattr(ctx, "axis_names", ()):
        mesh = ctx
    elif mesh is None:
        raise ValueError("manual_expert_mlp needs a mesh (arg or ambient set_mesh)")
    axis_names = getattr(mesh, "axis_names", ())
    n_exp = mesh.shape[expert_axis] if expert_axis in axis_names else 1
    n_data = mesh.shape[data_axis] if data_axis in axis_names else 1
    if exchange == "auto":
        exchange = "all_to_all"
    if exchange not in ("all_to_all", "psum"):
        raise ValueError(f"exchange must be all_to_all|psum|auto, got {exchange!r}")

    orig_shape = x.shape
    d = orig_shape[-1]
    tokens = x.reshape(-1, d)
    s = tokens.shape[0]
    g = num_groups
    e = num_experts
    if s % g:
        raise ValueError(f"{s} tokens not divisible by num_groups={g}")
    need = n_data * n_exp if exchange == "all_to_all" else n_data
    if g % need:
        raise ValueError(f"num_groups={g} must divide by {need} shards ({exchange})")
    if e % n_exp:
        raise ValueError(f"num_experts={e} not divisible by expert axis {n_exp}")
    sg = s // g
    capacity = max(1, int(np.ceil(sg * top_k / e * capacity_factor)))

    rk = params["router"]["kernel"]
    rb = params["router"]["bias"]
    w_in = params["w_in"]
    w_out = params["w_out"]
    grouped = tokens.reshape(g, sg, d)

    def body_a2a(grouped_local, rk, rb, w_in_local, w_out_local):
        # grouped_local: [G_local, sg, d]; w slabs: [E_local, d, h]/[E_local, h, d]
        dispatch, combine, _ = _route_grouped(
            grouped_local, rk, rb, num_experts=e, capacity=capacity, top_k=top_k
        )
        expert_in = jnp.einsum(
            "gsec,gsd->gecd", dispatch.astype(dtype), grouped_local.astype(dtype)
        )  # [G_local, E, C, d]
        if n_exp > 1:
            # experts -> groups exchange: split E into n_exp slabs, concat on
            # the group dim — each expert shard now holds ITS experts'
            # buffers for every group-set in this data row.
            expert_in = jax.lax.all_to_all(
                expert_in, expert_axis, split_axis=1, concat_axis=0, tiled=True
            )  # [G_local*n_exp, E_local, C, d]
        h = jax.nn.gelu(
            jnp.einsum("gecd,edh->gech", expert_in, w_in_local.astype(dtype))
        )
        expert_out = jnp.einsum("gech,ehd->gecd", h, w_out_local.astype(dtype))
        if n_exp > 1:
            expert_out = jax.lax.all_to_all(
                expert_out, expert_axis, split_axis=0, concat_axis=1, tiled=True
            )  # [G_local, E, C, d]
        out = jnp.einsum("gsec,gecd->gsd", combine.astype(dtype), expert_out)
        return out

    def body_psum(grouped_local, rk, rb, w_in_local, w_out_local):
        params_local = {
            "router": {"kernel": rk, "bias": rb},
            "w_in": w_in_local,
            "w_out": w_out_local,
        }
        return manual_expert_ffn_local(
            params_local, grouped_local,
            num_experts=e, n_expert_shards=n_exp, expert_axis=expert_axis,
            top_k=top_k, capacity=capacity, dtype=dtype,
        )

    if compat.manual_axes_of(mesh):
        raise ValueError(
            "manual_expert_mlp cannot nest inside an enclosing shard_map "
            "(Shardy rejects both re-binding a parent's manual axis and a "
            "sub-mesh that differs from the context mesh). Inside "
            "pipeline_apply, pass extra_manual_axes=('expert',) + "
            "stage_param_specs and call moe.manual_expert_ffn_local from the "
            "stage body instead."
        )
    # Specs reference only axes the mesh actually has — degenerate meshes
    # (no expert axis, or no data axis) run the same bodies with the
    # collectives compiled out (`if n_exp > 1` guards).
    def _present(*axes):
        return P(tuple(a for a in axes if a in axis_names) or None)

    w_spec = _present(expert_axis)
    if exchange == "all_to_all":
        body, x_spec = body_a2a, _present(data_axis, expert_axis)
    else:
        body, x_spec = body_psum, _present(data_axis)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(x_spec, P(), P(), w_spec, w_spec),
        out_specs=x_spec,
        axis_names=frozenset(a for a in (data_axis, expert_axis) if a in axis_names),
    )
    out = fn(grouped, rk, rb, w_in, w_out)
    return out.reshape(orig_shape).astype(dtype)


def _route_grouped(grouped, rk, rb, *, num_experts, capacity, top_k):
    """Router + per-group GShard routing over ``[G, sg, d]`` tokens."""
    logits = grouped.astype(jnp.float32) @ rk + rb  # [G, sg, E]
    gates = jax.nn.softmax(logits, axis=-1)
    return jax.vmap(
        lambda gg: _route_group(
            gg, num_experts=num_experts, capacity=capacity, top_k=top_k
        )
    )(gates)


def manual_expert_ffn_local(
    params_local,
    grouped: jax.Array,
    *,
    num_experts: int,
    n_expert_shards: int,
    expert_axis: str = EXPERT_AXIS,
    top_k: int = 2,
    capacity: int | None = None,
    capacity_factor: float = 1.25,
    dtype: Any = jnp.float32,
) -> jax.Array:
    """Expert-parallel MoE FFN for use INSIDE an already-manual region over
    ``expert_axis`` — the stage-body half of the data x expert x pipe
    workaround (``pipeline_apply(extra_manual_axes=("expert",), ...)``).

    ``params_local``: MoEMlp-layout params whose ``w_in``/``w_out`` are this
    shard's LOCAL ``[E/n, d, h]`` slabs (the region's in_specs sliced them);
    router kernel/bias replicated. ``grouped``: ``[G, sg, d]`` tokens,
    replicated over ``expert_axis``. Routing replicates across expert
    members (:func:`_route_group` semantics — identical to the einsum path);
    each member applies its local expert slice of dispatch/combine and one
    ``psum`` over ``expert_axis`` sums the partial outputs."""
    e = num_experts
    n_exp = n_expert_shards
    if capacity is None:
        sg = grouped.shape[1]
        capacity = max(1, int(np.ceil(sg * top_k / e * capacity_factor)))
    rk = params_local["router"]["kernel"]
    rb = params_local["router"]["bias"]
    dispatch, combine, _ = _route_grouped(
        grouped, rk, rb, num_experts=e, capacity=capacity, top_k=top_k
    )
    e_loc = e // n_exp
    start = (
        jax.lax.axis_index(expert_axis) * e_loc if n_exp > 1 else jnp.zeros((), jnp.int32)
    )
    disp_l = jax.lax.dynamic_slice_in_dim(dispatch.astype(dtype), start, e_loc, 2)
    comb_l = jax.lax.dynamic_slice_in_dim(combine.astype(dtype), start, e_loc, 2)
    expert_in = jnp.einsum("gsec,gsd->gecd", disp_l, grouped.astype(dtype))
    h = jax.nn.gelu(
        jnp.einsum("gecd,edh->gech", expert_in, params_local["w_in"].astype(dtype))
    )
    expert_out = jnp.einsum(
        "gech,ehd->gecd", h, params_local["w_out"].astype(dtype)
    )
    out = jnp.einsum("gsec,gecd->gsd", comb_l, expert_out)
    if n_exp > 1:
        out = jax.lax.psum(out, expert_axis)
    return out
