"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Entirely absent from the reference (no attention, no sequence axis —
SURVEY.md §5 'long-context'); built TPU-first per the driver's long-context
mandate. Both strategies run inside ``shard_map`` over the mesh's ``seq``
axis, so a sequence ``s``-times longer than one device's HBM allows fits:

* :func:`ring_attention` — blockwise attention with online softmax; K/V
  blocks rotate around the ring via ``lax.ppermute`` while each device keeps
  its Q shard. Compute on block ``i`` overlaps the transfer of block ``i+1``
  (XLA's latency-hiding scheduler pipelines the permute) — the
  Liu & Abbeel ring-attention schedule, implemented as a ``lax.scan`` of MXU
  matmuls rather than a hand-scheduled kernel.
* :func:`ulysses_attention` — DeepSpeed-Ulysses: ``lax.all_to_all`` swaps the
  sequence shard for a head shard, runs *dense* local attention per head
  group, and swaps back. Cheaper collectives for moderate sequence lengths;
  requires ``num_heads % seq_devices == 0``.

Both take ``[B, T, H, D]`` global arrays (T sharded over ``seq``) and return
the same layout; numerics match dense attention to float tolerance (tested on
the 8-device CPU mesh).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_training_pytorch_tpu.parallel.mesh import SEQ_AXIS

_NEG_INF = -1e30


def _block_attn(q, k, v, scale, bias=None):
    """One Q-block x K-block attention: returns (unnormalized out, row max,
    row sumexp) for online-softmax accumulation. Shapes [B, Tq, H, D] x
    [B, Tk, H, D]."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias
    m = logits.max(axis=-1)  # [B, H, Tq]
    p = jnp.exp(logits - m[..., None])
    l = p.sum(axis=-1)  # [B, H, Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return o, m, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = SEQ_AXIS,
    causal: bool = False,
) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis``. [B, T, H, D]."""
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no axis {axis!r}")
    scale = q.shape[-1] ** -0.5

    def kernel(q, k, v):
        s = lax.psum(1, axis)  # ring size
        my = lax.axis_index(axis)
        t_local = q.shape[1]
        q_pos = my * t_local + jnp.arange(t_local)  # global Q positions
        perm = [(i, (i + 1) % s) for i in range(s)]

        def block_bias(step):
            if not causal:
                return None
            # Who produced this K/V block: it has moved `step` hops forward.
            owner = (my - step) % s
            k_pos = owner * t_local + jnp.arange(t_local)
            bias = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0, _NEG_INF)
            return bias[None, None]  # [1, 1, Tq, Tk]

        def merge(acc, step, k_blk, v_blk):
            o, m, l = acc
            o_b, m_b, l_b = _block_attn(q, k_blk, v_blk, scale, block_bias(step))
            m_new = jnp.maximum(m, m_b)  # online softmax merge
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(m_b - m_new)
            o = o * alpha.transpose(0, 2, 1)[..., None] + o_b * beta.transpose(0, 2, 1)[..., None]
            l = l * alpha + l_b * beta
            return o, m_new, l

        def body(carry, step):
            # Rotate first, compute after: the own (step-0) block is handled
            # outside the scan, so no rotation result is ever discarded.
            o, m, l, k_blk, v_blk = carry
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)
            o, m, l = merge((o, m, l), step, k_blk, v_blk)
            return (o, m, l, k_blk, v_blk), None

        B, T, H, D = q.shape
        o0 = jnp.zeros((B, T, H, D), jnp.float32)
        m0 = jnp.full((B, H, T), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, T), jnp.float32)
        acc = merge((o0, m0, l0), 0, k, v)  # own block, no communication
        (o, m, l, _, _), _ = lax.scan(body, acc + (k, v), jnp.arange(1, s))
        o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return o.astype(q.dtype)

    spec = P(None, axis, None, None)
    return shard_map(
        kernel, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    use_flash: bool | None = None,
) -> jax.Array:
    """DeepSpeed-Ulysses sequence parallelism: all-to-all to head-sharded
    layout, dense local attention, all-to-all back. [B, T, H, D], T sharded
    on ``axis``; requires H divisible by the axis size.

    ``use_flash``: run the local attention through the Pallas flash kernel —
    after the all-to-all each device holds the FULL sequence for its head
    group, exactly the long-T shape where the kernel beats XLA (and where the
    O(T^2) score tensor may not even fit). None = auto: flash on TPU when the
    global sequence is long enough (``ops.pallas.FLASH_MIN_SEQ_LEN``).
    Differentiable either way (the kernel carries its own flash backward).
    """
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no axis {axis!r}")
    s = mesh.shape[axis]
    if q.shape[2] % s:
        raise ValueError(f"num_heads {q.shape[2]} not divisible by seq devices {s}")
    scale = q.shape[-1] ** -0.5

    def kernel(q, k, v):
        # [B, T/s, H, D] -> [B, T, H/s, D]: scatter heads, gather sequence.
        def seq_to_heads(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

        def heads_to_seq(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

        qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
        T = qh.shape[1]
        from distributed_training_pytorch_tpu.ops.pallas import (
            FLASH_MIN_SEQ_LEN,
            flash_attention,
        )

        flash = use_flash
        if flash is None:
            flash = jax.default_backend() == "tpu" and T >= FLASH_MIN_SEQ_LEN
        if flash:
            o = flash_attention(qh, kh, vh, causal=causal)
        else:
            bias = None
            if causal:
                pos = jnp.arange(T)
                bias = jnp.where(pos[:, None] >= pos[None, :], 0.0, _NEG_INF)[None, None]
            o, m, l = _block_attn(qh, kh, vh, scale, bias)
            o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return heads_to_seq(o.astype(q.dtype))

    spec = P(None, axis, None, None)
    return shard_map(
        kernel, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
