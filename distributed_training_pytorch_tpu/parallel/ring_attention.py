"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Entirely absent from the reference (no attention, no sequence axis —
SURVEY.md §5 'long-context'); built TPU-first per the driver's long-context
mandate. Both strategies run inside ``shard_map`` over the mesh's ``seq``
axis, so a sequence ``s``-times longer than one device's HBM allows fits:

* :func:`ring_attention` — blockwise attention with online softmax; K/V
  blocks rotate around the ring via ``lax.ppermute`` while each device keeps
  its Q shard. Compute on block ``i`` overlaps the transfer of block ``i+1``
  (XLA's latency-hiding scheduler pipelines the permute) — the
  Liu & Abbeel ring-attention schedule, implemented as a ``lax.scan`` of MXU
  matmuls rather than a hand-scheduled kernel.
* :func:`ulysses_attention` — DeepSpeed-Ulysses: ``lax.all_to_all`` swaps the
  sequence shard for a head shard, runs *dense* local attention per head
  group, and swaps back. Cheaper collectives for moderate sequence lengths;
  requires ``num_heads % seq_devices == 0``.

Both take ``[B, T, H, D]`` global arrays (T sharded over ``seq``) and return
the same layout; numerics match dense attention to float tolerance (tested on
the 8-device CPU mesh).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax

from distributed_training_pytorch_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_training_pytorch_tpu.parallel.mesh import SEQ_AXIS

_NEG_INF = -1e30


def _block_attn(q, k, v, scale, bias=None):
    """One Q-block x K-block attention: returns (unnormalized out, row max,
    row sumexp) for online-softmax accumulation. Shapes [B, Tq, H, D] x
    [B, Tk, H, D]."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias
    m = logits.max(axis=-1)  # [B, H, Tq]
    p = jnp.exp(logits - m[..., None])
    l = p.sum(axis=-1)  # [B, H, Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return o, m, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    impl: str = "auto",
) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis``. [B, T, H, D].

    ``impl``:

    * ``"dense"`` — each ring step materializes the [B, H, Tq, Tk] block
      logits (fine for moderate per-shard T; O(T_local^2) memory).
    * ``"flash"`` — each ring step runs the Pallas flash kernel on the
      visiting K/V block and merges via the kernel's LSE statistics, so
      per-shard memory stays O(T_local) and the [Tq, Tk] scores never exist.
      Under a causal mask, fully-masked blocks (owner > self) skip the kernel
      outright — about half the ring FLOPs, which the dense path spends on
      fully-bias-masked matmuls. Backward is the blockwise flash
      decomposition run as a reverse ring (dk/dv accumulate on the rotating
      blocks; one ring-level custom VJP owns the schedule).
    * ``"auto"`` — flash on TPU when the per-shard sequence clears the
      kernel's measured crossover (``ops.pallas.FLASH_MIN_SEQ_LEN``), else
      dense.
    """
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no axis {axis!r}")
    if impl not in ("auto", "dense", "flash"):
        raise ValueError(f"impl must be auto|dense|flash, got {impl!r}")
    if impl == "auto":
        from distributed_training_pytorch_tpu.ops.pallas import FLASH_MIN_SEQ_LEN

        t_local = q.shape[1] // mesh.shape[axis]
        impl = (
            "flash"
            if jax.default_backend() == "tpu" and t_local >= FLASH_MIN_SEQ_LEN
            else "dense"
        )
    if impl == "flash":
        return _ring_attention_flash(q, k, v, mesh, axis=axis, causal=causal)
    scale = q.shape[-1] ** -0.5

    def kernel(q, k, v):
        s = lax.psum(1, axis)  # ring size
        my = lax.axis_index(axis)
        t_local = q.shape[1]
        q_pos = my * t_local + jnp.arange(t_local)  # global Q positions
        perm = [(i, (i + 1) % s) for i in range(s)]

        def block_bias(step):
            if not causal:
                return None
            # Who produced this K/V block: it has moved `step` hops forward.
            owner = (my - step) % s
            k_pos = owner * t_local + jnp.arange(t_local)
            bias = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0, _NEG_INF)
            return bias[None, None]  # [1, 1, Tq, Tk]

        def merge(acc, step, k_blk, v_blk):
            o, m, l = acc
            o_b, m_b, l_b = _block_attn(q, k_blk, v_blk, scale, block_bias(step))
            m_new = jnp.maximum(m, m_b)  # online softmax merge
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(m_b - m_new)
            o = o * alpha.transpose(0, 2, 1)[..., None] + o_b * beta.transpose(0, 2, 1)[..., None]
            l = l * alpha + l_b * beta
            return o, m_new, l

        def body(carry, step):
            # Rotate first, compute after: the own (step-0) block is handled
            # outside the scan, so no rotation result is ever discarded.
            o, m, l, k_blk, v_blk = carry
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)
            o, m, l = merge((o, m, l), step, k_blk, v_blk)
            return (o, m, l, k_blk, v_blk), None

        B, T, H, D = q.shape
        o0 = jnp.zeros((B, T, H, D), jnp.float32)
        m0 = jnp.full((B, H, T), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, T), jnp.float32)
        acc = merge((o0, m0, l0), 0, k, v)  # own block, no communication
        (o, m, l, _, _), _ = lax.scan(body, acc + (k, v), jnp.arange(1, s))
        o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return o.astype(q.dtype)

    spec = P(None, axis, None, None)
    return shard_map(
        kernel, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)


def _ring_attention_flash(q, k, v, mesh, *, axis, causal):
    """Ring attention with the Pallas flash kernel as the per-block compute.

    Forward: each device keeps its q shard; K/V blocks rotate; every step
    runs ``flash_block_fwd`` (block-normalized output + LSE) and merges into
    the running output with ``logaddexp`` weights — the online softmax across
    blocks, with the within-block online softmax living in the kernel.

    Backward: the standard blockwise flash decomposition, run as a second
    ring. ``delta = rowsum(dO * O)`` and the final LSE are global per-q-row
    statistics, so each visiting K/V block's (dq, dk, dv) contributions are
    computable locally by the flash backward kernels; dq accumulates in place
    while dk/dv accumulate on buffers that rotate *with* their K/V blocks and
    arrive home after a full loop. A custom VJP around the two shard_maps
    owns the schedule (autodiff never sees the kernel internals).
    """
    s = mesh.shape[axis]
    interpret = jax.default_backend() != "tpu"
    from distributed_training_pytorch_tpu.ops.pallas import (
        flash_block_bwd,
        flash_block_fwd,
    )

    perm = [(i, (i + 1) % s) for i in range(s)]

    def block_type(step):
        # Causal block classification: the visiting block left its owner
        # `step` hops back. 0 = fully masked (skip), 1 = diagonal (local
        # causal), 2 = fully visible.
        my = lax.axis_index(axis)
        owner = (my - step) % s
        return jnp.where(owner == my, 1, jnp.where(owner < my, 2, 0))

    def fwd_kernel(q, k, v):
        b, tl, h, d = q.shape

        def fwd_block(step, k_blk, v_blk):
            if not causal:
                return flash_block_fwd(q, k_blk, v_blk, causal=False, interpret=interpret)

            def skip(_k, _v):
                return (
                    jnp.zeros((b, tl, h, d), q.dtype),
                    jnp.full((b, h, tl), _NEG_INF, jnp.float32),
                )

            return lax.switch(
                block_type(step),
                [
                    skip,
                    lambda kb, vb: flash_block_fwd(q, kb, vb, causal=True, interpret=interpret),
                    lambda kb, vb: flash_block_fwd(q, kb, vb, causal=False, interpret=interpret),
                ],
                k_blk,
                v_blk,
            )

        def merge(acc, step, k_blk, v_blk):
            o, lse = acc
            o_b, lse_b = fwd_block(step, k_blk, v_blk)
            lse_new = jnp.logaddexp(lse, lse_b)
            w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
            w_new = jnp.exp(lse_b - lse_new).transpose(0, 2, 1)[..., None]
            return o * w_old + o_b.astype(jnp.float32) * w_new, lse_new

        o0 = jnp.zeros((b, tl, h, d), jnp.float32)
        lse0 = jnp.full((b, h, tl), _NEG_INF, jnp.float32)
        acc = merge((o0, lse0), 0, k, v)  # own block, no communication

        def body(carry, step):
            o, lse, k_blk, v_blk = carry
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)
            o, lse = merge((o, lse), step, k_blk, v_blk)
            return (o, lse, k_blk, v_blk), None

        (o, lse, _, _), _ = lax.scan(body, acc + (k, v), jnp.arange(1, s))
        return o.astype(q.dtype), lse

    def bwd_kernel(q, k, v, g, o, lse):
        delta = jnp.sum(
            g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
        ).transpose(0, 2, 1)  # [B, H, TL]

        def bwd_block(step, k_blk, v_blk):
            if not causal:
                return flash_block_bwd(
                    q, k_blk, v_blk, g, lse, delta, causal=False, interpret=interpret
                )

            def skip(_k, _v):
                return (
                    jnp.zeros_like(q),
                    jnp.zeros_like(k_blk),
                    jnp.zeros_like(v_blk),
                )

            return lax.switch(
                block_type(step),
                [
                    skip,
                    lambda kb, vb: flash_block_bwd(
                        q, kb, vb, g, lse, delta, causal=True, interpret=interpret
                    ),
                    lambda kb, vb: flash_block_bwd(
                        q, kb, vb, g, lse, delta, causal=False, interpret=interpret
                    ),
                ],
                k_blk,
                v_blk,
            )

        # Accumulate dq/dk/dv in float32 across ring steps (the kernels
        # already accumulate f32 *within* a block; without this the
        # cross-step += happens in the input dtype and rounding error grows
        # with ring size — matching the f32 statistics the forward keeps).
        # dk/dv therefore ride the ring as f32: 2x the ICI bytes of the
        # bf16 activations, bought for s-step-independent gradient error.
        f32 = lambda t: t.astype(jnp.float32)
        dq0, dk0, dv0 = map(f32, bwd_block(0, k, v))

        def body(carry, step):
            dq, k_blk, v_blk, dk_blk, dv_blk = carry
            # dk/dv ride the same rotation as their K/V blocks so each device
            # adds its contribution to the visiting block in place.
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)
            dk_blk = lax.ppermute(dk_blk, axis, perm)
            dv_blk = lax.ppermute(dv_blk, axis, perm)
            dq_c, dk_c, dv_c = bwd_block(step, k_blk, v_blk)
            return (dq + f32(dq_c), k_blk, v_blk, dk_blk + f32(dk_c), dv_blk + f32(dv_c)), None

        (dq, _, _, dk, dv), _ = lax.scan(
            body, (dq0, k, v, dk0, dv0), jnp.arange(1, s)
        )
        # s-1 hops so far; one more brings each dk/dv block home.
        dk = lax.ppermute(dk, axis, perm)
        dv = lax.ppermute(dv, axis, perm)
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    spec = P(None, axis, None, None)
    lse_spec = P(None, None, axis)
    fwd_sm = shard_map(
        fwd_kernel,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, lse_spec),
        check_vma=False,
    )
    bwd_sm = shard_map(
        bwd_kernel,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, lse_spec),
        out_specs=(spec, spec, spec),
        check_vma=False,
    )

    @jax.custom_vjp
    def ring(q, k, v):
        return fwd_sm(q, k, v)[0]

    def ring_fwd(q, k, v):
        o, lse = fwd_sm(q, k, v)
        return o, (q, k, v, o, lse)

    def ring_bwd(res, g):
        q, k, v, o, lse = res
        return bwd_sm(q, k, v, g, o, lse)

    ring.defvjp(ring_fwd, ring_bwd)
    return ring(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    use_flash: bool | None = None,
) -> jax.Array:
    """DeepSpeed-Ulysses sequence parallelism: all-to-all to head-sharded
    layout, dense local attention, all-to-all back. [B, T, H, D], T sharded
    on ``axis``; requires H divisible by the axis size.

    ``use_flash``: run the local attention through the Pallas flash kernel —
    after the all-to-all each device holds the FULL sequence for its head
    group, exactly the long-T shape where the kernel beats XLA (and where the
    O(T^2) score tensor may not even fit). None = auto: flash on TPU when the
    global sequence is long enough (``ops.pallas.FLASH_MIN_SEQ_LEN``).
    Differentiable either way (the kernel carries its own flash backward).
    """
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no axis {axis!r}")
    s = mesh.shape[axis]
    if q.shape[2] % s:
        raise ValueError(f"num_heads {q.shape[2]} not divisible by seq devices {s}")
    scale = q.shape[-1] ** -0.5

    def kernel(q, k, v):
        # [B, T/s, H, D] -> [B, T, H/s, D]: scatter heads, gather sequence.
        def seq_to_heads(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

        def heads_to_seq(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

        qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
        T = qh.shape[1]
        from distributed_training_pytorch_tpu.ops.pallas import (
            FLASH_MIN_SEQ_LEN,
            flash_attention,
        )

        flash = use_flash
        if flash is None:
            flash = jax.default_backend() == "tpu" and T >= FLASH_MIN_SEQ_LEN
        if flash:
            o = flash_attention(qh, kh, vh, causal=causal)
        else:
            bias = None
            if causal:
                pos = jnp.arange(T)
                bias = jnp.where(pos[:, None] >= pos[None, :], 0.0, _NEG_INF)[None, None]
            o, m, l = _block_attn(qh, kh, vh, scale, bias)
            o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return heads_to_seq(o.astype(q.dtype))

    spec = P(None, axis, None, None)
    return shard_map(
        kernel, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
