"""Elastic topology re-planning: resume a checkpoint on a different device count.

PR 9 made checkpoints resharding-capable — every save carries a
sharding-metadata record (``parallel.sharding.sharding_record``) and restore
lays the stored global arrays into whatever layout the restore *target*
declares — but always onto the **same number of devices**. Production
preemptible fleets shrink and grow under the trainer: a run killed on N
chips routinely restarts on M. This module is the missing solver: given the
*saved* record's mesh axes and the *current* backend's device count, it
re-solves the mesh axes and the grad-accumulation factor so the resumed run
is batch-math-equivalent to the interrupted one.

Re-plan rules (docs/fault_tolerance.md "Elastic training"):

* **Model-sharding axes are preserved verbatim.** ``tensor``/``seq``/
  ``pipe``/``expert`` extents shape per-leaf partition sizes (head counts,
  stage splits, expert placement) in ways a solver cannot re-derive — if the
  new device count is not divisible by their product, the re-plan *refuses*
  with a typed :class:`ElasticReplanError` instead of guessing.
* **Batch axes absorb the change.** The leftover factor
  ``M / preserved_product`` becomes the new batch-shard extent
  (``data x fsdp`` — :func:`~distributed_training_pytorch_tpu.parallel.mesh.
  batch_shard_extent`'s axes). The fsdp share is ``gcd(old_fsdp, new_extent)``
  — never *larger* than the old fsdp extent, so every leaf the old mesh
  sharded stays divisible by construction (shrink divides the old extent;
  grow routes extra devices to ``data``). ``N -> 1`` degenerates to pure DP.
* **Global batch is invariant.** The re-plan never changes the effective
  batch: the same ``batch_size`` rows feed every optimizer step, the LR
  schedule still reads ``state.step``, and the optimizer update is the mean
  gradient over the identical global batch — so the optimizer trajectory is
  *value-equivalent* (bit-exact up to the float re-association that any
  change of reduction grouping legally causes; see the tolerance rationale
  in docs/fault_tolerance.md).
* **Grad accumulation keeps per-shard microbatch rows bounded.** Shrinking
  the batch extent grows per-device rows; :func:`replan_accum` picks the
  smallest factor whose per-shard microbatch rows do not exceed the original
  run's — so an elastic shrink cannot OOM a device that previously fit —
  while keeping ``batch % (extent * accum) == 0`` (the engine's microbatch
  reshape contract). Growing relaxes accumulation the same way.

:class:`TopologyMismatchError` is the *detection* seam: the checkpoint
manager validates every restore's recorded topology against
``jax.device_count()`` up front and raises it — naming both topologies —
instead of letting the mismatch surface as an opaque failure deep inside
orbax. ``Trainer`` catches the situation earlier still (it peeks at the
resume checkpoint before choosing its mesh) and calls :func:`replan`, so a
checkpoint written at ``fsdp=8`` restores onto 4 or 16 devices without user
intervention; the manager seam protects every *other* consumer (offline
eval, manual restores).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping

from distributed_training_pytorch_tpu.parallel.mesh import (
    AXIS_ORDER,
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    TENSOR_AXIS,
    MeshConfig,
)

__all__ = [
    "TopologyMismatchError",
    "ElasticReplanError",
    "ElasticPlan",
    "record_axes",
    "axes_device_product",
    "validate_topology",
    "replan",
    "replan_accum",
    "replan_absorbing",
    "replan_excluding",
    "replan_reader",
    "nearest_divisible_accum",
]

# Axes whose extents the re-plan preserves verbatim (model-sharding axes)
# vs. the batch-sharding axes it re-solves (batch_shard_extent's axes).
PRESERVED_AXES = (PIPE_AXIS, EXPERT_AXIS, SEQ_AXIS, TENSOR_AXIS)
BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


class TopologyMismatchError(RuntimeError):
    """A checkpoint's recorded mesh covers a different device count than the
    running backend — restoring it blindly would fail deep inside orbax with
    no mention of topology. Raised up front by
    ``CheckpointManager.restore`` (named topologies on both sides); pass
    ``allow_topology_change=True`` after re-planning the restore target for
    the current backend (``Trainer`` does both automatically for
    ``mesh=None``)."""


class ElasticReplanError(TopologyMismatchError):
    """The topology change cannot be re-planned automatically — a preserved
    model-sharding extent does not divide the new device count, or the
    global batch cannot be laid out on the re-solved batch extent."""


def record_axes(record_or_axes: Mapping) -> "dict[str, int]":
    """Normalize a sharding record (``{"mesh": {axis: size}, "specs": ...}``)
    or a bare axis-size mapping into ``{axis: int}``."""
    axes = record_or_axes.get("mesh", record_or_axes)
    return {str(k): int(v) for k, v in axes.items()}


def axes_device_product(axes: Mapping[str, int]) -> int:
    """The device count a mesh with these axis sizes covers."""
    product = 1
    for size in axes.values():
        product *= int(size)
    return product


def validate_topology(
    record: Mapping, device_count: int, *, name: str = "checkpoint"
) -> None:
    """Raise :class:`TopologyMismatchError` when ``record``'s mesh axes do
    not multiply out to ``device_count`` — the up-front check that turns an
    opaque orbax restore failure into an error naming both topologies."""
    axes = record_axes(record)
    saved = axes_device_product(axes)
    if saved == int(device_count):
        return
    raise TopologyMismatchError(
        f"{name} was written on a {saved}-device mesh {axes}, but this "
        f"backend has {device_count} devices. Re-plan the restore for the "
        "current topology (Trainer does this automatically for mesh=None — "
        "parallel.elastic.replan), or pass allow_topology_change=True with "
        "a restore target already laid out for the current backend."
    )


@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """One solved topology change: the re-planned mesh + accumulation."""

    old_axes: "dict[str, int]"
    new_axes: "dict[str, int]"
    mesh_config: MeshConfig
    old_accum_steps: int
    accum_steps: int
    reason: str

    @property
    def old_devices(self) -> int:
        return axes_device_product(self.old_axes)

    @property
    def new_devices(self) -> int:
        return axes_device_product(self.new_axes)

    def event_fields(self) -> dict:
        """The ``elastic_restore`` telemetry event's payload
        (docs/observability.md)."""
        return {
            "from_mesh": dict(self.old_axes),
            "to_mesh": dict(self.new_axes),
            "from_devices": self.old_devices,
            "to_devices": self.new_devices,
            "old_accum_steps": self.old_accum_steps,
            "accum_steps": self.accum_steps,
            "reason": self.reason,
        }


def replan_accum(
    batch_size: int, old_extent: int, new_extent: int, old_accum: int = 1
) -> int:
    """The re-planned grad-accumulation factor for a batch-extent change.

    Invariants: the effective global batch never changes (accumulation only
    splits one optimizer step's gradient mean into microbatch partial means);
    per-shard microbatch rows never exceed the original run's (an elastic
    shrink cannot exceed the activation memory the old config fit in); and
    ``batch % (new_extent * accum) == 0`` (the engine's microbatch reshape +
    batch-sharding contract). Picks the *smallest* such factor, so a grow
    relaxes accumulation symmetrically.
    """
    batch_size, old_extent, new_extent = int(batch_size), int(old_extent), int(new_extent)
    old_accum = max(1, int(old_accum))
    if batch_size % new_extent:
        raise ElasticReplanError(
            f"global batch_size {batch_size} is not divisible by the "
            f"re-planned batch-shard extent {new_extent}: no accumulation "
            "factor can fix row placement. Round batch_size to a multiple "
            f"of {new_extent}, or resume on a device count whose batch "
            "extent divides it."
        )
    # Per-shard microbatch rows of the ORIGINAL config — the memory budget
    # the re-plan must stay inside. A config that was itself un-divisible
    # (never dispatched) still yields a sane floor.
    old_rows = max(1, batch_size // (old_extent * old_accum))
    max_accum = batch_size // new_extent  # 1 row per shard per microbatch
    for accum in range(1, max_accum + 1):
        if batch_size % (new_extent * accum):
            continue
        if batch_size // (new_extent * accum) <= old_rows:
            return accum
    # Unreachable: accum == max_accum always qualifies (divides by the guard
    # above, and its 1 row/shard <= old_rows which is clamped >= 1).
    raise AssertionError("replan_accum: no divisible accumulation factor")


def replan_reader(
    plan_or_axes,
    *,
    shard_sizes,
    global_batch_size: int,
    cursor: int,
    process_index: int = 0,
    process_count: int = 1,
) -> dict:
    """Re-split the streaming shard assignment for a re-planned topology —
    the data-plane half of an elastic resume (docs/data.md "elastic
    re-split ritual").

    The global record sequence is a pure function of ``(seed, epoch, shard
    structure)`` and never moves; what changes across N→M is only *which
    slice of it each host feeds*. Given the solved :class:`ElasticPlan` (or
    bare new mesh axes) and the checkpoint's global ``cursor``, this derives
    the new per-host row-range assignment + its version for the new
    ``data x fsdp`` batch extent — pure index arithmetic, no data movement,
    no communication (every host derives the identical answer).
    """
    from distributed_training_pytorch_tpu.data.streaming.state import (
        shard_assignment,
    )

    if isinstance(plan_or_axes, ElasticPlan):
        axes = plan_or_axes.new_axes
    else:
        axes = record_axes(plan_or_axes)
    extent = max(
        1, int(axes.get(DATA_AXIS, 1)) * int(axes.get(FSDP_AXIS, 1))
    )
    return shard_assignment(
        shard_sizes=shard_sizes,
        global_batch_size=global_batch_size,
        process_index=process_index,
        process_count=process_count,
        batch_extent=extent,
        cursor=cursor,
    )


def nearest_divisible_accum(
    batch_size: int, extent: int, accum: int
) -> "int | None":
    """The accumulation factor closest to ``accum`` (ties to the smaller)
    satisfying the engine's microbatch contract
    ``batch % (extent * accum) == 0`` — the fail-fast suggestion the
    trainer's post-replan re-validation attaches. None when ``extent`` does
    not divide ``batch`` at all (no factor can fix row placement)."""
    batch_size, extent, accum = int(batch_size), int(extent), max(1, int(accum))
    if extent <= 0 or batch_size % extent:
        return None
    per_shard = batch_size // extent
    divisors = [d for d in range(1, per_shard + 1) if per_shard % d == 0]
    return min(divisors, key=lambda d: (abs(d - accum), d))


def replan(
    record_or_axes: Mapping,
    device_count: int,
    *,
    batch_size: int | None = None,
    accum_steps: int = 1,
) -> ElasticPlan:
    """Solve a saved mesh's axes for ``device_count`` devices.

    ``record_or_axes`` is the checkpoint's sharding record (or its bare
    ``mesh`` axes). ``batch_size``/``accum_steps`` are the resumed run's
    *configured* values (the same script config the interrupted run used);
    when ``batch_size`` is given, divisibility is validated and the
    accumulation factor re-solved (see :func:`replan_accum`), else
    accumulation passes through unchanged.
    """
    old_axes = record_axes(record_or_axes)
    device_count = int(device_count)
    if device_count < 1:
        raise ValueError(f"device_count must be >= 1, got {device_count}")
    unknown = [a for a in old_axes if a not in AXIS_ORDER]
    if unknown:
        raise ElasticReplanError(
            f"saved mesh {old_axes} names unknown axes {unknown}; known "
            f"axes are {AXIS_ORDER} — cannot re-plan a mesh this library "
            "did not lay out."
        )
    preserved = {
        axis: old_axes.get(axis, 1)
        for axis in PRESERVED_AXES
        if old_axes.get(axis, 1) > 1
    }
    preserved_product = axes_device_product(preserved)
    if device_count % preserved_product:
        raise ElasticReplanError(
            f"cannot re-plan the saved {axes_device_product(old_axes)}-device "
            f"mesh {old_axes} onto {device_count} devices: the preserved "
            f"model-sharding extents {preserved} (product {preserved_product}) "
            f"do not divide {device_count}. Tensor/seq/pipe/expert extents "
            "shape per-leaf partition sizes and are never re-solved — resume "
            "on a multiple of their product, or rebuild the run with a new "
            "explicit mesh."
        )
    new_extent = device_count // preserved_product
    old_fsdp = old_axes.get(FSDP_AXIS, 1)
    old_extent = old_axes.get(DATA_AXIS, 1) * old_fsdp
    # fsdp takes the largest share that both divides the new extent and
    # divides the OLD fsdp extent (gcd): every leaf the old mesh sharded
    # over fsdp stays divisible by construction; growth lands on `data`.
    new_fsdp = math.gcd(old_fsdp, new_extent)
    new_data = new_extent // new_fsdp
    new_axes = {DATA_AXIS: new_data}
    if new_fsdp > 1:
        new_axes[FSDP_AXIS] = new_fsdp
    new_axes.update(preserved)
    new_axes = {a: new_axes[a] for a in AXIS_ORDER if a in new_axes}
    new_accum = max(1, int(accum_steps))
    if batch_size is not None:
        new_accum = replan_accum(
            batch_size, old_extent, new_extent, old_accum=accum_steps
        )
    old_devices = axes_device_product(old_axes)
    direction = "shrink" if device_count < old_devices else "grow"
    config_kwargs = {
        name: size for name, size in new_axes.items() if name != DATA_AXIS
    }
    return ElasticPlan(
        old_axes=old_axes,
        new_axes=new_axes,
        mesh_config=MeshConfig(data=new_data, **config_kwargs),
        old_accum_steps=max(1, int(accum_steps)),
        accum_steps=new_accum,
        reason=f"{direction} {old_devices}->{device_count} devices",
    )


def replan_excluding(
    record_or_axes: Mapping,
    device_ids,
    exclude,
    *,
    batch_size: int | None = None,
    accum_steps: int = 1,
) -> ElasticPlan:
    """Re-plan a saved mesh onto the survivors of a degraded fleet: the
    devices in ``device_ids`` minus the ``exclude`` set — the fleet
    controller's straggler-remediation entry (ISSUE 16: a persistent
    ``straggler`` verdict names a chip; the remediation is a restart onto
    the M−1 healthy devices, solved by the same :func:`replan` rules an
    ordinary elastic shrink uses).

    ``device_ids`` is the CURRENT topology's device-id set (typically
    ``[d.id for d in jax.devices()]`` — but plain ints here, so a
    supervising controller can plan feasibility without a jax backend of
    its own); ``exclude`` the degraded ids to drop. Excluded ids not
    present are ignored (the chip may already be gone). Raises
    :class:`ElasticReplanError` when no devices survive; divisibility
    failures (a preserved model axis not dividing M−1, the global batch
    not fitting the shrunk extent) propagate from :func:`replan` — the
    controller treats any of these as "cannot remediate, surface to a
    human"."""
    ids = [int(d) for d in device_ids]
    dropped = sorted({int(d) for d in exclude} & set(ids))
    survivors = [d for d in ids if d not in set(dropped)]
    if not survivors:
        raise ElasticReplanError(
            f"excluding {sorted(int(d) for d in exclude)} from devices "
            f"{sorted(ids)} leaves no survivors — nothing to re-plan onto."
        )
    plan = replan(
        record_or_axes,
        len(survivors),
        batch_size=batch_size,
        accum_steps=accum_steps,
    )
    return dataclasses.replace(
        plan,
        reason=plan.reason
        + f" (excluding degraded chip(s) {','.join(str(d) for d in dropped)})",
    )


def replan_absorbing(
    record_or_axes: Mapping,
    device_ids,
    absorb,
    *,
    batch_size: int | None = None,
    accum_steps: int = 1,
) -> ElasticPlan:
    """:func:`replan_excluding`'s grow twin (ISSUE 20): re-plan a mesh onto
    the devices in ``device_ids`` PLUS the offered ``absorb`` ids — the
    fleet controller's chip-offer actuation entry. When a trainer's
    ``restart_excluding`` frees a chip, the accepted offer re-plans the
    serving replica's mesh onto its current devices plus the freed one
    through the same solver rules an elastic grow uses (model-sharding
    axes preserved-or-refused, the extra device landing on the batch
    axes). Offered ids already present are ignored (idempotent re-offer);
    divisibility failures propagate as :class:`ElasticReplanError` — the
    controller treats them as "cannot absorb, revert the handshake"."""
    ids = [int(d) for d in device_ids]
    added = sorted({int(d) for d in absorb} - set(ids))
    plan = replan(
        record_or_axes,
        len(ids) + len(added),
        batch_size=batch_size,
        accum_steps=accum_steps,
    )
    if not added:
        return plan
    return dataclasses.replace(
        plan,
        reason=plan.reason
        + f" (absorbing offered chip(s) {','.join(str(d) for d in added)})",
    )
