"""Device-mesh bootstrap and sharding helpers.

TPU-native replacement for the reference's process-group layer
(``trainer/trainer.py:74-82`` ``ddp_setup``/``destroy_process`` and the
torchrun env-var rendezvous in ``run.sh:9-14``): instead of
``init_process_group("nccl")`` plus per-rank CUDA device binding, we run
``jax.distributed.initialize`` (coordinator-based rendezvous over DCN) once per
host and build a named :class:`jax.sharding.Mesh` over all global devices.
Collectives then ride ICI/DCN via shardings — there is no NCCL-style tuning
surface (``run.sh:1-8``) because XLA's latency-hiding scheduler owns that.

Mesh axes used throughout the framework:

* ``data``  — data parallelism (the reference's only axis, DDP at
  ``trainer/trainer.py:52``).
* ``fsdp``  — parameter sharding (ZeRO-3 analog), optional.
* ``tensor``— tensor parallelism for wide layers, optional.
* ``seq``   — sequence/context parallelism (ring attention), optional.
* ``pipe``  — pipeline parallelism (``parallel.pipeline``), optional.
* ``expert``— MoE expert parallelism (``parallel.moe``), optional.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names, in mesh order. `data` is outermost so that pure-DP
# meshes are contiguous over ICI and cross-host traffic stays on the data axis;
# `pipe` sits just inside it (stage-to-stage ppermute tolerates DCN hops),
# while `seq`/`tensor` are innermost so their latency-sensitive collectives
# (ring permutes, all-reduces) ride contiguous ICI neighborhoods.
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"
TENSOR_AXIS = "tensor"
SEQ_AXIS = "seq"
AXIS_ORDER = (DATA_AXIS, FSDP_AXIS, PIPE_AXIS, EXPERT_AXIS, SEQ_AXIS, TENSOR_AXIS)

_initialized = False


def setup_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize multi-host JAX if launched as part of a pod.

    Analog of ``Trainer.ddp_setup`` (``trainer/trainer.py:74-77``) — but a
    no-op on single-process launches (TPU pods discovered via TPU metadata, or
    explicit coordinator env vars mirroring torchrun's MASTER_ADDR/RANK/
    WORLD_SIZE contract from ``run.sh:12-13``).

    Env vars honored (all optional): ``COORDINATOR_ADDRESS``, ``NUM_PROCESSES``,
    ``PROCESS_ID``.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("NUM_PROCESSES"):
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and os.environ.get("PROCESS_ID"):
        process_id = int(os.environ["PROCESS_ID"])
    if coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    elif process_id is not None:
        raise ValueError(
            "PROCESS_ID is set but COORDINATOR_ADDRESS/NUM_PROCESSES are not — "
            "a partial distributed config would silently train N independent "
            "single-process worlds. Set all three (or none for single-process)."
        )
    # Single-process (including single-host TPU and CPU tests): nothing to do.


def shutdown_distributed() -> None:
    """Analog of ``destroy_process`` (``trainer/trainer.py:80-82``)."""
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    """This host's process index (analog of torchrun RANK for hosts)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """True on process 0 — the only process that writes logs/metadata,
    mirroring the reference's rank-0-only sections (``trainer/trainer.py:115,163``)."""
    return jax.process_index() == 0


def create_mesh(
    axes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named device mesh.

    ``axes`` maps axis name -> size; at most one size may be ``-1`` meaning
    "all remaining devices". Default is a 1-D data mesh over every global
    device — the TPU equivalent of the reference's flat DDP world
    (``trainer/trainer.py:48-52``).
    """
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {DATA_AXIS: -1})
    n = len(devices)
    known = 1
    wildcard = None
    for name, size in axes.items():
        if size == -1:
            if wildcard is not None:
                raise ValueError("at most one mesh axis may be -1")
            wildcard = name
        else:
            known *= size
    if wildcard is not None:
        if n % known:
            raise ValueError(f"{n} devices not divisible by fixed axes {axes}")
        axes[wildcard] = n // known
    total = int(np.prod(list(axes.values())))
    if total != n:
        raise ValueError(f"mesh {axes} needs {total} devices, have {n}")
    unknown = [a for a in axes if a not in AXIS_ORDER]
    if unknown:
        raise ValueError(f"unknown mesh axes {unknown}; known axes are {AXIS_ORDER}")
    # Canonical ordering keeps `data` outermost regardless of dict order.
    names = sorted(axes, key=AXIS_ORDER.index)
    shape = tuple(axes[name] for name in names)
    device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(device_array, axis_names=tuple(names))


def batch_sharding(mesh: Mesh, batch_axes: Sequence[str] | None = None) -> NamedSharding:
    """Sharding for a batch: leading dim split over the data-like mesh axes.

    Replaces ``DistributedSampler``'s per-rank row assignment
    (``trainer/trainer.py:215``) — the batch is one global ``jax.Array`` whose
    leading axis is sharded over ``data`` (and ``fsdp`` if present).
    """
    if batch_axes is None:
        batch_axes = [a for a in (DATA_AXIS, FSDP_AXIS) if a in mesh.axis_names]
    spec = P(tuple(batch_axes)) if batch_axes else P()
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def chain_batch_sharding(mesh: Mesh, batch_axes: Sequence[str] | None = None) -> NamedSharding:
    """Sharding for a chain-stacked batch ``[chain, batch, ...]``: the leading
    (step/time) axis stays unsharded — every device sees every step of the
    window — while the second (batch) axis splits over the data-like mesh axes
    exactly as :func:`batch_sharding` does. This is the input layout of the
    engine's chained train step (``TrainEngine.train_steps_chained``), whose
    ``lax.scan`` slices one per-step batch off the leading axis per trip."""
    if batch_axes is None:
        batch_axes = [a for a in (DATA_AXIS, FSDP_AXIS) if a in mesh.axis_names]
    spec = P(None, tuple(batch_axes)) if batch_axes else P()
    return NamedSharding(mesh, spec)


def device_coords(mesh: Mesh) -> "dict[int, tuple[int, ...]]":
    """Map global device id -> this mesh's axis coordinates (one tuple per
    axis in ``mesh.axis_names`` order). Replica groups in compiled HLO name
    devices by their global ids (``use_global_device_ids``); this map is how
    ``analysis.comm_audit`` attributes a collective's device groups back to
    the mesh axes they span — robust to ``mesh_utils`` device reorderings
    because it reads positions off ``mesh.devices`` itself."""
    coords: dict[int, tuple[int, ...]] = {}
    for idx in np.ndindex(mesh.devices.shape):
        coords[int(mesh.devices[idx].id)] = tuple(int(i) for i in idx)
    return coords


def batch_shard_extent(mesh: Mesh) -> int:
    """How many ways the batch dimension is sharded on ``mesh`` — the
    product of the batch-like axes present (``data`` x ``fsdp``, the axes
    :func:`batch_sharding` splits dim 0 over). This, NOT ``mesh.devices.
    size``, is the divisor for global-batch divisibility checks and
    per-replica throughput math: a ``data=2, tensor=4`` mesh runs 2 batch
    shards on 8 chips — every ``tensor`` group of 4 devices cooperates on
    ONE shard."""
    extent = 1
    for axis in (DATA_AXIS, FSDP_AXIS):
        extent *= int(mesh.shape.get(axis, 1))
    return max(1, extent)


# Mesh-spec grammar (the ``MESH``/``BENCH_MESH`` env-knob syntax; see
# docs/parallelism.md): either concatenated axis-size pairs ("dp2fsdp2tp2"
# -> data=2, fsdp=2, tensor=2) or the two-axis shorthand "<kind>KxD" where K
# is the kind's extent and D the data extent ("fsdp4x2" -> fsdp=4, data=2;
# "tp2x4" -> tensor=2, data=4). "dp8" -> pure 8-way data parallelism.
_SPEC_KINDS = {
    "dp": "data",
    "fsdp": "fsdp",
    "tp": "tensor",
    "sp": "seq",
    "pp": "pipe",
    "ep": "expert",
}
_SPEC_SHORT_RE = re.compile(r"^(fsdp|tp|sp|pp|ep)(\d+)x(\d+)$")
_SPEC_PAIRS_RE = re.compile(r"(fsdp|dp|tp|sp|pp|ep)(\d+)")


def mesh_config_from_spec(spec: str) -> "MeshConfig":
    """Parse a compact mesh spec string into a :class:`MeshConfig`.

    ``"dp8"`` -> 8-way data; ``"fsdp4x2"`` -> fsdp=4, data=2;
    ``"tp2x4"`` -> tensor=2, data=4; ``"dp2fsdp2tp2"`` -> data=2, fsdp=2,
    tensor=2. One grammar shared by the examples' ``MESH`` knob and
    ``bench.py``'s ``BENCH_MESH`` sweep."""
    text = spec.strip().lower()
    if not text:
        raise ValueError("empty mesh spec")
    m = _SPEC_SHORT_RE.match(text)
    if m:
        kind, extent, data = m.group(1), int(m.group(2)), int(m.group(3))
        return MeshConfig(**{"data": data, _SPEC_KINDS[kind]: extent})
    pairs = _SPEC_PAIRS_RE.findall(text)
    if not pairs or "".join(k + n for k, n in pairs) != text:
        raise ValueError(
            f"unparseable mesh spec {spec!r} — use axis-size pairs like "
            "'dp8', 'dp2fsdp2tp2', or the shorthand 'fsdp4x2' / 'tp2x4' "
            "(<kind><extent>x<data>)"
        )
    axes: dict[str, int] = {}
    for kind, n in pairs:
        name = _SPEC_KINDS[kind]
        if name in axes:
            raise ValueError(f"mesh spec {spec!r} names axis {name!r} twice")
        axes[name] = int(n)
    axes.setdefault("data", 1)
    return MeshConfig(**axes)


def mesh_from_env(var: str = "MESH") -> Mesh | None:
    """Resolve the examples' ``MESH`` env knob (docs/parallelism.md
    grammar via :func:`mesh_config_from_spec`) to a built mesh.
    Unset/empty = None = the historical 1-D data mesh — the one
    implementation shared by every example entry so the knob's semantics
    cannot drift between them."""
    spec = os.environ.get(var)
    if not spec:
        return None
    return mesh_config_from_spec(spec).build()


def local_batch_size(global_batch_size: int, mesh: Mesh) -> int:
    """Per-host batch size — global-batch semantics of ``trainer/trainer.py:56``
    (``batch_size // world_size``), except the divisor is host count because
    each host feeds all of its local devices in one global array."""
    n = jax.process_count()
    if global_batch_size % n:
        raise ValueError(f"global batch {global_batch_size} not divisible by {n} processes")
    return global_batch_size // n


def global_array_from_host_local(batch, mesh: Mesh) -> jax.Array:
    """Assemble a global, data-sharded ``jax.Array`` from this host's slice.

    The TPU analog of DDP's implicit "each rank holds its own batch rows":
    every host passes its local rows; the result is a single global array laid
    out across the mesh without any cross-host copy.
    """
    sharding = batch_sharding(mesh)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        batch,
    )


def global_chain_array_from_host_local(batch, mesh: Mesh) -> jax.Array:
    """Chain-major twin of :func:`global_array_from_host_local`: every leaf is
    ``[chain, local_batch, ...]`` (this host's rows of ``chain`` consecutive
    global batches stacked on a new leading axis) and assembles into one global
    ``[chain, global_batch, ...]`` array laid out per
    :func:`chain_batch_sharding` — one H2D staging call per window instead of
    one per step."""
    sharding = chain_batch_sharding(mesh)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        batch,
    )


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh spec (used by the config system and ``run.sh`` twin)."""

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        axes = {DATA_AXIS: self.data}
        for name, size in (
            (FSDP_AXIS, self.fsdp),
            (PIPE_AXIS, self.pipe),
            (EXPERT_AXIS, self.expert),
            (SEQ_AXIS, self.seq),
            (TENSOR_AXIS, self.tensor),
        ):
            if size != 1:
                axes[name] = size
        return create_mesh(axes, devices=devices)
