from distributed_training_pytorch_tpu.parallel.mesh import (  # noqa: F401
    setup_distributed,
    shutdown_distributed,
    create_mesh,
    batch_sharding,
    replicated_sharding,
    local_batch_size,
    process_index,
    process_count,
    is_coordinator,
    global_array_from_host_local,
)
