from distributed_training_pytorch_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    setup_distributed,
    shutdown_distributed,
    batch_shard_extent,
    create_mesh,
    batch_sharding,
    mesh_config_from_spec,
    mesh_from_env,
    replicated_sharding,
    local_batch_size,
    process_index,
    process_count,
    is_coordinator,
    global_array_from_host_local,
)
from distributed_training_pytorch_tpu.parallel.elastic import (  # noqa: F401
    ElasticPlan,
    ElasticReplanError,
    TopologyMismatchError,
    replan,
    replan_accum,
    validate_topology,
)
from distributed_training_pytorch_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ulysses_attention,
)
from distributed_training_pytorch_tpu.parallel.sharding import (  # noqa: F401
    default_sharding_rules,
    sharding_record,
    spec_for_leaf,
    state_shardings,
    transformer_tp_rules,
    tree_shard_bytes,
)
from distributed_training_pytorch_tpu.parallel.pipeline import (  # noqa: F401
    PIPE_AXIS,
    bubble_fraction,
    pipeline_apply,
    schedule_stats,
    stack_stage_params,
)
from distributed_training_pytorch_tpu.parallel.moe import (  # noqa: F401
    EXPERT_AXIS,
    MoEMlp,
    load_balance_loss,
    manual_expert_ffn_local,
    manual_expert_mlp,
    router_z_loss,
)
