"""Parameter/state sharding rules: FSDP and tensor parallelism via GSPMD.

The reference has exactly one parallelism strategy — DDP data parallelism with
fully replicated parameters (``trainer/trainer.py:51-52``, SURVEY.md §2c).
This module is the TPU-native extension to sharded parameters: instead of
wrapper modules (FSDP) or hand-written collectives (Megatron), parameters get
:class:`~jax.sharding.PartitionSpec` s and XLA's SPMD partitioner inserts the
all-gathers / reduce-scatters (ZeRO-3 analog) or TP collectives and overlaps
them with compute (the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives).

Two layers of rules, applied per state leaf:

1. **Explicit rules** — ``(path_regex, PartitionSpec)`` pairs matched against
   the leaf's tree path (e.g. ``(r"qkv.*kernel", P(None, "tensor"))`` for
   Megatron-style column-parallel attention projections).
2. **FSDP fallback** — when the mesh has a nontrivial ``fsdp`` axis, shard the
   largest divisible dimension of any leaf with >= ``fsdp_min_size`` elements;
   smaller leaves stay replicated (per-parameter ZeRO-3 with a size cutoff).

Optimizer state (momentum etc.) mirrors the param tree inside optax's state
pytrees, so the same path matching shards it identically — the optimizer
update stays fully local, like ZeRO's sharded optimizer states.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_training_pytorch_tpu.parallel.mesh import FSDP_AXIS, TENSOR_AXIS

_logger = logging.getLogger(__name__)

# A rule: (regex matched against the leaf path, spec to apply).
Rule = tuple[str, P]


def _spec_fits(spec: P, shape: tuple[int, ...], mesh: Mesh) -> bool:
    """A spec fits when every named dim exists in the mesh and divides the
    corresponding array dimension."""
    if len(spec) > len(shape):
        return False
    for dim, names in zip(shape, spec):
        if names is None:
            continue
        names = names if isinstance(names, tuple) else (names,)
        total = 1
        for name in names:
            if name not in mesh.shape:
                return False
            total *= mesh.shape[name]
        if dim % total:
            return False
    return True


def _fsdp_spec(shape: tuple[int, ...], mesh: Mesh, axis: str, min_size: int) -> P:
    """Shard the largest divisible dim over ``axis``; replicate if none fits."""
    if axis not in mesh.shape or mesh.shape[axis] <= 1:
        return P()
    size = 1
    for d in shape:
        size *= d
    if size < min_size:
        return P()
    n = mesh.shape[axis]
    order = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for i in order:
        if shape[i] % n == 0:
            spec = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


def spec_for_leaf(
    path: str,
    shape: tuple[int, ...],
    mesh: Mesh,
    rules: Sequence[Rule] = (),
    *,
    fsdp_axis: str = FSDP_AXIS,
    fsdp_min_size: int = 2**18,
) -> P:
    for pattern, spec in rules:
        if re.search(pattern, path):
            if _spec_fits(spec, shape, mesh):
                return spec
            # An explicit rule that matched but doesn't divide the array is
            # almost always a config mistake (e.g. heads % tensor != 0) that
            # would otherwise silently disable TP — say so loudly.
            _logger.warning(
                "sharding rule %r matched %s (shape %s) but spec %s does not fit "
                "mesh %s — falling back to FSDP/replicated",
                pattern, path, shape, spec, dict(mesh.shape),
            )
            break
    return _fsdp_spec(shape, mesh, fsdp_axis, fsdp_min_size)


def state_shardings(
    state: Any,
    mesh: Mesh,
    rules: Sequence[Rule] = (),
    *,
    fsdp_axis: str = FSDP_AXIS,
    fsdp_min_size: int = 2**18,
) -> Any:
    """NamedSharding tree matching ``state`` (a TrainState or any pytree of
    arrays / ShapeDtypeStructs). Scalars and sub-2D leaves typically fall out
    replicated via the size cutoff."""

    def leaf_sharding(key_path, leaf):
        path = jax.tree_util.keystr(key_path)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if not shape:
            return NamedSharding(mesh, P())
        spec = spec_for_leaf(
            path, shape, mesh, rules, fsdp_axis=fsdp_axis, fsdp_min_size=fsdp_min_size
        )
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(leaf_sharding, state)


# -- predefined tensor-parallel rule sets ----------------------------------

def transformer_tp_rules(tensor_axis: str = TENSOR_AXIS) -> list[Rule]:
    """Megatron-style TP for the ViT/transformer blocks in ``models.vit``:
    column-parallel qkv + MLP-in (output features sharded), row-parallel
    attention-out + MLP-out (input features sharded; XLA inserts the
    all-reduce the row-parallel matmul needs). Biases of column-parallel
    layers shard on their feature dim."""
    return [
        # qkv DenseGeneral kernel [D, 3, H, d] -> heads sharded.
        (r"qkv.*kernel", P(None, None, tensor_axis, None)),
        (r"qkv.*bias", P(None, tensor_axis, None)),
        # attention out DenseGeneral kernel [H, d, D] -> heads (input) sharded.
        (r"\bout\b.*kernel", P(tensor_axis, None, None)),
        # MLP: first Dense column-parallel, second row-parallel.
        (r"MlpBlock_\d+.*Dense_0.*kernel", P(None, tensor_axis)),
        (r"MlpBlock_\d+.*Dense_0.*bias", P(tensor_axis)),
        (r"MlpBlock_\d+.*Dense_1.*kernel", P(tensor_axis, None)),
    ]
