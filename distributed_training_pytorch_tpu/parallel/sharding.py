"""Parameter/state sharding rules: FSDP and tensor parallelism via GSPMD.

The reference has exactly one parallelism strategy — DDP data parallelism with
fully replicated parameters (``trainer/trainer.py:51-52``, SURVEY.md §2c).
This module is the TPU-native extension to sharded parameters: instead of
wrapper modules (FSDP) or hand-written collectives (Megatron), parameters get
:class:`~jax.sharding.PartitionSpec` s and XLA's SPMD partitioner inserts the
all-gathers / reduce-scatters (ZeRO-3 analog) or TP collectives and overlaps
them with compute (the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives).

Two layers of rules, applied per state leaf:

1. **Explicit rules** — ``(path_regex, PartitionSpec)`` pairs matched against
   the leaf's tree path (e.g. ``(r"qkv.*kernel", P(None, "tensor"))`` for
   Megatron-style column-parallel attention projections).
2. **FSDP fallback** — when the mesh has a nontrivial ``fsdp`` axis, shard the
   largest divisible dimension of any leaf with >= ``fsdp_min_size`` elements;
   smaller leaves stay replicated (per-parameter ZeRO-3 with a size cutoff).

Optimizer state (momentum etc.) mirrors the param tree inside optax's state
pytrees, so the same path matching shards it identically — the optimizer
update stays fully local, like ZeRO's sharded optimizer states.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_training_pytorch_tpu.parallel.mesh import FSDP_AXIS, TENSOR_AXIS

_logger = logging.getLogger(__name__)

# A rule: (regex matched against the leaf path, spec to apply).
Rule = tuple[str, P]


def _spec_fits(spec: P, shape: tuple[int, ...], mesh: Mesh) -> bool:
    """A spec fits when every named dim exists in the mesh and divides the
    corresponding array dimension."""
    if len(spec) > len(shape):
        return False
    # strict=False: a spec legally names fewer dims than the array has
    # (trailing dims replicated) — truncation here is the contract.
    for dim, names in zip(shape, spec, strict=False):
        if names is None:
            continue
        names = names if isinstance(names, tuple) else (names,)
        total = 1
        for name in names:
            if name not in mesh.shape:
                return False
            total *= mesh.shape[name]
        if dim % total:
            return False
    return True


def _fsdp_spec(shape: tuple[int, ...], mesh: Mesh, axis: str, min_size: int) -> P:
    """Shard the largest divisible dim over ``axis``; replicate if none fits."""
    if axis not in mesh.shape or mesh.shape[axis] <= 1:
        return P()
    size = 1
    for d in shape:
        size *= d
    if size < min_size:
        return P()
    n = mesh.shape[axis]
    order = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for i in order:
        if shape[i] % n == 0:
            spec = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


def rule_for_leaf(
    path: str,
    shape: tuple[int, ...],
    mesh: Mesh,
    rules: Sequence[Rule] = (),
) -> "tuple[str, P] | None":
    """The ``(pattern, spec)`` of the first explicit rule that matched AND
    fits this leaf, or None when the leaf takes the FSDP/replicated fallback.
    Split out of :func:`spec_for_leaf` so consumers that need *attribution*
    — ``analysis.comm_audit`` traces an accidental full-param gather back to
    the rule that sharded the leaf — resolve rules by exactly the dispatch
    path's matching order."""
    for pattern, spec in rules:
        if re.search(pattern, path):
            if _spec_fits(spec, shape, mesh):
                return pattern, spec
            # An explicit rule that matched but doesn't divide the array is
            # almost always a config mistake (e.g. heads % tensor != 0) that
            # would otherwise silently disable TP — say so loudly.
            _logger.warning(
                "sharding rule %r matched %s (shape %s) but spec %s does not fit "
                "mesh %s — falling back to FSDP/replicated",
                pattern, path, shape, spec, dict(mesh.shape),
            )
            break
    return None


def spec_for_leaf(
    path: str,
    shape: tuple[int, ...],
    mesh: Mesh,
    rules: Sequence[Rule] = (),
    *,
    fsdp_axis: str = FSDP_AXIS,
    fsdp_min_size: int = 2**18,
) -> P:
    matched = rule_for_leaf(path, shape, mesh, rules)
    if matched is not None:
        return matched[1]
    return _fsdp_spec(shape, mesh, fsdp_axis, fsdp_min_size)


def state_shardings(
    state: Any,
    mesh: Mesh,
    rules: Sequence[Rule] = (),
    *,
    fsdp_axis: str = FSDP_AXIS,
    fsdp_min_size: int = 2**18,
) -> Any:
    """NamedSharding tree matching ``state`` (a TrainState or any pytree of
    arrays / ShapeDtypeStructs). Scalars and sub-2D leaves typically fall out
    replicated via the size cutoff."""

    def leaf_sharding(key_path, leaf):
        path = jax.tree_util.keystr(key_path)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if not shape:
            return NamedSharding(mesh, P())
        spec = spec_for_leaf(
            path, shape, mesh, rules, fsdp_axis=fsdp_axis, fsdp_min_size=fsdp_min_size
        )
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(leaf_sharding, state)


# -- per-device shard accounting -------------------------------------------


def shard_shape(global_shape: tuple[int, ...], sharding) -> tuple[int, ...]:
    """The per-device shard shape a leaf of ``global_shape`` has under
    ``sharding`` (a :class:`~jax.sharding.NamedSharding`; None or any
    sharding-less object means replicated — the global shape)."""
    if sharding is None or not hasattr(sharding, "shard_shape"):
        return tuple(global_shape)
    return tuple(sharding.shard_shape(tuple(global_shape)))


def expand_shardings(tree: Any, shardings: Any) -> Any:
    """Broadcast ``shardings`` to match ``tree``'s structure: a single
    Sharding instance applies to every leaf (the engine's pure-DP
    ``state_sharding`` is ONE replicated NamedSharding, not a tree);
    a matching pytree passes through."""
    if isinstance(shardings, jax.sharding.Sharding):
        return jax.tree.map(lambda _: shardings, tree)
    return shardings


def tree_shard_bytes(tree: Any, shardings: Any = None) -> float:
    """Per-DEVICE byte total of a pytree: every leaf sized at its shard
    shape under ``shardings`` (see :func:`expand_shardings`; None = each
    leaf's own ``.sharding`` when it carries one, else replicated). This —
    not the global aval sum — is what an SPMD program's per-device
    ``memory_analysis()`` argument bytes correspond to."""
    from distributed_training_pytorch_tpu.utils.hlo_flops import aval_bytes

    if shardings is None:
        leaves = [
            (tuple(getattr(x, "shape", ()) or ()), getattr(x, "dtype", None),
             getattr(x, "sharding", None))
            for x in jax.tree.leaves(tree)
        ]
    else:
        shardings = expand_shardings(tree, shardings)
        # strict: a shardings tree covering only part of `tree` must error,
        # not silently truncate into an undercounted byte total (this sum
        # feeds memory attribution and the preflight OOM verdict).
        leaves = [
            (tuple(getattr(x, "shape", ()) or ()), getattr(x, "dtype", None), s)
            for x, s in zip(
                jax.tree.leaves(tree),
                jax.tree.leaves(
                    shardings,
                    is_leaf=lambda s: isinstance(s, jax.sharding.Sharding),
                ),
                strict=True,
            )
        ]
    return float(
        sum(aval_bytes(shard_shape(shape, s), dtype) for shape, dtype, s in leaves)
    )


def sharding_record(state: Any, shardings: Any = None) -> dict | None:
    """Compact JSON-safe description of a state's sharded layout — the
    checkpoint sharding-metadata record (docs/parallelism.md): the mesh's
    axis sizes plus the PartitionSpec of every NON-replicated leaf. None
    when nothing is sharded (a pure-DP / host-snapshot state) — pre-sharding
    checkpoints and sharded ones are distinguishable by the record's
    presence. Restore does not NEED the record (the restore target's own
    shardings drive the relayout); it exists so a checkpoint's layout is
    inspectable before building a restore target, and so resharding
    restores can be detected and logged."""
    if shardings is not None:
        shardings = expand_shardings(state, shardings)
        pairs = zip(
            jax.tree_util.tree_leaves_with_path(state),
            jax.tree.leaves(shardings,
                            is_leaf=lambda s: isinstance(s, jax.sharding.Sharding)),
            strict=True,  # partial shardings tree = caller bug, not "less sharded"
        )
        leaves = [(path, s) for (path, _), s in pairs]
    else:
        leaves = [
            (path, getattr(leaf, "sharding", None))
            for path, leaf in jax.tree_util.tree_leaves_with_path(state)
        ]
    mesh_axes: dict[str, int] = {}
    specs: dict[str, str] = {}
    for path, s in leaves:
        if not isinstance(s, NamedSharding):
            continue
        mesh_axes = {str(k): int(v) for k, v in s.mesh.shape.items()}
        if s.spec != P():
            specs[jax.tree_util.keystr(path)] = str(s.spec)
    if not mesh_axes or not specs:
        return None
    return {"mesh": mesh_axes, "specs": specs}


# -- predefined tensor-parallel rule sets ----------------------------------

def default_sharding_rules(mesh: Mesh) -> "list[Rule] | None":
    """The ONE default rule-resolution policy, shared by
    ``Trainer(sharding_rules="auto")`` (via its ``build_sharding_rules``
    hook), ``bench.py``'s BENCH_MESH setup, and the multichip dryrun — so
    the bench measures the same program the Trainer runs: a mesh with a
    nontrivial ``tensor`` axis gets :func:`transformer_tp_rules` (conv
    models match none of its patterns and take the FSDP fallback); any
    other mesh gets None (pure FSDP / replicated)."""
    if mesh.shape.get(TENSOR_AXIS, 1) > 1:
        return transformer_tp_rules()
    return None


def transformer_tp_rules(tensor_axis: str = TENSOR_AXIS) -> list[Rule]:
    """Megatron-style TP for the transformer blocks in the model zoo —
    ``models.vit`` (qkv/out/MlpBlock naming) and ``models.transformer_lm``
    (qkv/attn_out/mlp_in/mlp_out/embed/lm_head): column-parallel qkv +
    MLP-in (output features sharded), row-parallel attention-out + MLP-out
    (input features sharded; XLA inserts the all-reduce the row-parallel
    matmul needs). Biases of column-parallel layers shard on their feature
    dim. The LM's embedding table and untied head shard over the vocab dim
    (Megatron's vocab-parallel embedding; the tied head reuses the embed
    kernel, so the one rule covers both). Rules that match a leaf but do
    not divide it fall back to FSDP/replicated with a loud warning
    (:func:`spec_for_leaf`), so these rules are safe to apply zoo-wide —
    VGG/ResNet/ConvNeXt simply match nothing and take the FSDP path."""
    return [
        # qkv DenseGeneral kernel [D, 3, H, d] -> heads sharded (ViT + LM).
        (r"qkv.*kernel", P(None, None, tensor_axis, None)),
        (r"qkv.*bias", P(None, tensor_axis, None)),
        # attention out DenseGeneral kernel [H, d, D] -> heads (input)
        # sharded: ViT names it `out`, the LM `attn_out`.
        (r"(\bout\b|attn_out).*kernel", P(tensor_axis, None, None)),
        # MLP: first Dense column-parallel, second row-parallel (ViT's
        # MlpBlock Dense_0/Dense_1, the LM's mlp_in/mlp_out).
        (r"MlpBlock_\d+.*Dense_0.*kernel", P(None, tensor_axis)),
        (r"MlpBlock_\d+.*Dense_0.*bias", P(tensor_axis)),
        (r"MlpBlock_\d+.*Dense_1.*kernel", P(tensor_axis, None)),
        (r"mlp_in.*kernel", P(None, tensor_axis)),
        (r"mlp_in.*bias", P(tensor_axis)),
        (r"mlp_out.*kernel", P(tensor_axis, None)),
        # LM embedding [V, D] + untied head [D, V]: vocab-parallel.
        (r"\bembed\b.*embedding", P(tensor_axis, None)),
        (r"lm_head.*kernel", P(None, tensor_axis)),
    ]
