"""Named-checkpoint store with best / last / periodic policies + resume.

Capability twin of the reference snapshot subsystem
(``trainer/trainer.py:85-101`` ``_save_snapshot``/``_load_snapshot`` and the
policy logic at ``:114-135,163-172``):

* three named policies — ``best`` (on validation-metric improvement per a
  ``(metric, "geq"|"leq")`` rule, ``trainer/trainer.py:118-124``), ``last``
  (every validating epoch, ``:164-165``) and ``checkpoint_epoch_N`` (every
  ``save_period`` epochs otherwise, ``:166-167``);
* the snapshot payload {epoch, model, optimizer, scheduler state}
  (``:85-92``) becomes {TrainState pytree, meta json} — optax schedules are
  pure functions of ``state.step`` so there is no separate scheduler state;
* resume restores ``cur_epoch`` so the epoch loop continues mid-schedule
  (``:96-101``, ``:110``).

TPU-native differences: saving is a *collective* (every process calls
``save``; Orbax coordinates the single metadata write) so the reference's
rank-0 + barrier choreography (``trainer/trainer.py:163-172``) disappears, and
saves may run async so the step loop is not blocked on filesystem I/O.
"""

from __future__ import annotations

import os
from typing import Any, Mapping

import jax
import orbax.checkpoint as ocp

BEST = "best"
LAST = "last"


def epoch_checkpoint_name(epoch: int) -> str:
    """``checkpoint_epoch_{N}`` — the periodic-save name at ``trainer/trainer.py:166``."""
    return f"checkpoint_epoch_{epoch}"


class CheckpointManager:
    """Save/restore named checkpoints of a ``TrainState`` under ``directory``.

    ``save_best_for=(metric_name, mode)`` with mode ``"geq"`` or ``"leq"``
    mirrors the reference's best-fitness rule (``trainer/trainer.py:118-124``,
    configured ``("accuracy", "geq")`` at ``main.py:18``): ``geq`` saves when
    the new value is >= the best seen, ``leq`` when <=.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        save_best_for: tuple[str, str] | None = None,
        async_save: bool = True,
        max_to_keep: int | None = None,
    ):
        self.directory = os.path.abspath(os.fspath(directory))
        if jax.process_index() == 0:
            os.makedirs(self.directory, exist_ok=True)
        if save_best_for is not None:
            metric, mode = save_best_for
            if mode not in ("geq", "leq"):
                raise ValueError(f"save_best_for mode must be 'geq' or 'leq', got {mode!r}")
        self.save_best_for = save_best_for
        # Retention for the PERIODIC checkpoints only (checkpoint_epoch_N):
        # keep the newest `max_to_keep`, delete older ones after each commit.
        # `best`/`last` are policy names, never garbage-collected. Deletion
        # runs on process 0 (shared-filesystem assumption, same as Orbax's).
        self.max_to_keep = max_to_keep
        self._best_value: float | None = None
        handler = ocp.CompositeCheckpointHandler()
        self._ckptr = (
            ocp.AsyncCheckpointer(handler) if async_save else ocp.Checkpointer(handler)
        )

    # -- paths -------------------------------------------------------------

    def path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def exists(self, name: str) -> bool:
        # A checkpoint is complete once Orbax's commit marker logic has
        # finalized the directory; an in-flight async save is not yet visible.
        return os.path.isdir(self.path(name))

    # -- save --------------------------------------------------------------

    def save(self, name: str, state: Any, epoch: int, metrics: Mapping | None = None) -> None:
        """Collective save of ``state`` + meta under ``directory/name``.

        ``epoch`` is stored as the *resume* epoch — the caller passes the next
        epoch to train, matching the reference storing ``epoch + 1`` for
        ``last`` and ``epoch`` for ``best`` (``trainer/trainer.py:87,124,165``
        — the asymmetry is the caller's policy, not the store's).
        """
        self.wait()  # a name may be overwritten; finish any in-flight save first
        self._gc_periodic()  # previous save is committed; safe to prune now
        meta = {"epoch": int(epoch), "best_value": self._best_value}
        # Record the param tree's top level so consumers can auto-select the
        # restore target's wrapper layout (e.g. whether params nest under
        # InputNormalizer's 'inner' scope — ADVICE r4: keying that on a
        # mutable env var across train/resume/eval was a foot-gun).
        try:
            meta["params_top_level"] = sorted(state.params.keys())
        except AttributeError:
            pass
        if metrics is not None:
            meta["metrics"] = {k: float(v) for k, v in metrics.items()}
        # Decomposed layout (params / opt_state / rest) — the analog of the
        # reference saving model/optimizer/scheduler state dicts as separate
        # keys (``trainer/trainer.py:85-92``); it also lets consumers that
        # only need weights (offline eval) restore params alone even when
        # their optimizer differs from the training one.
        self._ckptr.save(
            self.path(name),
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(state.params),
                opt_state=ocp.args.StandardSave(state.opt_state),
                rest=ocp.args.StandardSave(
                    {"step": state.step, "rng": state.rng, "model_state": state.model_state}
                ),
                meta=ocp.args.JsonSave(meta),
            ),
            force=True,
        )

    def maybe_save_best(self, metrics: Mapping, state: Any, epoch: int) -> bool:
        """Apply the best-fitness rule; save under ``best`` on improvement.

        Returns True when a new best was saved (``trainer/trainer.py:118-130``).
        """
        if self.save_best_for is None:
            return False
        metric, mode = self.save_best_for
        if metric not in metrics:
            raise KeyError(
                f"save_best_for metric {metric!r} not in validation metrics {list(metrics)}"
            )
        value = float(metrics[metric])
        improved = (
            self._best_value is None
            or (mode == "geq" and value >= self._best_value)
            or (mode == "leq" and value <= self._best_value)
        )
        if improved:
            self._best_value = value
            self.save(BEST, state, epoch, metrics=metrics)
        return improved

    # -- restore -----------------------------------------------------------

    def restore(
        self, name_or_path: str, target_state: Any, *, params_only: bool = False
    ) -> tuple[Any, int]:
        """Restore ``(state, resume_epoch)`` from a named checkpoint or path.

        ``target_state`` is a concrete or abstract ``TrainState`` whose
        structure/shardings define the restore layout — the analog of calling
        ``_load_snapshot`` after ``build_model`` so keys line up
        (``trainer/trainer.py:44-45,96-101``).

        ``params_only=True`` restores weights and model_state but keeps the
        target's optimizer state/step — for consumers (offline eval,
        fine-tuning) whose optimizer differs from the training run's.
        """
        self.wait()  # an in-flight async save only becomes visible once committed
        path = self._resolve(name_or_path)
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target_state)
        items = {
            "params": ocp.args.StandardRestore(abstract.params),
            "meta": ocp.args.JsonRestore(),
        }
        if params_only:
            # Restore `rest` as stored (no target structure): only its
            # model_state is consumed, and imposing the target's rng layout
            # would fail when the eval process uses a different PRNG impl
            # than training did (threefry keys are 2 words, rbg 4).
            items["rest"] = ocp.args.StandardRestore()
        else:
            items["rest"] = ocp.args.StandardRestore(
                {
                    "step": abstract.step,
                    "rng": abstract.rng,
                    "model_state": abstract.model_state,
                }
            )
            items["opt_state"] = ocp.args.StandardRestore(abstract.opt_state)
        restored = self._ckptr.restore(path, args=ocp.args.Composite(**items))
        meta = restored.meta or {}
        if meta.get("best_value") is not None:
            self._best_value = float(meta["best_value"])
        state = target_state.replace(
            params=restored.params,
            model_state=restored.rest["model_state"],
        )
        if not params_only:
            state = state.replace(
                opt_state=restored.opt_state,
                step=restored.rest["step"],
                rng=restored.rest["rng"],
            )
        return state, int(meta.get("epoch", 0))

    def _resolve(self, name_or_path: str) -> str:
        """Name-or-path -> absolute checkpoint dir, with the existence and
        pre-0.1-layout checks every reader needs."""
        path = self.path(name_or_path) if os.sep not in name_or_path else name_or_path
        path = os.path.abspath(path)  # orbax rejects relative paths
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no checkpoint at {path}")
        if os.path.isdir(os.path.join(path, "state")):
            raise ValueError(
                f"{path} uses the pre-0.1 monolithic 'state' checkpoint layout; "
                "re-save it with this version (decomposed params/opt_state/rest)."
            )
        return path

    def read_meta(self, name_or_path: str) -> dict:
        """The checkpoint's meta json alone (epoch, best_value, metrics,
        params_top_level) — no state structure needed, so consumers can
        inspect a checkpoint's layout BEFORE building the restore target."""
        self.wait()
        restored = self._ckptr.restore(
            self._resolve(name_or_path),
            args=ocp.args.Composite(meta=ocp.args.JsonRestore()),
        )
        return dict(restored.meta or {})

    # -- lifecycle ---------------------------------------------------------

    @property
    def best_value(self) -> float | None:
        return self._best_value

    def wait(self) -> None:
        """Block until any in-flight async save has committed."""
        if isinstance(self._ckptr, ocp.AsyncCheckpointer):
            self._ckptr.wait_until_finished()

    def _gc_periodic(self) -> None:
        """Prune committed ``checkpoint_epoch_N`` dirs beyond ``max_to_keep``
        (newest kept). Call only with no save in flight."""
        if self.max_to_keep is None or jax.process_index() != 0:
            return
        import re
        import shutil

        pattern = re.compile(r"^checkpoint_epoch_(\d+)$")
        found = []
        for entry in os.listdir(self.directory):
            match = pattern.match(entry)
            if match and os.path.isdir(self.path(entry)):
                found.append((int(match.group(1)), entry))
        found.sort()
        for _, entry in found[: max(0, len(found) - self.max_to_keep)]:
            shutil.rmtree(self.path(entry), ignore_errors=True)

    def close(self) -> None:
        self.wait()
        self._gc_periodic()
        self._ckptr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
