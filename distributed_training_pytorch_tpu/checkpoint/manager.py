"""Named-checkpoint store with best / last / periodic policies + resume.

Capability twin of the reference snapshot subsystem
(``trainer/trainer.py:85-101`` ``_save_snapshot``/``_load_snapshot`` and the
policy logic at ``:114-135,163-172``):

* three named policies — ``best`` (on validation-metric improvement per a
  ``(metric, "geq"|"leq")`` rule, ``trainer/trainer.py:118-124``), ``last``
  (every validating epoch, ``:164-165``) and ``checkpoint_epoch_N`` (every
  ``save_period`` epochs otherwise, ``:166-167``);
* the snapshot payload {epoch, model, optimizer, scheduler state}
  (``:85-92``) becomes {TrainState pytree, meta json} — optax schedules are
  pure functions of ``state.step`` so there is no separate scheduler state;
* resume restores ``cur_epoch`` so the epoch loop continues mid-schedule
  (``:96-101``, ``:110``).

Crash consistency (the fault-tolerance upgrade over both the reference and
the plain Orbax layout):

* **Atomic commits** — every save lands in ``directory/.staging/<name>.<n>``
  first; only after the write fully completes (async saves included) is the
  staging dir renamed onto ``directory/<name>``. A reader can never observe
  a partially-written checkpoint under a final name, no matter where the
  process dies. Crash leftovers (orphaned staging dirs, a half-finished
  swap) are repaired on the next manager construction.
* **Integrity manifest** — at commit time every file's size + SHA-256 is
  recorded in ``manifest.dtp.json`` inside the checkpoint. ``validate``
  re-hashes on load; torn writes, flipped bits, and deleted files all raise
  :class:`CorruptCheckpointError` instead of feeding garbage to a restore.
* **Bounded retry** — transient write failures (``OSError``, including
  injected :class:`~distributed_training_pytorch_tpu.fault.InjectedFault`)
  are retried ``save_retries`` times with exponential backoff before a save
  is declared failed.
* **Newest-valid fallback** — :meth:`restore_latest_valid` walks committed
  checkpoints newest-first and restores the first that passes validation,
  so a corrupt ``last`` degrades to the previous good snapshot instead of
  killing the resume.

TPU-native differences: saving is a *collective* (every process calls
``save``; Orbax coordinates the single metadata write) so the reference's
rank-0 + barrier choreography (``trainer/trainer.py:163-172``) disappears, and
saves may run async so the step loop is not blocked on filesystem I/O.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

BEST = "best"
LAST = "last"

MANIFEST_NAME = "manifest.dtp.json"
_STAGING_DIR = ".staging"
_OLD_SUFFIX = ".old"


class CheckpointError(RuntimeError):
    """A save failed permanently (every retry exhausted)."""


class CorruptCheckpointError(CheckpointError):
    """A checkpoint on disk fails integrity validation."""


def epoch_checkpoint_name(epoch: int) -> str:
    """``checkpoint_epoch_{N}`` — the periodic-save name at ``trainer/trainer.py:166``."""
    return f"checkpoint_epoch_{epoch}"


def _is_typed_key(x) -> bool:
    try:
        return jnp.issubdtype(x.dtype, jax.dtypes.prng_key)
    except (AttributeError, TypeError):
        return False


class CheckpointManager:
    """Save/restore named checkpoints of a ``TrainState`` under ``directory``.

    ``save_best_for=(metric_name, mode)`` with mode ``"geq"`` or ``"leq"``
    mirrors the reference's best-fitness rule (``trainer/trainer.py:118-124``,
    configured ``("accuracy", "geq")`` at ``main.py:18``): ``geq`` saves when
    the new value is >= the best seen, ``leq`` when <=.

    ``save_retries``/``retry_backoff`` bound recovery from transient write
    failures; ``fault_plan`` wires a
    :class:`~distributed_training_pytorch_tpu.fault.FaultPlan` into the
    write path (test-only; production leaves it ``None``).
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        save_best_for: tuple[str, str] | None = None,
        async_save: bool = True,
        max_to_keep: int | None = None,
        save_retries: int = 2,
        retry_backoff: float = 0.25,
        fault_plan=None,
    ):
        self.directory = os.path.abspath(os.fspath(directory))
        if save_best_for is not None:
            metric, mode = save_best_for
            if mode not in ("geq", "leq"):
                raise ValueError(f"save_best_for mode must be 'geq' or 'leq', got {mode!r}")
        self.save_best_for = save_best_for
        # Retention for the PERIODIC checkpoints only (checkpoint_epoch_N):
        # keep the newest `max_to_keep`, delete older ones after each commit.
        # `best`/`last` are policy names, never garbage-collected. Deletion
        # runs on process 0 (shared-filesystem assumption, same as Orbax's).
        self.max_to_keep = max_to_keep
        self.save_retries = int(save_retries)
        self.retry_backoff = float(retry_backoff)
        self.fault_plan = fault_plan
        # Optional telemetry EventLog (duck-typed: anything with .emit).
        # restore_latest_valid reports each checkpoint it rejects while
        # scanning backward through it, so recovery skips land in the JSONL
        # flight record instead of only in free-text logger lines. The
        # trainer assigns it after constructing its event log; None (the
        # default) keeps the manager telemetry-free.
        self.event_log = None
        self._best_value: float | None = None
        self._staging_seq = 0
        # (staging_path, final_name, composite_args) of the in-flight save;
        # commit happens at the next wait()/save()/restore() boundary.
        self._pending: tuple[str, str, Any] | None = None
        if jax.process_index() == 0:
            os.makedirs(self.directory, exist_ok=True)
            self._recover_crash_leftovers()
        handler = ocp.CompositeCheckpointHandler()
        self._ckptr = (
            ocp.AsyncCheckpointer(handler) if async_save else ocp.Checkpointer(handler)
        )

    # -- paths -------------------------------------------------------------

    def path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def exists(self, name: str) -> bool:
        # A checkpoint is complete once the staging dir has been renamed onto
        # the final name; an in-flight async save is not yet visible.
        return os.path.isdir(self.path(name))

    def checkpoint_names(self) -> list[str]:
        """Committed checkpoint names, newest first (by directory mtime)."""
        found = []
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for entry in entries:
            if entry.startswith(".") or entry.endswith(_OLD_SUFFIX):
                continue
            p = self.path(entry)
            if os.path.isdir(p):
                found.append((os.path.getmtime(p), entry))
        found.sort(reverse=True)
        return [name for _, name in found]

    def _new_staging(self, name: str) -> str:
        self._staging_seq += 1
        return os.path.join(self.directory, _STAGING_DIR, f"{name}.{self._staging_seq}")

    def _recover_crash_leftovers(self) -> None:
        """Repair the crash windows: a half-finished swap (``<name>.old``
        present), and staging dirs from saves that never committed. A staging
        dir that exists under its plain ``<name>.<seq>`` name holds a COMPLETE
        write (Orbax renames its own tmp dir there only on finish) — e.g. an
        async save whose process died between write-finish and the next
        wait(); such checkpoints are promoted, not discarded."""
        for entry in os.listdir(self.directory):
            if not entry.endswith(_OLD_SUFFIX):
                continue
            old_path = self.path(entry)
            if not os.path.isdir(old_path):
                continue
            final = self.path(entry[: -len(_OLD_SUFFIX)])
            if os.path.isdir(final):
                # crash after the new checkpoint landed: old copy is garbage
                shutil.rmtree(old_path, ignore_errors=True)
            else:
                # crash between the two renames: roll the old copy back
                os.rename(old_path, final)
        staging_root = os.path.join(self.directory, _STAGING_DIR)
        if os.path.isdir(staging_root):
            for entry in sorted(os.listdir(staging_root)):
                path = os.path.join(staging_root, entry)
                # Orbax in-flight tmp dirs (write never finished) stay garbage.
                if not os.path.isdir(path) or "orbax" in entry.lower():
                    continue
                name = entry.rsplit(".", 1)[0]
                final = self.path(name)
                if os.path.isdir(final):
                    continue  # never clobber a committed checkpoint
                try:
                    self._write_manifest(path)
                    os.rename(path, final)
                except OSError:
                    pass  # unreadable leftovers are swept below
            shutil.rmtree(staging_root, ignore_errors=True)

    # -- save --------------------------------------------------------------

    def save(
        self,
        name: str,
        state: Any,
        epoch: int,
        metrics: Mapping | None = None,
        loop_state: Mapping | None = None,
        telemetry: Mapping | None = None,
        sharding: Mapping | None = None,
        data_state: Mapping | None = None,
    ) -> None:
        """Collective save of ``state`` + meta under ``directory/name``.

        ``epoch`` is stored as the *resume* epoch — the caller passes the next
        epoch to train, matching the reference storing ``epoch + 1`` for
        ``last`` and ``epoch`` for ``best`` (``trainer/trainer.py:87,124,165``
        — the asymmetry is the caller's policy, not the store's).

        ``loop_state`` carries mid-epoch resume info (e.g. ``step_in_epoch``
        for a preemption save) into the meta json, so a resumed run can skip
        already-trained batches and stay bit-exact with an uninterrupted one.

        ``telemetry`` carries cumulative run-accounting counters (the
        trainer's goodput buckets, ``telemetry/goodput.py``) into the meta
        json the same way — json round-trips Python floats exactly, so a
        resumed run's counters are bit-identical to the saved ones.

        ``sharding`` is the state's sharding-metadata record
        (``parallel.sharding.sharding_record``: mesh axis sizes + the
        PartitionSpec of every sharded leaf). When None it is derived from
        ``state``'s live leaves — callers whose state was already
        snapshotted to host numpy (the async saver) pass the record they
        captured from the live arrays, because ``device_get`` strips
        shardings. Orbax writes the GLOBAL array either way (every process
        contributes its addressable shards); the record documents the
        layout the run trained in, and lets a restore into a different mesh
        be detected and logged as a resharding restore
        (docs/parallelism.md).

        ``data_state`` is the streaming reader's checkpoint-carried state
        (``data.streaming.state.ReaderState.to_json()``: epoch, global
        record cursor, shuffle seed, shard structure, assignment version).
        It rides as its own ``data/`` composite item under the same rule as
        the loss-scale item: present only when the run streams, and a
        missing item means "fresh cursor" — so pre-streaming checkpoints,
        non-streaming runs, and streaming runs all restore against any
        target (:meth:`read_data_state`).
        """
        self.wait()  # a name may be overwritten; finish any in-flight save first
        self._gc_periodic()  # previous save is committed; safe to prune now
        meta = {"epoch": int(epoch), "best_value": self._best_value}
        # Record the param tree's top level so consumers can auto-select the
        # restore target's wrapper layout (e.g. whether params nest under
        # InputNormalizer's 'inner' scope — ADVICE r4: keying that on a
        # mutable env var across train/resume/eval was a foot-gun).
        try:
            meta["params_top_level"] = sorted(state.params.keys())
        except AttributeError:
            pass
        if metrics is not None:
            meta["metrics"] = {k: float(v) for k, v in metrics.items()}
        if loop_state is not None:
            meta["loop"] = {k: int(v) for k, v in loop_state.items()}
        if telemetry is not None:
            meta["telemetry"] = dict(telemetry)
            # Attempt provenance (ISSUE 16): the restart generation that
            # wrote this checkpoint rides the telemetry mapping from the
            # trainer but is hoisted to a first-class meta field — "which
            # attempt produced the state I'm about to resume from?" is a
            # recovery question, not a goodput-accounting one, and hoisting
            # keeps every save path's signature unchanged.
            if "attempt" in meta["telemetry"]:
                meta["attempt"] = int(meta["telemetry"].pop("attempt"))
        if sharding is None:
            from distributed_training_pytorch_tpu.parallel.sharding import (
                sharding_record,
            )

            sharding = sharding_record(state)
        if sharding is not None:
            meta["sharding"] = dict(sharding)
        # Typed PRNG keys carry an extended dtype serializers reject; store
        # the raw key words + impl name and rebuild on restore (this is also
        # what makes params_only restores work across PRNG impls — key
        # widths differ: threefry 2 words, rbg 4).
        rest = {"step": state.step, "model_state": state.model_state}
        if _is_typed_key(state.rng):
            rest["rng_data"] = jax.random.key_data(state.rng)
            meta["rng_impl"] = str(jax.random.key_impl(state.rng))
        else:
            rest["rng_data"] = state.rng
            meta["rng_impl"] = None
        # Decomposed layout (params / opt_state / rest [/ scale]) — the analog
        # of the reference saving model/optimizer/scheduler state dicts as
        # separate keys (``trainer/trainer.py:85-92``); it also lets consumers
        # that only need weights (offline eval) restore params alone even when
        # their optimizer differs from the training one.
        items = {
            "params": ocp.args.StandardSave(state.params),
            "opt_state": ocp.args.StandardSave(state.opt_state),
            "rest": ocp.args.StandardSave(rest),
        }
        # Mixed-precision loss-scale state (precision.loss_scale) rides as its
        # OWN composite item, present only when it has array leaves (a
        # DynamicScale; None/NoOpScale states save the pre-precision layout
        # verbatim) — so pre-precision checkpoints, fp32 checkpoints, and
        # fp16 checkpoints all restore against any target: a missing item
        # means "keep the target's fresh default scale".
        scale_state = getattr(state, "loss_scale", None)
        if jax.tree.leaves(scale_state):
            from flax import serialization

            items["scale"] = ocp.args.StandardSave(
                serialization.to_state_dict(scale_state)
            )
            meta["loss_scale"] = type(scale_state).__name__
        if data_state:
            items["data"] = ocp.args.JsonSave(dict(data_state))
        args = ocp.args.Composite(meta=ocp.args.JsonSave(meta), **items)
        staging = self._new_staging(name)
        try:
            self._attempt_save(staging, args, blocking=False)
        except OSError as e:
            self._pending = (staging, name, args)
            self._retry_pending(e)
            return
        self._pending = (staging, name, args)
        if not isinstance(self._ckptr, ocp.AsyncCheckpointer):
            self._finalize_pending()

    def _attempt_save(self, staging: str, args, *, blocking: bool) -> None:
        if self.fault_plan is not None:
            self.fault_plan.maybe_raise("checkpoint_write")
        self._ckptr.save(staging, args=args, force=True)
        if blocking and isinstance(self._ckptr, ocp.AsyncCheckpointer):
            self._ckptr.wait_until_finished()

    def _retry_pending(self, first_error: BaseException) -> None:
        """Blocking retry of the pending save with exponential backoff;
        commits on success, raises :class:`CheckpointError` when exhausted."""
        staging, name, args = self._pending
        self._pending = None
        err: BaseException = first_error
        delay = self.retry_backoff
        for _ in range(self.save_retries):
            shutil.rmtree(staging, ignore_errors=True)
            time.sleep(delay)
            delay *= 2
            staging = self._new_staging(name)
            try:
                self._attempt_save(staging, args, blocking=True)
            except OSError as e:
                err = e
                continue
            self._commit(staging, name)
            self._commit_barrier()
            return
        shutil.rmtree(staging, ignore_errors=True)
        # Failure must still reach the commit barrier: peers whose local
        # write succeeded are already waiting in it — raising without
        # aligning would deadlock every other host.
        self._commit_barrier()
        raise CheckpointError(
            f"checkpoint save of {name!r} failed after {self.save_retries + 1} attempts"
        ) from err

    def _finalize_pending(self) -> None:
        """Drive the in-flight save to a committed (or failed) end state.

        For async saves the commit (manifest + rename) runs at the next
        manager call rather than from Orbax's background thread — a write
        that finished mid-epoch sits complete-but-uncommitted in .staging
        until then. A crash in that window does NOT lose it: recovery
        promotes completed staging dirs (see ``_recover_crash_leftovers``).
        """
        if self._pending is None:
            return
        staging, name, args = self._pending
        if isinstance(self._ckptr, ocp.AsyncCheckpointer):
            try:
                self._ckptr.wait_until_finished()
            except OSError as e:
                self._retry_pending(e)
                return
        self._commit(staging, name)
        self._pending = None
        self._commit_barrier()

    def _commit_barrier(self) -> None:
        """Multi-host alignment: a non-zero process must not observe its
        wait() returning before process 0's staging→final rename has
        happened (exists()/restore() right after a collective save would
        otherwise race the commit). Saves are collective, so every process
        reaches this barrier exactly once per finalized save."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("dtp_checkpoint_commit")

    def _commit(self, staging: str, name: str) -> None:
        """Manifest + atomic swap. The final name flips from old checkpoint
        (or absent) to fully-written new checkpoint in one rename."""
        if jax.process_index() == 0:
            self._write_manifest(staging)
            final = self.path(name)
            old = final + _OLD_SUFFIX
            if os.path.isdir(final):
                if os.path.isdir(old):
                    shutil.rmtree(old)
                os.rename(final, old)
            os.rename(staging, final)
            # Persist the rename itself (manifest file data is fsync'd at
            # write; payload durability is the writer's concern) — without
            # this a power loss can resurrect the pre-rename directory view.
            dirfd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
            shutil.rmtree(old, ignore_errors=True)
            if self.fault_plan is not None:
                ev = self.fault_plan.fires("corrupt_checkpoint")
                if ev is not None:
                    from distributed_training_pytorch_tpu.fault.inject import (
                        corrupt_checkpoint,
                    )

                    corrupt_checkpoint(final, mode=ev.payload or "truncate")

    def _write_manifest(self, staging: str) -> None:
        entries = {}
        for dirpath, _, files in os.walk(staging):
            for fname in files:
                fp = os.path.join(dirpath, fname)
                rel = os.path.relpath(fp, staging)
                if rel == MANIFEST_NAME:
                    continue
                digest = hashlib.sha256()
                with open(fp, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        digest.update(chunk)
                entries[rel] = {
                    "size": os.path.getsize(fp),
                    "sha256": digest.hexdigest(),
                }
        with open(os.path.join(staging, MANIFEST_NAME), "w") as f:  # jaxlint: disable=file-write-without-rank-gate -- both call sites are process_index()==0-gated (save path and ctor crash recovery); the gate is one frame up, outside this helper's lexical scope
            json.dump({"version": 1, "files": entries}, f)
            f.flush()
            os.fsync(f.fileno())

    def best_improved(self, metrics: Mapping) -> bool:
        """Apply the best-fitness rule and record a new best value — WITHOUT
        saving. Split from :meth:`maybe_save_best` so the async save path
        (``resilience.AsyncCheckpointSaver.maybe_save_best``) can evaluate
        the rule on-thread and route the save through its own queue."""
        if self.save_best_for is None:
            return False
        metric, mode = self.save_best_for
        if metric not in metrics:
            raise KeyError(
                f"save_best_for metric {metric!r} not in validation metrics {list(metrics)}"
            )
        value = float(metrics[metric])
        improved = (
            self._best_value is None
            or (mode == "geq" and value >= self._best_value)
            or (mode == "leq" and value <= self._best_value)
        )
        if improved:
            self._best_value = value
        return improved

    def maybe_save_best(
        self,
        metrics: Mapping,
        state: Any,
        epoch: int,
        telemetry: Mapping | None = None,
        data_state: Mapping | None = None,
    ) -> bool:
        """Apply the best-fitness rule; save under ``best`` on improvement.

        Returns True when a new best was saved (``trainer/trainer.py:118-130``).
        """
        if not self.best_improved(metrics):
            return False
        self.save(
            BEST, state, epoch, metrics=metrics, telemetry=telemetry,
            data_state=data_state,
        )
        return True

    # -- integrity ---------------------------------------------------------

    def validate(self, name_or_path: str) -> None:
        """Verify the checkpoint against its integrity manifest.

        Raises :class:`CorruptCheckpointError` on a missing manifest, a
        missing/extra-truncated file, a size mismatch, or a hash mismatch —
        i.e. on every artifact a torn write or bit rot can produce.
        """
        self.wait()
        path = self._resolve(name_or_path)
        manifest_path = os.path.join(path, MANIFEST_NAME)
        if not os.path.isfile(manifest_path):
            raise CorruptCheckpointError(
                f"{path}: no integrity manifest ({MANIFEST_NAME}) — checkpoint "
                "was not committed by this manager or the commit was torn"
            )
        try:
            with open(manifest_path, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
            raise CorruptCheckpointError(f"{path}: unreadable manifest: {e}") from e
        for rel, want in manifest.get("files", {}).items():
            fp = os.path.join(path, rel)
            if not os.path.isfile(fp):
                raise CorruptCheckpointError(f"{path}: missing file {rel}")
            size = os.path.getsize(fp)
            if size != want["size"]:
                raise CorruptCheckpointError(
                    f"{path}: {rel} is {size} bytes, manifest says {want['size']} "
                    "(torn write)"
                )
            digest = hashlib.sha256()
            with open(fp, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    digest.update(chunk)
            if digest.hexdigest() != want["sha256"]:
                raise CorruptCheckpointError(f"{path}: {rel} content hash mismatch")

    def is_valid(self, name_or_path: str) -> bool:
        try:
            self.validate(name_or_path)
            return True
        except (CorruptCheckpointError, FileNotFoundError, ValueError):
            return False

    # -- restore -----------------------------------------------------------

    def restore(
        self,
        name_or_path: str,
        target_state: Any,
        *,
        params_only: bool = False,
        validate: bool = True,
        allow_topology_change: bool = False,
    ) -> tuple[Any, int]:
        """Restore ``(state, resume_epoch)`` from a named checkpoint or path.

        ``target_state`` is a concrete or abstract ``TrainState`` whose
        structure/shardings define the restore layout — the analog of calling
        ``_load_snapshot`` after ``build_model`` so keys line up
        (``trainer/trainer.py:44-45,96-101``).

        ``params_only=True`` restores weights and model_state but keeps the
        target's optimizer state/step — for consumers (offline eval,
        fine-tuning) whose optimizer differs from the training run's.

        ``validate=False`` skips the integrity check (reading a checkpoint
        produced by an external Orbax writer with no manifest).

        A sharded checkpoint whose recorded mesh covers a different device
        count than this backend raises
        :class:`~distributed_training_pytorch_tpu.parallel.elastic.
        TopologyMismatchError` up front, naming both topologies — instead of
        failing deep inside orbax with no mention of topology.
        ``allow_topology_change=True`` proceeds (the elastic-restore path:
        the caller has laid ``target_state`` out for the *current* backend,
        e.g. via ``parallel.elastic.replan`` — the Trainer does this
        automatically); the stored global arrays then relay into the
        target's shardings exactly as any resharding restore does.

        Checkpoints written before the crash-consistency upgrade (no
        ``rng_impl`` in meta, rng stored as a key array under ``rest.rng``,
        no manifest) still restore: their rest tree is read as stored and
        validation is skipped for the manifest they never had.
        """
        self.wait()  # an in-flight async save only becomes visible once committed
        path = self._resolve(name_or_path)
        has_manifest = os.path.isfile(os.path.join(path, MANIFEST_NAME))
        if validate and has_manifest:
            # Validate BEFORE any read: a torn meta json must surface as
            # CorruptCheckpointError (hash mismatch), not a raw orbax error.
            self.validate(path)
        try:
            pre_meta = self.read_meta(path)
        except Exception as e:  # orbax raises various things on torn json
            raise CorruptCheckpointError(f"{path}: unreadable meta: {e}") from e
        legacy = "rng_impl" not in pre_meta
        if validate and not has_manifest and not legacy:
            # current-format checkpoint with its manifest gone: torn commit
            self.validate(path)  # raises the canonical no-manifest error
        # Topology seam (ISSUE 12): a recorded mesh whose device product
        # disagrees with the backend must fail HERE with names attached —
        # not as an opaque orbax sharding-deserialization error — unless the
        # caller explicitly opted into the elastic path. A record-less
        # checkpoint (pure DP / pre-sharding) has no topology to validate:
        # its global arrays restore onto any backend.
        topo_changed = False
        record = pre_meta.get("sharding")
        if record:
            from distributed_training_pytorch_tpu.parallel.elastic import (
                TopologyMismatchError,
                validate_topology,
            )

            try:
                validate_topology(
                    record,
                    jax.device_count(),
                    name=f"checkpoint {os.path.basename(path)!r}",
                )
            except TopologyMismatchError:
                topo_changed = True
                if not allow_topology_change:
                    raise
        # to_shape_dtype_struct preserves each live leaf's NamedSharding, so
        # the restore target's layout — replicated for DP, fsdp/tensor
        # shards otherwise — drives where orbax lays the bytes. That is what
        # makes restore RESHARDING-CAPABLE: a checkpoint written under one
        # mesh restores into any other (DP <-> FSDP both directions,
        # test-enforced) because orbax reads the stored global array and
        # places the target's shards, whatever the writer's layout was.
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target_state)
        self._note_reshard(name_or_path, pre_meta, target_state)
        items = {
            "params": ocp.args.StandardRestore(abstract.params),
            "meta": ocp.args.JsonRestore(),
        }
        if (params_only and not topo_changed) or legacy:
            # Restore `rest` as stored (no target structure): params_only
            # consumes only its model_state, and a legacy rest tree has a
            # different key layout than the current target would impose.
            # On a topology-changed restore the as-stored read is the one
            # path that WOULD die deep in orbax (the stored sharding files
            # name the writer's devices), so params_only then takes the
            # targeted branch below — trading the cross-PRNG-impl width
            # leniency (a same-topology-only concern) for restorability.
            items["rest"] = ocp.args.StandardRestore()
        else:
            # rng is stored as raw key words; recover their aval from the
            # target's key (works across impls of the same width; differing
            # widths restore shape-as-stored below). eval_shape strips the
            # sharding, so it is re-attached from the target key — without
            # it orbax falls back to the checkpoint's sharding file, which
            # is exactly wrong on a resharding restore.
            rng_data = jax.eval_shape(
                lambda k: jax.random.key_data(k) if _is_typed_key(k) else k,
                abstract.rng,
            )
            rng_sharding = getattr(target_state.rng, "sharding", None)
            if isinstance(rng_sharding, jax.sharding.NamedSharding):
                rng_data = jax.ShapeDtypeStruct(
                    rng_data.shape,
                    rng_data.dtype,
                    sharding=jax.sharding.NamedSharding(
                        rng_sharding.mesh, jax.sharding.PartitionSpec()
                    ),
                )
            items["rest"] = ocp.args.StandardRestore(
                {
                    "step": abstract.step,
                    "model_state": abstract.model_state,
                    "rng_data": rng_data,
                }
            )
        if not params_only:
            items["opt_state"] = ocp.args.StandardRestore(abstract.opt_state)
        # Loss-scale state: restored only when BOTH sides speak it — the
        # checkpoint carries a `scale` item AND the target state has scale
        # leaves to lay it into. A pre-precision (or fp32) checkpoint under
        # a dynamic-scale target leaves the target's fresh default in place;
        # a dynamic-scale checkpoint under an fp32 target drops the scale.
        target_scale = getattr(target_state, "loss_scale", None)
        restore_scale = (
            not params_only
            and bool(jax.tree.leaves(target_scale))
            and os.path.isdir(os.path.join(path, "scale"))
        )
        if restore_scale:
            from flax import serialization

            items["scale"] = ocp.args.StandardRestore(
                serialization.to_state_dict(abstract.loss_scale)
            )
        restored = self._ckptr.restore(path, args=ocp.args.Composite(**items))
        meta = restored.meta or {}
        if meta.get("best_value") is not None:
            self._best_value = float(meta["best_value"])
        state = target_state.replace(
            params=restored.params,
            model_state=restored.rest["model_state"],
        )
        if not params_only:
            rng = self._restored_rng(restored.rest, meta, target_state.rng)
            state = state.replace(
                opt_state=restored.opt_state,
                step=restored.rest["step"],
                rng=rng,
            )
        if restore_scale:
            from flax import serialization

            state = state.replace(
                loss_scale=serialization.from_state_dict(target_scale, restored.scale)
            )
        return state, int(meta.get("epoch", 0))

    def _note_reshard(self, name: str, pre_meta: Mapping, target_state: Any) -> None:
        """Detect a resharding restore — the checkpoint's recorded layout
        differs from the restore target's — and put it in the flight record
        (``checkpoint_reshard`` event; docs/observability.md). Detection
        only: the relayout itself is orbax's restore doing its normal job
        against the target shardings. A missing stored record means pure-DP
        / pre-sharding — restoring THAT into a sharded target (or a sharded
        checkpoint into a DP target) is the DP<->FSDP elasticity path and
        is still logged."""
        if self.event_log is None:
            return
        from distributed_training_pytorch_tpu.parallel.sharding import (
            sharding_record,
        )

        stored = pre_meta.get("sharding")
        target = sharding_record(target_state)
        if stored == target:
            return
        self.event_log.emit(
            "checkpoint_reshard",
            name=os.path.basename(str(name)),
            from_mesh=(stored or {}).get("mesh"),
            to_mesh=(target or {}).get("mesh"),
            from_sharded_leaves=len((stored or {}).get("specs", {})),
            to_sharded_leaves=len((target or {}).get("specs", {})),
        )

    @staticmethod
    def _restored_rng(rest: Mapping, meta: Mapping, target_rng):
        """Rebuild the PRNG key from either storage format: current (raw key
        words under ``rng_data`` + impl in meta) or legacy (key array under
        ``rng``, possibly deserialized as raw words)."""
        if "rng_data" in rest:
            impl = meta.get("rng_impl")
            data = rest["rng_data"]
            return jax.random.wrap_key_data(jnp.asarray(data), impl=impl) if impl else data
        rng = rest["rng"]
        if _is_typed_key(target_rng) and not _is_typed_key(rng):
            try:
                rng = jax.random.wrap_key_data(
                    jnp.asarray(rng), impl=str(jax.random.key_impl(target_rng))
                )
            except (TypeError, ValueError):
                pass  # width mismatch: hand back as stored
        return rng

    def latest_valid_name(self) -> "str | None":
        """The name ``restore_latest_valid`` would restore — the newest
        committed checkpoint passing integrity validation, or None when no
        valid checkpoint exists. Lets consumers (the trainer's elastic-resume
        peek) inspect the resume checkpoint's meta BEFORE building a restore
        target, with exactly the fallback-past-corruption choice the real
        restore will make; rejected checkpoints emit ``checkpoint_rejected``
        the same way."""
        self.wait()
        return self._latest_valid_name([])

    def _latest_valid_name(self, skipped: list) -> "str | None":
        for name in self.checkpoint_names():
            try:
                self.validate(name)
            except (CorruptCheckpointError, FileNotFoundError, ValueError) as e:
                skipped.append(name)
                if self.event_log is not None:
                    # Recovery skips become flight-record facts (ISSUE 5):
                    # a torn preemption save silently degrading the resume
                    # to an older snapshot is visible in the JSONL log, not
                    # only in logger text.
                    self.event_log.emit(
                        "checkpoint_rejected", name=name, reason=str(e)
                    )
                continue
            return name
        return None

    def restore_latest_valid(
        self,
        target_state: Any,
        *,
        params_only: bool = False,
        allow_topology_change: bool = False,
    ) -> tuple[Any, int, str]:
        """Restore from the newest checkpoint that passes validation.

        Walks committed checkpoints newest-first; a corrupt ``last`` (torn
        preemption save, bit rot) falls back to the previous good snapshot
        instead of crashing the resume. Returns ``(state, epoch, name)``;
        raises :class:`CheckpointError` when nothing valid remains.
        """
        self.wait()
        skipped: list = []
        name = self._latest_valid_name(skipped)
        if name is None:
            raise CheckpointError(
                f"no valid checkpoint under {self.directory} "
                f"(invalid/corrupt: {skipped or 'none found'})"
            )
        # validate=False: _latest_valid_name just hashed every file;
        # re-validating inside restore would double the resume path's disk
        # reads.
        state, epoch = self.restore(
            name,
            target_state,
            params_only=params_only,
            validate=False,
            allow_topology_change=allow_topology_change,
        )
        return state, epoch, name

    def _resolve(self, name_or_path: str) -> str:
        """Name-or-path -> absolute checkpoint dir, with the existence and
        pre-0.1-layout checks every reader needs."""
        path = self.path(name_or_path) if os.sep not in name_or_path else name_or_path
        path = os.path.abspath(path)  # orbax rejects relative paths
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no checkpoint at {path}")
        if os.path.isdir(os.path.join(path, "state")):
            raise ValueError(
                f"{path} uses the pre-0.1 monolithic 'state' checkpoint layout; "
                "re-save it with this version (decomposed params/opt_state/rest)."
            )
        return path

    def read_meta(self, name_or_path: str) -> dict:
        """The checkpoint's meta json alone (epoch, best_value, metrics,
        params_top_level, loop state) — no state structure needed, so
        consumers can inspect a checkpoint's layout BEFORE building the
        restore target."""
        self.wait()
        restored = self._ckptr.restore(
            self._resolve(name_or_path),
            args=ocp.args.Composite(meta=ocp.args.JsonRestore()),
        )
        return dict(restored.meta or {})

    def read_data_state(self, name_or_path: str) -> "dict | None":
        """The checkpoint's streaming reader state (``data/`` item), or None
        when the checkpoint has none — a pre-streaming checkpoint or a
        non-streaming run. The None IS the contract (the loss-scale item
        rule): a missing item means "fresh cursor", so old checkpoints load
        into streaming runs without fabricating a position."""
        self.wait()
        path = self._resolve(name_or_path)
        # Gate on the item directory like the scale-item restore does:
        # requesting an absent composite item from orbax is an error, not
        # a None.
        if not os.path.isdir(os.path.join(path, "data")):
            return None
        restored = self._ckptr.restore(
            path, args=ocp.args.Composite(data=ocp.args.JsonRestore())
        )
        return dict(restored.data or {})

    # -- lifecycle ---------------------------------------------------------

    @property
    def best_value(self) -> float | None:
        return self._best_value

    def wait(self) -> None:
        """Block until any in-flight save has fully committed (write finished
        AND atomically renamed to its final name)."""
        self._finalize_pending()
        if isinstance(self._ckptr, ocp.AsyncCheckpointer):
            self._ckptr.wait_until_finished()

    def _gc_periodic(self) -> None:
        """Prune committed ``checkpoint_epoch_N`` dirs beyond ``max_to_keep``
        (newest kept). Call only with no save in flight."""
        if self.max_to_keep is None or jax.process_index() != 0:
            return
        import re

        pattern = re.compile(r"^checkpoint_epoch_(\d+)$")
        found = []
        for entry in os.listdir(self.directory):
            match = pattern.match(entry)
            if match and os.path.isdir(self.path(entry)):
                found.append((int(match.group(1)), entry))
        found.sort()
        for _, entry in found[: max(0, len(found) - self.max_to_keep)]:
            shutil.rmtree(self.path(entry), ignore_errors=True)

    def close(self) -> None:
        self.wait()
        self._gc_periodic()
        if jax.process_index() == 0:
            shutil.rmtree(os.path.join(self.directory, _STAGING_DIR), ignore_errors=True)
        self._ckptr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
