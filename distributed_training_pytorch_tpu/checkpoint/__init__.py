from distributed_training_pytorch_tpu.checkpoint.manager import (  # noqa: F401
    BEST,
    LAST,
    CheckpointError,
    CheckpointManager,
    CorruptCheckpointError,
    epoch_checkpoint_name,
)
