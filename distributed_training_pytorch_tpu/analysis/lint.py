"""jaxlint — AST lint rules for bugs this codebase has actually shipped.

Generic linters cannot see JAX's failure modes: a ``float()`` that is free
host code everywhere else is a device sync inside a compiled region; an
``open(..., "w")`` that is fine in a script double-writes from N hosts in a
training job; a counter bumped from a background thread is invisible until a
chaos soak catches the torn read. Each rule here is grounded in a bug a past
PR fixed after the fact (docs/static_analysis.md carries the full catalog
with the history):

``host-sync-in-step``
    ``.item()`` / ``float()`` / ``int()`` / ``np.asarray`` /
    ``jax.device_get`` on traced values inside a compiled region. The
    engine's whole design keeps metrics device-resident (the reference paid
    a ``loss.item()`` sync per step); one of these in a step fn silently
    reintroduces that per-step stall.
``wall-clock-in-step``
    ``time.time()`` / ``time.monotonic()`` / ``datetime.now()`` inside a
    compiled region: the value freezes at trace time, so the program bakes
    in one timestamp — and any data-dependent use breaks the bit-exact
    resume invariant (a resumed trace sees a different constant).
``file-write-without-rank-gate``
    ``open()`` for write with no ``process_index() == 0`` gate in sight
    (the ``utils/logger`` convention): N hosts interleaving half-lines on a
    shared filesystem, the exact failure the EventLog's rank-0 ownership
    exists to prevent.
``cross-thread-mutation-without-lock``
    an attribute mutated from a ``threading.Thread`` target (or a method it
    calls) outside any ``with self.<lock>:`` block, on an object the main
    thread shares — the PR 5 EventLog t_mono regression, and the
    async-saver counter races this PR fixes.
``bare-except``
    ``except:`` catches ``KeyboardInterrupt``/``SystemExit``; a Ctrl-C'd
    run that keeps going (or a swallowed watchdog exit) is a hang with
    extra steps. ``except Exception`` is the correct broad form.
``missing-donate-on-jit``
    a ``jax.jit`` whose function carries a state-named first parameter with
    no ``donate_argnums``: the optimizer state's old buffers stay live
    across the update, doubling state memory — the ROADMAP item 3
    donation-audit concern, at the source level (``analysis.hlo_audit``
    checks the same invariant on the compiled program).
``zip-no-strict``
    ``zip()`` over pytree-leaf iterables without an explicit ``strict=``.
    Two leaf lists zipped lazily truncate to the shorter one — a partial
    shardings tree silently undercounted the preflight's per-device byte
    total this exact way (the PR 9 review fix in
    ``parallel.sharding.tree_shard_bytes``); a structure mismatch must be
    an error, not "fewer leaves". Scoped to zips whose arguments touch tree
    leaves (``tree.leaves``/``tree_flatten``/``leaves``-named iterables) —
    config-tuple zips are the generic layer's business (ruff B905 backstops
    repo-wide when installed). ``strict=False`` is accepted: it documents
    that truncation is the contract.

Static analysis is heuristic; false positives are waived inline —
``# jaxlint: disable=<rule> -- <reason>`` (``analysis.waivers``) — and every
waiver is counted and printed by ``scripts/static_audit.py``.

Scope notes (documented limitations, by design small): compiled regions are
resolved per module (a cross-module callee of a jitted fn is linted in its
own module's context); thread targets are resolved for ``self.<method>``
targets within a class; any ``with self.<attr>:`` counts as holding a lock.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, Iterator

from distributed_training_pytorch_tpu.analysis.waivers import Waiver, scan_waivers

__all__ = ["Finding", "LintResult", "RULES", "lint_source", "lint_paths"]

RULES = {
    "host-sync-in-step": "host sync (.item()/float()/int()/np.asarray/"
    "device_get) inside a compiled region",
    "wall-clock-in-step": "wall-clock read (time.time/datetime.now) inside "
    "a compiled region",
    "file-write-without-rank-gate": "open() for write without a "
    "process_index == 0 gate (utils/logger convention)",
    "cross-thread-mutation-without-lock": "attribute mutated from a thread "
    "target without holding a lock",
    "bare-except": "bare except: swallows KeyboardInterrupt/SystemExit",
    "missing-donate-on-jit": "state-carrying jax.jit without donate_argnums",
    "zip-no-strict": "zip() over pytree-leaf iterables without strict= "
    "(silent truncation on structure mismatch)",
    "waiver-missing-reason": "jaxlint disable comment without a '-- reason'",
}

# Call names whose function-argument(s) are traced into a compiled program:
# (name, positional indices of function args).
_COMPILED_ROOT_CALLS = {
    "jit": (0,),
    "pjit": (0,),
    "scan": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "cond": (1, 2),
    "while_loop": (0, 1),
    "checkpoint": (0,),
    "remat": (0,),
    "shard_map": (0,),
    "pallas_call": (0,),
}

_STATE_PARAM_NAMES = {"state", "st", "carry", "train_state"}

_WALL_CLOCK_TIME_ATTRS = {
    "time", "monotonic", "perf_counter", "process_time", "thread_time",
    "monotonic_ns", "perf_counter_ns", "time_ns",
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    waived: bool = False
    waiver_reason: str | None = None

    def describe(self) -> str:
        tag = f"  [waived: {self.waiver_reason}]" if self.waived else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tag}"


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    waivers: list[Waiver]

    @property
    def unwaived(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived]

    @property
    def waived(self) -> list[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def unused_waivers(self) -> list[Waiver]:
        return [w for w in self.waivers if not w.used]

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def merge(self, other: "LintResult") -> "LintResult":
        return LintResult(
            self.findings + other.findings, self.waivers + other.waivers
        )


# -- small AST helpers ------------------------------------------------------


def _terminal_name(node: ast.AST) -> str | None:
    """The rightmost identifier of a Name/Attribute chain (``jax.lax.scan``
    -> ``scan``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _identifiers(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


def _is_self_attribute(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    )


def _walk_skipping_defs(body: list[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class defs
    (each def is visited on its own, so rules fire once per site)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue  # never descend — the def is visited on its own
        stack.extend(ast.iter_child_nodes(node))


def _rankish(name: str) -> bool:
    low = name.lower()
    return low.startswith("proc") or "process" in low or "rank" in low


def _is_rank_gate(test: ast.AST) -> bool:
    """A test expression that gates on 'am I the writing process': a compare
    of a proc/rank-ish identifier against 0, a truthiness check of an
    ``enabled`` flag, or a call to something named like ``process_index``."""
    for node in ast.walk(test):
        if isinstance(node, ast.Compare):
            operands = [node.left, *node.comparators]
            has_zero = any(
                isinstance(op, ast.Constant) and op.value == 0 for op in operands
            )
            if has_zero and any(
                _rankish(ident)
                for op in operands
                for ident in _identifiers(op)
            ):
                return True
        name = _terminal_name(node)
        if name == "enabled" or (name is not None and "process_index" in name):
            return True
        if isinstance(node, ast.Call):
            called = _terminal_name(node.func) or ""
            if "is_coordinator" in called or "is_rank" in called.lower():
                return True
    return False


# -- the per-module analyzer ------------------------------------------------


class _ModuleLint:
    def __init__(self, tree: ast.Module, source: str, path: str):
        self.tree = tree
        self.path = path
        self.findings: list[Finding] = []
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # name -> defs with that bare name, anywhere in the module.
        self.defs: dict[str, list[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(rule=rule, path=self.path, line=node.lineno, message=message)
        )

    # -- compiled-region resolution ------------------------------------

    def _resolve_fn_arg(self, node: ast.AST) -> list[ast.AST]:
        """Function nodes an argument expression may refer to: a local def by
        name, a ``self.X`` method by name, or a literal lambda."""
        if isinstance(node, ast.Lambda):
            return [node]
        name = _terminal_name(node)
        if name is not None:
            return list(self.defs.get(name, ()))
        if isinstance(node, ast.Call):
            # functools.partial(f, ...) — unwrap to f.
            if _terminal_name(node.func) == "partial" and node.args:
                return self._resolve_fn_arg(node.args[0])
        return []

    def compiled_regions(self) -> set[ast.AST]:
        roots: set[ast.AST] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                called = _terminal_name(node.func)
                indices = _COMPILED_ROOT_CALLS.get(called or "")
                if indices:
                    for i in indices:
                        if i < len(node.args):
                            roots.update(self._resolve_fn_arg(node.args[i]))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    target = deco.func if isinstance(deco, ast.Call) else deco
                    if isinstance(deco, ast.Call) and _terminal_name(target) == "partial":
                        if deco.args and _terminal_name(deco.args[0]) == "jit":
                            roots.add(node)
                        continue
                    if _terminal_name(target) in ("jit", "pjit"):
                        roots.add(node)
        # Transitive closure over same-module calls (f() or self.f()).
        compiled = set(roots)
        frontier = list(roots)
        while frontier:
            fn = frontier.pop()
            body = fn.body if not isinstance(fn, ast.Lambda) else [ast.Expr(fn.body)]
            for node in ast.walk(ast.Module(body=list(body), type_ignores=[])):
                if isinstance(node, ast.Call):
                    for target in self._resolve_fn_arg(node.func):
                        if target not in compiled:
                            compiled.add(target)
                            frontier.append(target)
        return compiled

    # -- rules -----------------------------------------------------------

    def check_compiled_region_rules(self) -> None:
        for fn in self.compiled_regions():
            body = fn.body if not isinstance(fn, ast.Lambda) else [ast.Expr(fn.body)]
            fn_name = getattr(fn, "name", "<lambda>")
            for node in _walk_skipping_defs(list(body)):
                if not isinstance(node, ast.Call):
                    continue
                self._check_host_sync(node, fn_name)
                self._check_wall_clock(node, fn_name)

    def _check_host_sync(self, call: ast.Call, fn_name: str) -> None:
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not call.args:
                self.emit(
                    "host-sync-in-step", call,
                    f".item() in compiled region {fn_name!r} blocks on the "
                    "device every step — keep metrics as device arrays and "
                    "fetch at log points",
                )
                return
            if func.attr == "device_get":
                self.emit(
                    "host-sync-in-step", call,
                    f"jax.device_get in compiled region {fn_name!r} is a "
                    "host round-trip inside the step",
                )
                return
            if func.attr in ("asarray", "array") and isinstance(func.value, ast.Name):
                if func.value.id in ("np", "numpy", "onp"):
                    self.emit(
                        "host-sync-in-step", call,
                        f"np.{func.attr} in compiled region {fn_name!r} "
                        "materializes a traced value on host",
                    )
                return
        if isinstance(func, ast.Name) and func.id in ("float", "int") and len(call.args) == 1:
            arg = call.args[0]
            if isinstance(arg, ast.Constant):
                return
            # Static-config casts are fine: self/cls attributes, and shape/
            # dtype metadata (Python values at trace time, no device sync).
            if _is_self_attribute(arg):
                return
            if any(n in ("shape", "ndim", "size", "dtype") for n in _identifiers(arg)):
                return
            self.emit(
                "host-sync-in-step", call,
                f"{func.id}() on a (possibly traced) value in compiled "
                f"region {fn_name!r} forces a device sync — use jnp casts "
                "and fetch on host at sync points",
            )

    def _check_wall_clock(self, call: ast.Call, fn_name: str) -> None:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return
        if (
            isinstance(func.value, ast.Name)
            and func.value.id == "time"
            and func.attr in _WALL_CLOCK_TIME_ATTRS
        ):
            self.emit(
                "wall-clock-in-step", call,
                f"time.{func.attr}() in compiled region {fn_name!r} freezes "
                "at trace time and breaks bit-exact resume",
            )
        elif func.attr in ("now", "utcnow") and "datetime" in set(
            _identifiers(func.value)
        ):
            self.emit(
                "wall-clock-in-step", call,
                f"datetime.{func.attr}() in compiled region {fn_name!r} "
                "freezes at trace time and breaks bit-exact resume",
            )

    def check_bare_except(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
                    continue  # catch-log-reraise keeps the interrupt alive
                self.emit(
                    "bare-except", node,
                    "bare 'except:' swallows KeyboardInterrupt/SystemExit — "
                    "catch Exception (or the specific error) instead",
                )

    def check_file_writes(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            called = _terminal_name(node.func)
            if called not in ("open", "fdopen"):
                continue
            mode = None
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if not isinstance(mode, str) or not set(mode) & set("wax+"):
                continue
            if self._rank_gated(node):
                continue
            self.emit(
                "file-write-without-rank-gate", node,
                f"open(..., {mode!r}) with no process_index == 0 gate in the "
                "enclosing function or class — in a multi-host job every "
                "process runs this write (utils/logger convention: rank 0 "
                "owns the file)",
            )

    def _rank_gated(self, node: ast.AST) -> bool:
        cur: ast.AST | None = node
        enclosing_fn = None
        enclosing_cls = None
        while cur is not None:
            if isinstance(cur, ast.If) and _is_rank_gate(cur.test):
                return True
            if enclosing_fn is None and isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                enclosing_fn = cur
            if enclosing_cls is None and isinstance(cur, ast.ClassDef):
                enclosing_cls = cur
            cur = self.parents.get(cur)
        # Lenient fallbacks: a guard-with-early-return anywhere in the same
        # function, or a class whose construction establishes the gate
        # (EventLog: self.enabled = ... and proc == 0).
        if enclosing_fn is not None:
            for sub in ast.walk(enclosing_fn):
                if isinstance(sub, ast.If) and _is_rank_gate(sub.test):
                    return True
        if enclosing_cls is not None:
            for sub in ast.walk(enclosing_cls):
                if isinstance(sub, ast.Assign) and any(
                    _is_self_attribute(t) and t.attr == "enabled"
                    for t in sub.targets
                ):
                    if _is_rank_gate(sub.value):
                        return True
        return False

    def check_cross_thread(self) -> None:
        for cls in ast.walk(self.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {
                n.name: n
                for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            targets: list[str] = []
            for node in ast.walk(cls):
                if (
                    isinstance(node, ast.Call)
                    and _terminal_name(node.func) == "Thread"
                ):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            if _is_self_attribute(kw.value):
                                targets.append(kw.value.attr)
                            elif isinstance(kw.value, ast.Name):
                                targets.append(kw.value.id)
            if not targets:
                continue
            # Thread region = target methods + same-class methods they call.
            region: set[str] = set()
            frontier = [t for t in targets if t in methods]
            while frontier:
                name = frontier.pop()
                if name in region:
                    continue
                region.add(name)
                for node in ast.walk(methods[name]):
                    if isinstance(node, ast.Call) and _is_self_attribute(node.func):
                        if node.func.attr in methods and node.func.attr not in region:
                            frontier.append(node.func.attr)
            for name in sorted(region):
                self._check_thread_method(cls, methods[name], name)

    def _check_thread_method(
        self, cls: ast.ClassDef, method: ast.AST, name: str
    ) -> None:
        for node in ast.walk(method):
            if isinstance(node, ast.Assign):
                stores = [t for t in node.targets if _is_self_attribute(t)]
            elif isinstance(node, ast.AugAssign) and _is_self_attribute(node.target):
                stores = [node.target]
            else:
                continue
            if not stores:
                continue
            if self._under_self_lock(node, boundary=method):
                continue
            for target in stores:
                self.emit(
                    "cross-thread-mutation-without-lock", node,
                    f"self.{target.attr} is mutated in {cls.name}.{name} — "
                    "code reachable from a threading.Thread target — outside "
                    "any 'with self.<lock>:' block; the main thread shares "
                    "this object",
                )

    def _under_self_lock(self, node: ast.AST, boundary: ast.AST) -> bool:
        cur: ast.AST | None = node
        while cur is not None and cur is not boundary:
            if isinstance(cur, ast.With) and any(
                _is_self_attribute(item.context_expr)
                or (
                    isinstance(item.context_expr, ast.Call)
                    and _is_self_attribute(item.context_expr.func)
                )
                for item in cur.items
            ):
                return True
            cur = self.parents.get(cur)
        return False

    def check_missing_donate(self) -> None:
        seen_defs: set[ast.AST] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and _terminal_name(node.func) in (
                "jit", "pjit",
            ):
                if any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in node.keywords
                ):
                    continue
                if not node.args:
                    continue
                # A bare name may resolve to several same-named defs (a
                # nested fn shadowing a method): one finding per call site.
                for fn in self._resolve_fn_arg(node.args[0]):
                    if self._state_first_param(fn):
                        seen_defs.add(fn)
                        self.emit(
                            "missing-donate-on-jit", node,
                            f"jax.jit({getattr(fn, 'name', '<lambda>')}) "
                            "carries state (first parameter "
                            f"{self._first_param(fn)!r}) but no "
                            "donate_argnums — the old state buffers stay "
                            "live across the update, doubling state memory",
                        )
                        break
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    target = deco.func if isinstance(deco, ast.Call) else deco
                    kws = deco.keywords if isinstance(deco, ast.Call) else []
                    is_jit = _terminal_name(target) in ("jit", "pjit") or (
                        isinstance(deco, ast.Call)
                        and _terminal_name(target) == "partial"
                        and deco.args
                        and _terminal_name(deco.args[0]) in ("jit", "pjit")
                    )
                    if not is_jit or node in seen_defs:
                        continue
                    if any(
                        kw.arg in ("donate_argnums", "donate_argnames")
                        for kw in kws
                    ):
                        continue
                    if self._state_first_param(node):
                        self.emit(
                            "missing-donate-on-jit", node,
                            f"@jit on {node.name!r} carries state (first "
                            f"parameter {self._first_param(node)!r}) but no "
                            "donate_argnums",
                        )

    def check_zip_strict(self) -> None:
        for node in ast.walk(self.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "zip"
            ):
                continue
            if any(kw.arg == "strict" for kw in node.keywords):
                continue
            if len(node.args) < 2 or any(
                isinstance(a, ast.Starred) for a in node.args
            ):
                # zip(*rows) transposes one iterable — no two trees to
                # mismatch; single-arg zips likewise.
                continue
            treeish = any(
                "leaves" in ident
                or "flatten" in ident
                or ident in ("tree", "tree_util", "treedef")
                for arg in node.args
                for ident in _identifiers(arg)
            )
            if not treeish:
                continue
            self.emit(
                "zip-no-strict", node,
                "zip() over pytree leaves without strict= silently truncates "
                "to the shorter tree on a structure mismatch (the PR 9 "
                "partial-shardings undercount); pass strict=True, or "
                "strict=False if truncation really is the contract",
            )

    @staticmethod
    def _first_param(fn: ast.AST) -> str | None:
        args = fn.args.args
        if args and args[0].arg in ("self", "cls"):
            args = args[1:]
        return args[0].arg if args else None

    def _state_first_param(self, fn: ast.AST) -> bool:
        first = self._first_param(fn)
        return first is not None and (
            first in _STATE_PARAM_NAMES or first.endswith("_state")
        )

    def run(self) -> list[Finding]:
        self.check_compiled_region_rules()
        self.check_bare_except()
        self.check_file_writes()
        self.check_cross_thread()
        self.check_missing_donate()
        self.check_zip_strict()
        self.findings.sort(key=lambda f: (f.line, f.rule))
        return self.findings


# -- public API -------------------------------------------------------------


def lint_source(source: str, path: str = "<string>") -> LintResult:
    """Lint one module's source. Syntax errors surface as the generic
    layer's concern (``analysis.generic``) — here they raise."""
    tree = ast.parse(source, filename=path)
    findings = _ModuleLint(tree, source, path).run()
    waivers = scan_waivers(source, path)
    resolved: list[Finding] = []
    for finding in findings:
        waiver = waivers.get(finding.line)
        if waiver is not None and waiver.covers(finding.rule):
            waiver.used = True
            if waiver.reason:
                finding.waived = True
                finding.waiver_reason = waiver.reason
            else:
                resolved.append(
                    Finding(
                        rule="waiver-missing-reason",
                        path=path,
                        line=waiver.line,
                        message=(
                            f"disable={','.join(waiver.rules)} has no "
                            "'-- <reason>': waivers must say why "
                            "(the finding below stays live)"
                        ),
                    )
                )
        resolved.append(finding)
    return LintResult(resolved, list(waivers.values()))


def lint_paths(paths: Iterable[str]) -> LintResult:
    """Lint every ``.py`` file under the given files/directories."""
    result = LintResult([], [])
    for root in paths:
        files = []
        if os.path.isdir(root):
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(
                    os.path.join(dirpath, f)
                    for f in filenames
                    if f.endswith(".py")
                )
        else:
            files.append(root)
        for file in sorted(files):
            with open(file, encoding="utf-8") as f:
                source = f.read()
            result = result.merge(lint_source(source, file))
    return result
