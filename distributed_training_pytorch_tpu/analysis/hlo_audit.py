"""Compiled-program (HLO) audit: verify invariants on the *real* programs.

The jaxlint layer (``analysis.lint``) reads source; this layer reads what
XLA actually compiled. Three invariants, each grounded in a measured cost:

**Donation** — every param/optimizer-state input buffer of the train step
must be input-output aliased (``donate_argnums`` honored end to end). An
undonated state doubles its memory for the duration of the step AND forces
a copy; ROADMAP item 3 names a donation/buffer-aliasing audit of the
chained scan as part of closing the mfu 0.71 vs mfu_exec 0.49 gap. The
check parses the compiled module's ``input_output_alias`` header and sizes
any undonated leaf with ``utils.hlo_flops.aval_bytes``.

**Precision leaks** — under a bf16/fp16 policy, no fp32 ``dot``/
``convolution`` may appear: the policy casts at the loss boundary, and an
f32 matmul sneaking in (a forgotten cast on a new branch) silently halves
MXU throughput for that op. Ops are bucketed by the profiling package's
shared categorizer (``profiling.categories.categorize``) so "what counts
as MXU work" has exactly one definition in the codebase. This check reads
the **lowered (pre-optimization) module**: program semantics. The compiled
text would lie on CPU — the CPU backend legitimately promotes bf16 dots to
f32 internally (measured: ``convert -> f32 dot -> convert``), which is a
backend choice, not a program bug.

**Host callbacks** — the chained window program must contain no host
round-trips (``infeed``/``outfeed``/``send``/``recv``/callback
custom-calls): one callback inside a ``chain_steps=N`` window reintroduces
the per-step host dispatch that chaining exists to remove, N times per
window.

All three run on CPU in seconds (abstract avals only — nothing executes),
which is what lets ``scripts/static_audit.py`` sit in verify.sh next to the
retrace/precision/perf gates.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable

import jax
import jax.numpy as jnp

from distributed_training_pytorch_tpu.profiling.categories import categorize
from distributed_training_pytorch_tpu.utils.hlo_flops import aval_bytes

__all__ = [
    "DonationReport",
    "PrecisionReport",
    "CallbackReport",
    "HloAuditReport",
    "parse_input_output_aliases",
    "count_entry_parameters",
    "audit_donation",
    "audit_precision_leaks",
    "audit_host_callbacks",
    "build_audit_engine",
    "run_hlo_audit",
]

_ALIAS_BLOCK_RE = re.compile(r"input_output_alias=\{((?:[^{}]|\{[^{}]*\})*)\}")
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\((\d+),")
_ENTRY_LAYOUT_RE = re.compile(r"entry_computation_layout=\{\(")

# Host-callback markers in optimized HLO text. ``custom_call_target`` values
# are checked separately against _CALLBACK_TARGET_RE.
_CALLBACK_OPS = (" infeed(", " outfeed(", " send(", " recv(",
                 " send-done(", " recv-done(")
_CALLBACK_TARGET_RE = re.compile(
    r'custom_call_target="([^"]*(?:callback|host|py_func)[^"]*)"', re.IGNORECASE
)


def parse_input_output_aliases(hlo_text: str) -> set[int]:
    """Parameter numbers that are input-output aliased (donated) in a
    compiled module's header. Empty set when the header carries no
    ``input_output_alias`` at all — the undonated-program signature."""
    m = _ALIAS_BLOCK_RE.search(hlo_text)
    if not m:
        return set()
    return {int(p) for p in _ALIAS_ENTRY_RE.findall(m.group(1))}


def count_entry_parameters(hlo_text: str) -> int:
    """Number of entry-computation parameters, from the
    ``entry_computation_layout={(...)->...}`` header — used to verify the
    jax-leaf <-> XLA-parameter index mapping is one-to-one before the
    donation report trusts it."""
    m = _ENTRY_LAYOUT_RE.search(hlo_text)
    if not m:
        raise ValueError("no entry_computation_layout header in HLO text")
    depth, count, any_tokens = 1, 0, False
    for ch in hlo_text[m.end():]:
        if ch in "([{<":
            depth += 1
        elif ch in ")]}>":
            depth -= 1
            if depth == 0:
                break
        elif ch == "," and depth == 1:
            count += 1
        elif not ch.isspace():
            any_tokens = True
    return count + 1 if any_tokens else 0


@dataclasses.dataclass
class DonationReport:
    """Per-leaf donation audit of one compiled program."""

    entries: list[dict]  # {path, role, shape, dtype, bytes, donated}
    label: str = ""

    @property
    def undonated(self) -> list[dict]:
        return [e for e in self.entries if e["must_donate"] and not e["donated"]]

    @property
    def undonated_bytes(self) -> float:
        return sum(e["bytes"] for e in self.undonated)

    @property
    def audited_bytes(self) -> float:
        return sum(e["bytes"] for e in self.entries if e["must_donate"])

    @property
    def donated_fraction(self) -> float:
        total = self.audited_bytes
        if not total:
            return 1.0
        return 1.0 - self.undonated_bytes / total

    @property
    def ok(self) -> bool:
        return not self.undonated

    def describe(self) -> str:
        head = (
            f"donation[{self.label}]: "
            f"{self.donated_fraction * 100:.1f}% of "
            f"{int(self.audited_bytes)} param+opt bytes aliased"
        )
        if self.ok:
            return head + " — OK"
        rows = "".join(
            f"\n    UNDONATED {e['path']} {e['dtype']}{list(e['shape'])} "
            f"({int(e['bytes'])} bytes)"
            for e in self.undonated
        )
        return head + f"; {int(self.undonated_bytes)} bytes undonated:" + rows


def _leaf_role(path_str: str) -> str:
    if ".params" in path_str:
        return "params"
    if ".opt_state" in path_str:
        return "opt_state"
    return "other"


def audit_donation(
    compiled,
    abstract_args: tuple,
    *,
    must_donate: Callable[[str], bool] | None = None,
    label: str = "",
) -> DonationReport:
    """Check that every leaf ``must_donate`` selects (default: params and
    optimizer state) is input-output aliased in ``compiled``.

    ``abstract_args`` is the full argument tuple the program was lowered
    with (e.g. ``(state, batch)``): its flattened leaves correspond 1:1, in
    order, to the module's entry parameters — asserted against the entry
    layout header before the mapping is trusted (jit's unused-argument
    pruning would silently shift the numbering otherwise).
    """
    from jax.tree_util import keystr, tree_flatten_with_path

    text = compiled.as_text()
    aliased = parse_input_output_aliases(text)
    leaves, _ = tree_flatten_with_path(abstract_args)
    n_params = count_entry_parameters(text)
    if n_params != len(leaves):
        raise ValueError(
            f"cannot map leaves to XLA parameters: program has {n_params} "
            f"entry parameters but the argument tree has {len(leaves)} "
            "leaves (an unused argument was pruned?) — the donation report "
            "would attribute aliases to the wrong leaves."
        )
    if must_donate is None:
        must_donate = lambda p: _leaf_role(p) in ("params", "opt_state")  # noqa: E731
    entries = []
    for index, (path, leaf) in enumerate(leaves):
        path_str = keystr(path)
        entries.append(
            {
                "path": path_str,
                "role": _leaf_role(path_str),
                "shape": tuple(leaf.shape),
                "dtype": str(leaf.dtype),
                "bytes": aval_bytes(leaf.shape, getattr(leaf, "dtype", None)),
                "donated": index in aliased,
                "must_donate": bool(must_donate(path_str)),
            }
        )
    return DonationReport(entries=entries, label=label)


@dataclasses.dataclass
class PrecisionReport:
    """fp32 MXU ops found in a low-precision program's lowered module."""

    leaks: list[dict]  # {op, category, result_type}
    policy: str = ""
    mxu_ops: int = 0  # total dot/conv ops inspected

    @property
    def ok(self) -> bool:
        # Zero MXU ops in a train step is not "clean" — it means the parse
        # (or the workload) regressed and the check would pass vacuously.
        return not self.leaks and self.mxu_ops > 0

    def describe(self) -> str:
        if not self.mxu_ops:
            return (
                f"precision[{self.policy}]: found NO dot/conv ops at all — "
                "parser or audit-workload regression (a train step always "
                "has matmuls); refusing a vacuous pass"
            )
        if self.ok:
            return (
                f"precision[{self.policy}]: no fp32 dot/conv among "
                f"{self.mxu_ops} MXU ops — OK"
            )
        rows = "".join(
            f"\n    LEAK {x['op']} -> {x['result_type']} ({x['category']})"
            for x in self.leaks
        )
        return (
            f"precision[{self.policy}]: {len(self.leaks)} fp32 MXU op(s) "
            "in a low-precision program:" + rows
        )


def audit_precision_leaks(lowered_text: str, *, policy: str = "") -> PrecisionReport:
    """Find fp32 ``dot``/``convolution`` ops in a lowered (StableHLO)
    module. Uses the shared profiling categorizer to decide which ops are
    MXU work, then checks each one's result element type."""
    leaks = []
    mxu_ops = 0
    matches = list(re.finditer(r"stablehlo\.([\w.]+)", lowered_text))
    for i, m in enumerate(matches):
        op = m.group(1)
        category = categorize(op)
        if category not in ("matmul", "convolution"):
            continue
        # The op's own type signature is the `-> tensor<...>` before the
        # next op begins; a signature past that belongs to someone else.
        bound = matches[i + 1].start() if i + 1 < len(matches) else len(lowered_text)
        sig = lowered_text.find("-> tensor<", m.end(), bound)
        if sig < 0:
            continue
        mxu_ops += 1
        end = lowered_text.find(">", sig + len("-> tensor<"))
        result = lowered_text[sig + len("-> tensor<"):end]
        dtype = result.rsplit("x", 1)[-1] if "x" in result else result
        if dtype == "f32":
            leaks.append({"op": op, "category": category, "result_type": result})
    return PrecisionReport(leaks=leaks, policy=policy, mxu_ops=mxu_ops)


@dataclasses.dataclass
class CallbackReport:
    """Host round-trip ops found in a compiled program."""

    hits: list[str]
    label: str = ""

    @property
    def ok(self) -> bool:
        return not self.hits

    def describe(self) -> str:
        if self.ok:
            return f"host-callbacks[{self.label}]: none — OK"
        return (
            f"host-callbacks[{self.label}]: {len(self.hits)} host "
            f"round-trip op(s) in the compiled program: {self.hits}"
        )


def audit_host_callbacks(hlo_text: str, *, label: str = "") -> CallbackReport:
    hits = []
    for marker in _CALLBACK_OPS:
        if marker in hlo_text:
            hits.append(marker.strip(" ("))
    hits.extend(_CALLBACK_TARGET_RE.findall(hlo_text))
    return CallbackReport(hits=hits, label=label)


# -- the audited workload ---------------------------------------------------


def build_audit_engine(precision=None, mesh=None, *, sharding_rules=None,
                       fsdp_min_size: int = 2**18):
    """A small conv+dense workload through the real :class:`TrainEngine` —
    the same shape of fixture the perf gate times (CPU-viable, compiles in
    seconds), here only *lowered*, never run. Returns ``(engine,
    abstract_state, abstract_batch)``; nothing touches a device.
    ``sharding_rules``/``fsdp_min_size`` configure the sharded-audit
    variants (a low ``fsdp_min_size`` so the fixture's small leaves really
    shard — a "sharded" audit of a fully replicated program would be a
    vacuous pass)."""
    import optax
    from flax import linen as nn

    from distributed_training_pytorch_tpu.ops import cross_entropy_loss
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
    from distributed_training_pytorch_tpu.train import (
        TrainEngine,
        make_supervised_loss,
    )
    from distributed_training_pytorch_tpu.train.state import TrainState

    class AuditNet(nn.Module):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = nn.relu(nn.Conv(8, (3, 3))(x))
            x = x.reshape(x.shape[0], -1)
            return nn.Dense(10)(x)

    def criterion(logits, batch):
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"loss": loss}

    model = AuditNet()
    optimizer = optax.sgd(0.05, momentum=0.9)
    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optimizer,
        mesh if mesh is not None else mesh_lib.create_mesh(),
        precision=precision,
        sharding_rules=sharding_rules,
        fsdp_min_size=fsdp_min_size,
    )
    batch_size = 8 * max(1, jax.device_count())

    def make_state(rng):
        variables = model.init(rng, jnp.zeros((1, 8, 8, 3), jnp.float32))
        params = variables.pop("params")
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
            model_state=dict(variables),
            rng=rng,
            loss_scale=engine.initial_loss_scale,
        )

    abstract_state = jax.eval_shape(make_state, jax.random.key(0))
    abstract_batch = {
        "image": jax.ShapeDtypeStruct((batch_size, 8, 8, 3), jnp.float32),
        "label": jax.ShapeDtypeStruct((batch_size,), jnp.int32),
    }
    return engine, abstract_state, abstract_batch


def _stack_abstract(batch: dict, length: int) -> dict:
    # Shared stacking rule (train.engine): the audited window shape is the
    # dispatched one by construction.
    from distributed_training_pytorch_tpu.train.engine import stack_chain_batch

    return stack_chain_batch(batch, length)


@dataclasses.dataclass
class HloAuditReport:
    single: DonationReport
    chained: DonationReport
    precision: PrecisionReport
    callbacks: CallbackReport
    # SPMD-partitioned twins (ISSUE 10): the same invariants on programs
    # whose state is REALLY fsdp/tensor-sharded. None = skipped (fewer than
    # 8 devices — the forced-host count scripts/static_audit.py sets up);
    # the `sharded` flag distinguishes "ran and passed" from "not run".
    sharded_single: "DonationReport | None" = None
    sharded_chained: "DonationReport | None" = None
    sharded_precision: "PrecisionReport | None" = None
    injected: bool = False

    @property
    def sharded(self) -> bool:
        return self.sharded_single is not None

    def _parts(self):
        parts = [self.single, self.chained, self.precision, self.callbacks]
        parts += [
            p
            for p in (self.sharded_single, self.sharded_chained, self.sharded_precision)
            if p is not None
        ]
        return parts

    @property
    def ok(self) -> bool:
        return all(part.ok for part in self._parts())

    def describe(self) -> str:
        lines = ["  " + part.describe() for part in self._parts()]
        if not self.sharded:
            lines.append(
                "  sharded audit: SKIPPED (needs >= 8 devices for the "
                "data=2/fsdp=2/tensor=2 mesh; static_audit forces 8 host "
                "devices, so the verify gate always runs it)"
            )
        return "\n".join(lines)

    def to_fields(self) -> dict:
        """Flat JSON-safe summary for the ``static_audit`` telemetry event."""
        fields = {
            "undonated_bytes_single": self.single.undonated_bytes,
            "undonated_bytes_chained": self.chained.undonated_bytes,
            "donated_fraction_single": self.single.donated_fraction,
            "donated_fraction_chained": self.chained.donated_fraction,
            "precision_leaks": len(self.precision.leaks),
            "host_callbacks": len(self.callbacks.hits),
            "sharded": self.sharded,
            "injected": self.injected,
            "passed": self.ok,
        }
        if self.sharded:
            fields["donated_fraction_sharded_single"] = (
                self.sharded_single.donated_fraction
            )
            fields["donated_fraction_sharded_chained"] = (
                self.sharded_chained.donated_fraction
            )
            fields["sharded_precision_leaks"] = len(self.sharded_precision.leaks)
        return fields


def _audit_mesh():
    """The sharded-audit mesh: data=2/fsdp=2/tensor=2 over the first 8
    devices — every sharding mode the Trainer hot path supports, in one
    program. None when the platform has fewer than 8 devices (the audit is
    then skipped and says so; ``scripts/static_audit.py`` forces an 8-device
    host platform so the verify gate always exercises it)."""
    if jax.device_count() < 8:
        return None
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

    return mesh_lib.create_mesh(
        {"data": 2, "fsdp": 2, "tensor": 2}, devices=jax.devices()[:8]
    )


# Explicit TP rule for the audit fixture's Dense head + a low FSDP cutoff:
# the fixture's leaves are tiny, and a "sharded" audit of a program whose
# every leaf fell back to replicated would pass vacuously. test_analysis
# pins that the audited state really carries fsdp AND tensor specs.
_AUDIT_SHARDING_RULES = (("Dense_0.*kernel", jax.sharding.PartitionSpec(None, "tensor")),)
_AUDIT_FSDP_MIN_SIZE = 128


def run_hlo_audit(chain_steps: int = 4, *, inject_violation: bool = False) -> HloAuditReport:
    """Lower the real single-step and chained train programs on abstract
    avals (via ``TrainEngine.compile_step_probe``) and audit donation, then
    audit a bf16-policy lowering for precision leaks and the chained
    program for host callbacks. With >= 8 devices the same donation +
    precision invariants are audited on SPMD-partitioned twins — a
    data=2/fsdp=2/tensor=2 mesh with genuinely sharded state — because
    donation under partitioning is a separate property (aliasing must
    survive SPMD's parameter rewriting) and ISSUE 10's sharded hot path
    depends on it.

    ``inject_violation=True`` is the self-test seam (the perf gate's
    ``--inject-slowdown`` analog): the donation audits — sharded ones
    included — run against probes lowered WITHOUT donation, structurally
    the exact bug the audit exists to catch, and the report must come back
    failing.
    """
    donate = not inject_violation
    engine, state, batch = build_audit_engine()
    single = engine.compile_step_probe(state, batch, donate=donate)
    single_report = audit_donation(single, (state, batch), label="single-step")
    window = _stack_abstract(batch, chain_steps)
    chained = engine.compile_step_probe(
        state, window, donate=donate, chain_length=chain_steps
    )
    chained_report = audit_donation(
        chained, (state, window), label=f"chained x{chain_steps}"
    )
    callback_report = audit_host_callbacks(
        chained.as_text(), label=f"chained x{chain_steps}"
    )
    bf16_engine, bf16_state, bf16_batch = build_audit_engine(precision="bf16")
    lowered = bf16_engine.lower_step_probe(bf16_state, bf16_batch, donate=donate)
    precision_report = audit_precision_leaks(lowered.as_text(), policy="bf16")
    sharded_single = sharded_chained = sharded_precision = None
    mesh = _audit_mesh()
    if mesh is not None:
        sh_engine, sh_state, sh_batch = build_audit_engine(
            mesh=mesh,
            sharding_rules=_AUDIT_SHARDING_RULES,
            fsdp_min_size=_AUDIT_FSDP_MIN_SIZE,
        )
        sh_compiled = sh_engine.compile_step_probe(sh_state, sh_batch, donate=donate)
        sharded_single = audit_donation(
            sh_compiled, (sh_state, sh_batch), label="sharded single-step"
        )
        sh_window = _stack_abstract(sh_batch, chain_steps)
        sh_chained = sh_engine.compile_step_probe(
            sh_state, sh_window, donate=donate, chain_length=chain_steps
        )
        sharded_chained = audit_donation(
            sh_chained, (sh_state, sh_window),
            label=f"sharded chained x{chain_steps}",
        )
        sh_bf16_engine, sh_bf16_state, sh_bf16_batch = build_audit_engine(
            precision="bf16",
            mesh=mesh,
            sharding_rules=_AUDIT_SHARDING_RULES,
            fsdp_min_size=_AUDIT_FSDP_MIN_SIZE,
        )
        sh_lowered = sh_bf16_engine.lower_step_probe(
            sh_bf16_state, sh_bf16_batch, donate=donate
        )
        sharded_precision = audit_precision_leaks(
            sh_lowered.as_text(), policy="bf16 sharded"
        )
    return HloAuditReport(
        single=single_report,
        chained=chained_report,
        precision=precision_report,
        callbacks=callback_report,
        sharded_single=sharded_single,
        sharded_chained=sharded_chained,
        sharded_precision=sharded_precision,
        injected=inject_violation,
    )
