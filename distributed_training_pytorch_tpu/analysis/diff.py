"""Structural program diff: what did the compiler emit DIFFERENTLY? (ISSUE 14)

The profile diff (``profiling/diff.py``) says which *category of time*
explains a step_ms change; this module answers the structural question
underneath it, on the same ``TrainEngine.compile_step_probe`` lowerings the
HLO and comm audits already read (abstract avals, zero execution,
CPU-viable):

* **HLO signature diff** — per-category instruction counts (through the ONE
  shared ``profiling.categories.categorize``) and the fusion count of two
  optimized-HLO texts. A Pallas kernel landing shows up as a conv/dot
  instruction replaced by a custom-call; an XLA flag change shows up as a
  fusion-count shift; a shape leak shows up as the instruction count
  ballooning.
* **Comm inventory diff** — two ``comm_audit.collective_inventory`` results
  compared per mesh axis (byte deltas) and per collective op, with
  replica-group changes *named*: a collective whose device groups moved to a
  different axis, group count, or group size is exactly the mis-rule /
  re-route signature the comm audit hunts within one program — here it is
  caught *between* two programs (e.g. a sharding-rule edit silently turning
  a tensor-axis reduce-scatter into a full all-gather).

Both diffs are pure text/dataclass transforms so they unit-test on
hand-built programs; ``scripts/run_compare.py`` exposes them on real
lowerings via ``--hlo`` / run-dir inputs.
"""

from __future__ import annotations

import dataclasses

from distributed_training_pytorch_tpu.analysis.comm_audit import (
    COMM_OPS,
    CommInventory,
)
from distributed_training_pytorch_tpu.profiling.categories import categorize
from distributed_training_pytorch_tpu.profiling.diff import (
    attribute_delta,
    describe_rows,
)

__all__ = [
    "CommDiff",
    "HloSignature",
    "HloStructuralDiff",
    "diff_comm",
    "diff_hlo",
    "hlo_signature",
    "iter_instruction_opcodes",
]


def iter_instruction_opcodes(hlo_text: str):
    """Yield ``(instruction_name, opcode)`` for every instruction line of an
    (optimized or lowered) HLO text. An instruction line is
    ``[ROOT ]%name = <type> opcode(operands...), attrs`` — the type may be a
    parenthesized tuple with internal spaces, so the type segment is skipped
    by balanced-paren scan, not by whitespace split."""
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if " = " not in line:
            continue
        head, rhs = line.split(" = ", 1)
        head = head.strip()
        if head.startswith("ROOT "):
            head = head[len("ROOT "):].strip()
        if not head.startswith("%") and not head.replace(".", "").replace(
            "-", ""
        ).replace("_", "").isalnum():
            continue
        rhs = rhs.lstrip()
        if rhs.startswith("("):  # tuple type: skip the balanced group
            depth, j = 0, 0
            while j < len(rhs):
                if rhs[j] == "(":
                    depth += 1
                elif rhs[j] == ")":
                    depth -= 1
                    if depth == 0:
                        break
                j += 1
            rhs = rhs[j + 1:].lstrip()
        else:  # scalar/array type: one whitespace-delimited token
            cut = rhs.find(" ")
            if cut < 0:
                continue
            rhs = rhs[cut + 1:].lstrip()
        paren = rhs.find("(")
        if paren <= 0:
            continue
        opcode = rhs[:paren].strip()
        # Opcode tokens are lowercase identifiers with dashes (all-reduce,
        # get-tuple-element); anything else is a non-instruction line that
        # happened to carry " = " (metadata, frontend attributes).
        if not opcode or not opcode.replace("-", "").replace("_", "").isalnum():
            continue
        yield head, opcode


@dataclasses.dataclass
class HloSignature:
    """The structural fingerprint of one optimized-HLO program."""

    label: str
    instructions: int
    fusions: int
    collectives: int
    category_counts: dict  # shared-categorizer bucket -> instruction count
    opcode_counts: dict  # raw opcode -> count (the fine-grained view)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def hlo_signature(hlo_text: str, *, label: str = "") -> HloSignature:
    """Fingerprint an HLO text: instruction/fusion/collective counts plus
    per-category counts through the ONE shared categorizer — so a category
    row here and a category row in a profile report mean the same bucket."""
    categories: dict[str, int] = {}
    opcodes: dict[str, int] = {}
    fusions = 0
    collectives = 0
    total = 0
    for _, opcode in iter_instruction_opcodes(hlo_text):
        total += 1
        opcodes[opcode] = opcodes.get(opcode, 0) + 1
        cat = categorize(opcode)
        categories[cat] = categories.get(cat, 0) + 1
        if opcode == "fusion":
            fusions += 1
        if any(opcode.startswith(c) for c in COMM_OPS):
            collectives += 1
    return HloSignature(
        label=label,
        instructions=total,
        fusions=fusions,
        collectives=collectives,
        category_counts=categories,
        opcode_counts=opcodes,
    )


@dataclasses.dataclass
class HloStructuralDiff:
    """Two program fingerprints and their ranked per-category count deltas
    (the one ``attribute_delta`` rule — deltas sum to the total instruction
    delta by construction)."""

    before: HloSignature
    after: HloSignature
    category_deltas: list  # list[DeltaRow] over category_counts
    opcode_deltas: list  # list[DeltaRow] over opcode_counts

    @property
    def instruction_delta(self) -> int:
        return self.after.instructions - self.before.instructions

    @property
    def fusion_delta(self) -> int:
        return self.after.fusions - self.before.fusions

    @property
    def collective_delta(self) -> int:
        return self.after.collectives - self.before.collectives

    @property
    def identical(self) -> bool:
        return (
            self.instruction_delta == 0
            and all(r.delta == 0 for r in self.opcode_deltas)
        )

    def to_dict(self) -> dict:
        return {
            "before": self.before.to_dict(),
            "after": self.after.to_dict(),
            "instruction_delta": self.instruction_delta,
            "fusion_delta": self.fusion_delta,
            "collective_delta": self.collective_delta,
            "category_deltas": [r.to_dict() for r in self.category_deltas],
            "opcode_deltas": [r.to_dict() for r in self.opcode_deltas],
        }

    def describe(self, *, top: int = 6) -> str:
        if self.identical:
            return (
                f"HLO structure identical ({self.before.instructions} "
                "instructions, same opcode mix)"
            )
        line = (
            f"HLO instructions {self.before.instructions} -> "
            f"{self.after.instructions} ({self.instruction_delta:+d}), "
            f"fusions {self.before.fusions} -> {self.after.fusions} "
            f"({self.fusion_delta:+d}): "
        )
        rows = [r for r in self.category_deltas if r.delta]
        return line + describe_rows(rows, unit="ops", top=top, digits=0)


def diff_hlo(before, after, *, label_before: str = "before",
             label_after: str = "after") -> HloStructuralDiff:
    """Structural diff of two programs — HLO texts or prebuilt
    :class:`HloSignature` s (pass a compiled executable's ``as_text()``)."""
    sig_b = (before if isinstance(before, HloSignature)
             else hlo_signature(str(before), label=label_before))
    sig_a = (after if isinstance(after, HloSignature)
             else hlo_signature(str(after), label=label_after))
    return HloStructuralDiff(
        before=sig_b,
        after=sig_a,
        category_deltas=attribute_delta(sig_b.category_counts, sig_a.category_counts),
        opcode_deltas=attribute_delta(sig_b.opcode_counts, sig_a.opcode_counts),
    )


def _axes_key(axes: tuple) -> str:
    return "x".join(axes) if axes else "?"


@dataclasses.dataclass
class CommDiff:
    """Two collective inventories compared: per-axis and per-op byte deltas
    (ranked, the one attribution rule) plus *named* replica-group changes —
    the collectives that appeared, vanished, or moved to different device
    groups between the two programs."""

    before: CommInventory
    after: CommInventory
    axis_deltas: list  # list[DeltaRow] over by_axes byte totals
    op_deltas: list  # list[DeltaRow] over by_op byte totals
    group_changes: list  # list[str] — named new/removed/regrouped collectives

    @property
    def total_delta(self) -> float:
        return self.after.total_bytes - self.before.total_bytes

    @property
    def identical(self) -> bool:
        return not self.group_changes and all(r.delta == 0 for r in self.axis_deltas)

    def to_dict(self) -> dict:
        return {
            "before_bytes": self.before.total_bytes,
            "after_bytes": self.after.total_bytes,
            "total_delta_bytes": self.total_delta,
            "axis_deltas": [r.to_dict() for r in self.axis_deltas],
            "op_deltas": [r.to_dict() for r in self.op_deltas],
            "group_changes": list(self.group_changes),
        }

    def describe(self, *, top: int = 6) -> str:
        if self.identical:
            return (
                f"comm identical ({len(self.before.collectives)} collective(s), "
                f"{int(self.before.total_bytes)} B/step)"
            )
        lines = [
            f"comm {int(self.before.total_bytes)} -> "
            f"{int(self.after.total_bytes)} B/step "
            f"({self.total_delta:+.0f} B): per-axis "
            + describe_rows(
                [r for r in self.axis_deltas if r.delta], unit="B", top=top, digits=0
            )
        ]
        for change in self.group_changes:
            lines.append(f"  groups: {change}")
        return "\n".join(lines)


def diff_comm(before: CommInventory, after: CommInventory) -> CommDiff:
    """Diff two ``collective_inventory`` results. Byte deltas are attributed
    per mesh axis and per collective op; group changes are matched by
    instruction name (stable for the same program lowered twice; a renamed
    instruction reports as removed+new — which IS a structural change)."""
    axis_rows = attribute_delta(
        {_axes_key(a): v for a, v in before.by_axes().items()},
        {_axes_key(a): v for a, v in after.by_axes().items()},
    )
    op_rows = attribute_delta(before.by_op(), after.by_op())

    by_name_b = {c.name: c for c in before.collectives}
    by_name_a = {c.name: c for c in after.collectives}
    changes: list[str] = []
    for name in sorted(set(by_name_b) | set(by_name_a)):
        cb, ca = by_name_b.get(name), by_name_a.get(name)
        if cb is None:
            changes.append(f"NEW {ca.describe()}")
        elif ca is None:
            changes.append(f"REMOVED {cb.describe()}")
        elif (cb.axes, cb.groups, cb.group_size) != (ca.axes, ca.groups, ca.group_size):
            changes.append(
                f"REGROUPED {name} [{ca.op}]: "
                f"{cb.groups} group(s) of {cb.group_size} over "
                f"{_axes_key(cb.axes)} -> {ca.groups} group(s) of "
                f"{ca.group_size} over {_axes_key(ca.axes)}"
            )
        elif cb.bytes != ca.bytes:
            changes.append(
                f"RESIZED {name} [{ca.op}]: {int(cb.bytes)} -> {int(ca.bytes)} B"
            )
    return CommDiff(
        before=before,
        after=after,
        axis_deltas=axis_rows,
        op_deltas=op_rows,
        group_changes=changes,
    )
