"""SPMD communication audit: what the partitioner *actually* emitted.

PR 9 wired FSDP/TP meshes into the Trainer hot path; XLA's SPMD partitioner
inserts every collective. Nothing verified the result: a one-line
sharding-rule mistake silently turns a reduce-scatter into a full-parameter
all-gather, and the only symptom is a flat bench round. This module is the
analysis/ subsystem's third pillar — PR 7 reads donation out of the compiled
program, PR 8 reads memory, this reads *communication*:

**Inventory** — every ``all-reduce`` / ``all-gather`` / ``reduce-scatter`` /
``collective-permute`` / ``all-to-all`` in the optimized HLO of the real
SPMD-partitioned single-step AND chained programs (via the existing
``TrainEngine.compile_step_probe`` machinery on abstract avals: zero device
execution, CPU-viable under forced host devices), each with its byte volume
and the mesh axes its device groups span. Byte convention: the *logical
tensor size communicated* — ``max(operand bytes, result bytes)`` — so an
all-gather (small in, full out), an all-reduce (full both sides) and a
reduce-scatter (full in, shard out) of the same tensor all count its full
bytes, and the figure is lowering-invariant (this CPU backend legally lowers
a grad reduce-scatter as all-reduce + slice — measured — and both spellings
score the same). Replica groups (iota ``[G,S]<=[dims]T(perm)`` and explicit
``{{..}}`` forms) map back to :class:`MeshConfig` axis names through
``parallel.mesh.device_coords``; the reported axes are the *physical* groups
the bytes crossed (XLA may legally re-route, e.g. an fsdp gather through a
tensor-neighbor permute — measured on the mixed mesh).

**Expected-comm model** — analytic per-step comm derived from the mesh + the
resolved sharding rules (the ISSUE 11 derivation, docs/parallelism.md):

* pure DP (batch sharded, params replicated): one grad sync per param leaf
  ≈ total param bytes;
* ``fsdp``: + param all-gather forward and re-gather/scatter backward
  ≈ 2 x fsdp-sharded param bytes;
* ``tensor``: + per-layer activation syncs ≈ 2 x rows_per_replica x
  sum(layer dims) x dtype bytes per tensor-sharded leaf (fwd + bwd).

Two hard failure modes, each reported with the offending HLO op and the
leaf/rule it traces to (``parallel.sharding.rule_for_leaf``):

* **accidental-gather** — an all-gather over groups spanning an axis the
  rules shard *without* gathering (``tensor``/``seq``; fsdp gathers params
  by design) moving >= the full unsharded bytes of the largest such leaf.
  This is the mis-rule signature: e.g. a rule anchored to ``.params`` only
  leaves the momentum twin unsharded, and the optimizer update must gather
  the full parameter every step (measured: the injected spec below).
* **model-exceeded** — total inventory bytes > expected x (1 + tolerance).
  A catastrophe bound (default tolerance 1.0, i.e. 2x): the model
  deliberately over-estimates legit comm, so tripping it means comm the
  derivation cannot explain at all. The *tight* instrument is the baseline.

**Baseline gate** — per-mesh-spec single-step totals persist in a committed
``COMM_BASELINE.json``, gated exactly like ``PERF_BASELINE.json``: the one
``profiling.gate.check`` rule (fail iff measured > baseline x (1+tol)), the
``--update`` ritual (``scripts/static_audit.py --update-comm-baseline``) and
the stale nudge when comm *shrinks* past tolerance. Byte totals are
deterministic for a given XLA, so the default tolerance (25%) only absorbs
compiler-version lowering changes — a rule regression that doubles gather
traffic cannot pass.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Sequence

import jax
import numpy as np

from distributed_training_pytorch_tpu.profiling.categories import categorize
from distributed_training_pytorch_tpu.profiling.gate import (
    GateResult,
    check as gate_check,
    load_baseline,
    update_baseline,
)
from distributed_training_pytorch_tpu.utils.hlo_flops import (
    DTYPE_BYTES,
    OPNAME_RE,
    aval_bytes,
)

__all__ = [
    "COMM_OPS",
    "COMM_BASELINE_PATH",
    "AUDIT_MESH_SPECS",
    "Collective",
    "CommInventory",
    "ExpectedComm",
    "CommSpecReport",
    "CommAuditReport",
    "parse_replica_groups",
    "mesh_axes_for_groups",
    "collective_inventory",
    "expected_comm",
    "comm_findings",
    "comm_fields",
    "run_comm_audit",
]

# The collective opcodes this audit inventories, as they appear in optimized
# HLO text. `categorize()` buckets every one of them as "collective" — the
# per-op rows below join the profiler's attribution through that shared
# categorizer (test-enforced).
COMM_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# Repo-root COMM_BASELINE.json (this module lives two levels down) — the
# comm twin of profiling.gate.DEFAULT_BASELINE_PATH.
COMM_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "COMM_BASELINE.json",
)

# The audited mesh layouts: every sharding mode the Trainer hot path
# supports, as 8-device spec strings (the docs/parallelism.md grammar) —
# pure DP, pure FSDP, tensor x data, and the mixed mesh the HLO audit's
# sharded twins use.
AUDIT_MESH_SPECS = ("dp8", "fsdp8", "tp2x4", "dp2fsdp2tp2")

# Axes whose *parameters* a correct program never gathers whole: fsdp
# gathers params by design (ZeRO-3), but a tensor/seq-sharded weight stays
# sharded through fwd+bwd — only activations cross those axes. A full-param
# all-gather there is the mis-rule catastrophe this audit exists to catch.
NEVER_GATHER_AXES = ("tensor", "seq")

# Default tolerances: the analytic model is a deliberate over-estimate, so
# its bound is loose (fail past 2x expected); the committed baseline is
# deterministic per XLA version, so its gate is tight.
MODEL_TOLERANCE = 1.0
BASELINE_TOLERANCE = 0.25

# Sync spellings AND the async `-start` halves TPU optimized HLO emits
# (`all-gather-start`/`all-reduce-start`/...): the `-start` op carries the
# shapes and replica groups, so it IS the collective for counting purposes;
# the paired `-done` never matches (the regex requires `(` right after the
# optional suffix) — counting both would double every async collective.
_OPCODE_RE = re.compile(
    r"(?<!%)\b(" + "|".join(re.escape(op) for op in COMM_OPS) + r")(-start)?\("
)
_SHAPE_RE = re.compile(r"\b([a-z]\w*)\[([0-9,]*)\]")
_GROUPS_EXPLICIT_RE = re.compile(
    r"replica_groups=\{(\{[\d,\s]*\}(?:,\s*\{[\d,\s]*\})*)\}"
)
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?"
)
_GROUP_RE = re.compile(r"\{([\d,\s]*)\}")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\s*\d+\},?\s*)+)\}")


@dataclasses.dataclass
class Collective:
    """One collective instruction of a compiled program."""

    op: str  # opcode: all-reduce | all-gather | ...
    name: str  # HLO instruction name (%all-gather.2)
    bytes: float  # logical bytes communicated: max(operand, result)
    axes: tuple[str, ...]  # mesh axes the device groups span
    groups: int  # number of communicating device groups
    group_size: int  # devices per group (2 for a permute pair)
    result_shape: str  # result type text, for reports
    op_name: str = ""  # jax op_name metadata (traces to the model op)

    @property
    def profile_category(self) -> str:
        """The shared profiling bucket this op lands in (always
        ``collective`` — the join with ``profiling.categories``)."""
        return categorize(self.op)

    def describe(self) -> str:
        axes = "x".join(self.axes) if self.axes else "?"
        return (
            f"{self.name} [{self.op}] {self.result_shape} "
            f"{int(self.bytes)} B over {axes} "
            f"({self.groups} group(s) of {self.group_size})"
        )


@dataclasses.dataclass
class CommInventory:
    """Every collective of one compiled program, with totals."""

    collectives: list[Collective]
    label: str = ""
    chain_length: int = 1  # informational: unrolled windows repeat per step

    @property
    def total_bytes(self) -> float:
        return sum(c.bytes for c in self.collectives)

    def by_op(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for c in self.collectives:
            out[c.op] = out.get(c.op, 0.0) + c.bytes
        return out

    def by_axes(self) -> dict[tuple[str, ...], float]:
        out: dict[tuple[str, ...], float] = {}
        for c in self.collectives:
            out[c.axes] = out.get(c.axes, 0.0) + c.bytes
        return out

    def describe(self) -> str:
        ops = ", ".join(
            f"{op}={int(v)}B" for op, v in sorted(self.by_op().items())
        )
        axes = ", ".join(
            f"{'x'.join(a) or '?'}={int(v)}B"
            for a, v in sorted(self.by_axes().items())
        )
        return (
            f"inventory[{self.label}]: {len(self.collectives)} collective(s), "
            f"{int(self.total_bytes)} B total ({ops or 'none'}; per-axis: "
            f"{axes or 'none'})"
        )


def parse_replica_groups(attrs: str) -> "list[tuple[int, ...]] | None":
    """Device groups from a collective's attribute text. Handles both the
    explicit ``replica_groups={{0,1},{2,3}}`` form and the iota form
    ``replica_groups=[G,S]<=[dims]`` / ``...T(perm)`` (reshape an iota of
    prod(dims) by ``dims``, transpose by ``perm``, reshape to G groups of
    S). None when the attribute is absent (e.g. collective-permute)."""
    m = _GROUPS_IOTA_RE.search(attrs)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",") if x]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",") if x]
            ids = ids.transpose(perm)
        ids = ids.reshape(n_groups, group_size)
        return [tuple(int(i) for i in row) for row in ids]
    m = _GROUPS_EXPLICIT_RE.search(attrs)
    if m:
        return [
            tuple(int(x) for x in g.split(",") if x.strip())
            for g in _GROUP_RE.findall(m.group(1))
        ]
    return None


def _permute_groups(attrs: str) -> "list[tuple[int, ...]] | None":
    """``source_target_pairs`` of a collective-permute as 2-device groups,
    self-pairs (no bytes move) dropped."""
    m = _PAIRS_RE.search(attrs)
    if m is None:
        return None
    pairs = re.findall(r"\{(\d+),\s*(\d+)\}", m.group(1))
    return [(int(s), int(t)) for s, t in pairs if s != t]


def mesh_axes_for_groups(
    groups: Sequence[Sequence[int]], coords: "dict[int, tuple[int, ...]]",
    axis_names: Sequence[str],
) -> tuple[str, ...]:
    """The mesh axes that *vary* inside the device groups — the axes this
    collective's bytes cross. Devices absent from ``coords`` (a program over
    foreign devices) yield ``()`` = unmapped, never a wrong name."""
    varying: set[int] = set()
    for group in groups:
        if len(group) < 2:
            continue
        pts = []
        for dev in group:
            if dev not in coords:
                return ()
            pts.append(coords[dev])
        for dim in range(len(axis_names)):
            if len({p[dim] for p in pts}) > 1:
                varying.add(dim)
    return tuple(axis_names[i] for i in sorted(varying))


def _shape_bytes(segment: str) -> list[float]:
    """Byte size of every typed shape (``f32[64,10]``) in an HLO text
    segment, at the shared ``DTYPE_BYTES`` widths. Layout suffixes
    (``{1,0}``) and attribute brackets never match — the regex requires a
    dtype word before ``[``, and unknown words are skipped."""
    sizes: list[float] = []
    for dtype, dims in _SHAPE_RE.findall(segment):
        if dtype not in DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * DTYPE_BYTES[dtype])
    return sizes


def _segment_bytes(segment: str) -> float:
    return sum(_shape_bytes(segment))


def collective_inventory(hlo_text: str, mesh, *, label: str = "",
                         chain_length: int = 1) -> CommInventory:
    """Parse every collective out of optimized HLO text, sized and mapped to
    ``mesh``'s axes. For an unrolled chained program each step's collectives
    appear (and count) once per step — totals scale with the window, exactly
    like the bytes the wire carries."""
    from distributed_training_pytorch_tpu.parallel.mesh import device_coords

    coords = device_coords(mesh)
    axis_names = tuple(mesh.axis_names)
    out: list[Collective] = []
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if " = " not in line:
            continue
        head, rhs = line.split(" = ", 1)
        m = _OPCODE_RE.search(rhs)
        if m is None:
            continue
        op = m.group(1)
        is_start = m.group(2) is not None
        result_seg = rhs[: m.start()]
        # Operand segment: balanced-paren scan (types may nest tuples).
        i = rhs.find("(", m.start())
        depth, j = 0, i
        while j < len(rhs):
            if rhs[j] == "(":
                depth += 1
            elif rhs[j] == ")":
                depth -= 1
                if depth == 0:
                    break
            j += 1
        operand_seg = rhs[i:j]
        attrs = rhs[j:]
        if op == "collective-permute":
            groups = _permute_groups(attrs)
        else:
            groups = parse_replica_groups(attrs)
        if groups is not None:
            groups = [g for g in groups if len(g) > 1]
            if not groups:
                continue  # singleton groups: no bytes cross any link
        if is_start:
            # An async `-start` result is the (operand, output, ...) buffer
            # tuple: summing it would double-count the collective. The
            # largest single buffer is the communicated tensor (full size
            # for gather/reduce either way under the max(in, out) rule).
            volume = max(
                _shape_bytes(result_seg) + _shape_bytes(operand_seg),
                default=0.0,
            )
        else:
            volume = max(_segment_bytes(result_seg), _segment_bytes(operand_seg))
        opname = OPNAME_RE.search(attrs)
        out.append(
            Collective(
                op=op,
                name=head.replace("ROOT ", "").strip(),
                bytes=volume,
                axes=mesh_axes_for_groups(groups or (), coords, axis_names),
                groups=len(groups) if groups else 0,
                group_size=max((len(g) for g in groups), default=0) if groups else 0,
                result_shape=result_seg.strip(),
                op_name=opname.group(1) if opname else "",
            )
        )
    return CommInventory(collectives=out, label=label, chain_length=chain_length)


# -- the analytic expected-comm model ---------------------------------------


@dataclasses.dataclass
class ExpectedComm:
    """Analytic per-step comm bytes derived from mesh + resolved rules."""

    terms: dict  # {"grad_sync": ..., "fsdp_gather": ..., "tp_activations": ...}
    leaves: list  # [{path, shape, dtype, bytes, axes, rule}] for param leaves
    chain_length: int = 1

    @property
    def total(self) -> float:
        return float(sum(self.terms.values())) * self.chain_length

    def tensor_leaves(self) -> list:
        return [
            leaf for leaf in self.leaves
            if any(a in NEVER_GATHER_AXES for a in leaf["axes"])
        ]

    def describe(self) -> str:
        terms = ", ".join(f"{k}={int(v)}B" for k, v in self.terms.items() if v)
        return (
            f"expected model: {int(self.total)} B/window "
            f"(x{self.chain_length} step(s); {terms or 'no comm expected'})"
        )


def _spec_axes(spec) -> tuple[str, ...]:
    axes: list[str] = []
    for entry in tuple(spec):
        if entry is None:
            continue
        for name in entry if isinstance(entry, tuple) else (entry,):
            axes.append(str(name))
    return tuple(axes)


def expected_comm(engine, state, batch, *, chain_length: int = 1) -> ExpectedComm:
    """The ISSUE 11 model, from the engine's OWN resolved shardings (the
    same ``state_sharding_tree`` the dispatch path lays state out with) —
    deliberately an over-estimate of legitimate comm (grad syncs counted at
    full leaf bytes even when the wgrad runs on shards), because its check
    only fires *above* tolerance: what it bounds is comm the derivation
    cannot explain at all."""
    from jax.tree_util import keystr, tree_flatten_with_path

    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
    from distributed_training_pytorch_tpu.parallel import sharding as sharding_lib

    mesh = engine.mesh
    abstract_state = jax.eval_shape(lambda s: s, state)
    shardings = engine.state_sharding_tree(abstract_state)
    rules = tuple(engine.sharding_rules or ())
    state_leaves = tree_flatten_with_path(abstract_state)[0]
    sharding_leaves = jax.tree.leaves(
        shardings, is_leaf=lambda s: isinstance(s, jax.sharding.Sharding)
    )
    leaves = []
    for (path, leaf), sharding in zip(state_leaves, sharding_leaves, strict=True):
        path_str = keystr(path)
        if ".params" not in path_str:
            continue
        shape = tuple(getattr(leaf, "shape", ()) or ())
        spec = getattr(sharding, "spec", jax.sharding.PartitionSpec())
        matched = sharding_lib.rule_for_leaf(path_str, shape, mesh, rules)
        leaves.append(
            {
                "path": path_str,
                "shape": shape,
                "dtype": str(getattr(leaf, "dtype", None)),
                "bytes": aval_bytes(shape, getattr(leaf, "dtype", None)),
                "axes": _spec_axes(spec),
                "rule": matched[0] if matched else None,
            }
        )
    extent = mesh_lib.batch_shard_extent(mesh)
    tensor = int(mesh.shape.get(mesh_lib.TENSOR_AXIS, 1))
    terms = {"grad_sync": 0.0, "fsdp_gather": 0.0, "tp_activations": 0.0}
    if extent > 1:
        # One gradient sync per param leaf (all-reduce, or the
        # reduce-scatter+all-gather pair ZeRO splits it into — same full
        # bytes either way under the inventory's max(in, out) convention).
        terms["grad_sync"] = sum(leaf["bytes"] for leaf in leaves)
    for leaf in leaves:
        if mesh_lib.FSDP_AXIS in leaf["axes"]:
            # Forward all-gather + backward re-gather/scatter traffic.
            terms["fsdp_gather"] += 2.0 * leaf["bytes"]
    if tensor > 1:
        batch_leaves = jax.tree.leaves(batch)
        rows = 0
        if batch_leaves:
            lead = tuple(getattr(batch_leaves[0], "shape", ()) or (0,))[0]
            rows = max(1, int(lead) // max(1, extent))
        for leaf in leaves:
            if any(a in NEVER_GATHER_AXES for a in leaf["axes"]):
                # Per-layer activation syncs, fwd + bwd: rows x the layer's
                # dim sum is a ceiling for the activation tensors that cross
                # the tensor axis around this weight.
                width = sum(leaf["shape"]) if leaf["shape"] else 1
                dtype_bytes = aval_bytes((1,), leaf["dtype"])
                terms["tp_activations"] += 2.0 * rows * width * dtype_bytes
    return ExpectedComm(terms=terms, leaves=leaves, chain_length=chain_length)


# -- the two failure modes --------------------------------------------------


def comm_findings(
    inventory: CommInventory,
    expected: ExpectedComm,
    *,
    tolerance: float = MODEL_TOLERANCE,
) -> list[dict]:
    """Apply the two hard failure modes to one program's inventory. Each
    finding carries the offending HLO op and the leaf/rule it traces to."""
    findings: list[dict] = []
    # Per-LEAF thresholds (the ISSUE 11 wording: "any collective moving >=
    # the full unsharded param bytes"): a gather of a small kernel's full
    # bytes must fire even when a bigger kernel exists, and attribution
    # names the largest leaf the volume explains. Scoped to weight-shaped
    # leaves (ndim >= 2): bias vectors are activation-scale, and a clean
    # program's activation gathers would false-positive against them (a
    # mis-ruled bias still shows up in the baseline gate's totals).
    tensor_leaves = [
        leaf for leaf in expected.tensor_leaves() if len(leaf["shape"]) >= 2
    ]
    if tensor_leaves:
        for c in inventory.collectives:
            if c.op != "all-gather":
                continue
            if not any(a in NEVER_GATHER_AXES for a in c.axes):
                continue
            explained = [
                leaf for leaf in tensor_leaves if c.bytes >= leaf["bytes"]
            ]
            if not explained:
                continue
            leaf = max(explained, key=lambda x: x["bytes"])
            findings.append(
                {
                    "kind": "accidental-gather",
                    "op": c.name,
                    "bytes": c.bytes,
                    "axes": c.axes,
                    "leaf": leaf["path"],
                    "rule": leaf["rule"],
                    "detail": (
                        f"{c.name} moves {int(c.bytes)} B over "
                        f"{'x'.join(c.axes)} — >= the full unsharded "
                        f"{int(leaf['bytes'])} B of {leaf['path']} "
                        f"(rule {leaf['rule']!r}): a {'/'.join(NEVER_GATHER_AXES)}-"
                        "sharded parameter must never be gathered whole; "
                        "this is the mis-rule signature (a reduce-scatter "
                        "turned into a full param all-gather)"
                    ),
                }
            )
    if expected.total > 0 and inventory.total_bytes > expected.total * (1.0 + tolerance):
        worst = max(inventory.collectives, key=lambda c: c.bytes, default=None)
        findings.append(
            {
                "kind": "model-exceeded",
                "op": worst.name if worst else "",
                "bytes": inventory.total_bytes,
                "axes": worst.axes if worst else (),
                "leaf": None,
                "rule": None,
                "detail": (
                    f"total comm {int(inventory.total_bytes)} B exceeds the "
                    f"analytic model's {int(expected.total)} B x "
                    f"(1+{tolerance:g}) — comm the mesh+rules derivation "
                    "cannot explain (largest op: "
                    f"{worst.describe() if worst else 'n/a'})"
                ),
            }
        )
    elif expected.total == 0 and inventory.total_bytes > 0:
        findings.append(
            {
                "kind": "model-exceeded",
                "op": inventory.collectives[0].name,
                "bytes": inventory.total_bytes,
                "axes": inventory.collectives[0].axes,
                "leaf": None,
                "rule": None,
                "detail": (
                    f"model expects ZERO comm on this mesh but the program "
                    f"moves {int(inventory.total_bytes)} B"
                ),
            }
        )
    return findings


# -- per-mesh-spec audit + the gate -----------------------------------------


@dataclasses.dataclass
class CommSpecReport:
    """One mesh layout's audit: single + chained inventories, the model,
    findings, and the baseline verdict."""

    spec: str
    single: CommInventory
    chained: CommInventory
    expected: ExpectedComm
    chain_steps: int
    findings: list
    gate: GateResult | None = None
    injected: bool = False

    @property
    def ok(self) -> bool:
        if self.findings:
            return False
        return self.gate is None or self.gate.passed

    def measurement(self) -> dict:
        """The JSON-safe baseline entry for this spec (the figures
        ``COMM_BASELINE.json`` persists and the gate re-measures)."""
        return {
            "comm_bytes_per_step": round(self.single.total_bytes, 1),
            "comm_bytes_chained": round(self.chained.total_bytes, 1),
            "chain_steps": self.chain_steps,
            "collectives": len(self.single.collectives),
            "platform": jax.devices()[0].platform,
            "workload": "auditnet-conv8-dense10",
        }

    def describe(self) -> str:
        lines = [f"comm[{self.spec}]:"]
        lines.append("    " + self.single.describe())
        lines.append("    " + self.chained.describe())
        lines.append("    " + self.expected.describe())
        for f in self.findings:
            lines.append(f"    FAIL {f['kind']}: {f['detail']}")
        if self.gate is not None:
            lines.append("    " + self.gate.describe())
        if self.ok and not self.findings:
            lines.append("    OK")
        return "\n".join(lines)


@dataclasses.dataclass
class CommAuditReport:
    specs: list
    injected: bool = False
    skipped: "str | None" = None

    @property
    def ok(self) -> bool:
        if self.skipped is not None:
            return True  # skipped-and-says-so, the sharded-audit contract
        return all(s.ok for s in self.specs)

    def describe(self) -> str:
        if self.skipped is not None:
            return f"  comm audit: SKIPPED ({self.skipped})"
        return "\n".join("  " + s.describe() for s in self.specs)

    def to_fields(self) -> dict:
        """Flat JSON-safe summary for the ``static_audit`` telemetry event."""
        if self.skipped is not None:
            return {"comm_skipped": self.skipped, "comm_passed": True}
        return {
            "comm_bytes": {
                s.spec: round(s.single.total_bytes, 1) for s in self.specs
            },
            "comm_findings": sum(len(s.findings) for s in self.specs),
            "comm_gate_failures": sum(
                1 for s in self.specs if s.gate is not None and not s.gate.passed
            ),
            "comm_injected": self.injected,
            "comm_passed": self.ok,
        }


# The injected mis-rule (the --inject-violation comm seam): anchored to the
# *params* subtree only, so the momentum twin in opt_state falls back to
# replicated on a tensor x data mesh — the optimizer update then has a
# tensor-sharded gradient feeding a replicated momentum leaf, and the
# partitioner MUST all-gather the full parameter-shaped buffer every step
# (measured: `all-gather f32[512,5]->[512,10]` over the tensor axis, the
# exact full-kernel 20480 B). One over-anchored regex = the one-line
# sharding-rule mistake the motivation names.
_MISRULED_TP_RULES = (
    (r"\.params\['Dense_0'\]\['kernel'\]",
     jax.sharding.PartitionSpec(None, "tensor")),
)


def _spec_engine(spec: str, *, rules="auto"):
    """Audit engine for one mesh-spec string over the first 8 devices, with
    the HLO audit's fixture conventions (low ``fsdp_min_size`` + explicit TP
    rule so the small fixture leaves genuinely shard)."""
    from distributed_training_pytorch_tpu.analysis.hlo_audit import (
        _AUDIT_FSDP_MIN_SIZE,
        _AUDIT_SHARDING_RULES,
        build_audit_engine,
    )
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.mesh_config_from_spec(spec).build(devices=jax.devices()[:8])
    if rules == "auto":
        rules = (
            _AUDIT_SHARDING_RULES
            if mesh.shape.get(mesh_lib.TENSOR_AXIS, 1) > 1
            else None
        )
    return build_audit_engine(
        mesh=mesh, sharding_rules=rules, fsdp_min_size=_AUDIT_FSDP_MIN_SIZE
    )


def audit_comm_spec(
    spec: str,
    *,
    chain_steps: int = 4,
    rules="auto",
    tolerance: float = MODEL_TOLERANCE,
    injected: bool = False,
) -> CommSpecReport:
    """Inventory + model + failure modes for one mesh layout's real
    single-step AND chained programs (abstract lowerings only)."""
    from distributed_training_pytorch_tpu.train.engine import stack_chain_batch

    engine, state, batch = _spec_engine(spec, rules=rules)
    single_compiled = engine.compile_step_probe(state, batch, donate=True)
    single = collective_inventory(
        single_compiled.as_text(), engine.mesh, label=f"{spec} single-step"
    )
    window = stack_chain_batch(batch, chain_steps)
    chained_compiled = engine.compile_step_probe(
        state, window, donate=True, chain_length=chain_steps
    )
    chained = collective_inventory(
        chained_compiled.as_text(),
        engine.mesh,
        label=f"{spec} chained x{chain_steps}",
        chain_length=chain_steps,
    )
    expected = expected_comm(engine, state, batch)
    findings = comm_findings(single, expected, tolerance=tolerance)
    expected_window = expected_comm(
        engine, state, batch, chain_length=chain_steps
    )
    findings += comm_findings(chained, expected_window, tolerance=tolerance)
    return CommSpecReport(
        spec=spec,
        single=single,
        chained=chained,
        expected=expected,
        chain_steps=chain_steps,
        findings=findings,
        injected=injected,
    )


def run_comm_audit(
    chain_steps: int = 4,
    *,
    inject_violation: bool = False,
    baseline: "dict | None" = None,
    model_tolerance: float = MODEL_TOLERANCE,
) -> CommAuditReport:
    """The full comm audit: every :data:`AUDIT_MESH_SPECS` layout's real
    single-step and chained programs, each gated against ``baseline`` (a
    loaded ``COMM_BASELINE.json`` dict; None = no baseline gating — the
    tests' mode). ``inject_violation=True`` audits ONLY the mis-ruled TP
    spec, which MUST come back failing with an accidental-gather finding —
    the self-test exercises the detector; the clean specs already ran in
    the clean pass, and re-auditing them would double verify.sh's stage-2
    comm cost for zero coverage.

    Needs >= 8 devices (the forced-host-platform convention shared with the
    HLO audit's sharded twins); fewer -> a report that says SKIPPED rather
    than a vacuous pass."""
    if jax.device_count() < 8:
        return CommAuditReport(
            specs=[],
            injected=inject_violation,
            skipped=(
                f"needs >= 8 devices for the audited meshes, have "
                f"{jax.device_count()} (scripts/static_audit.py forces an "
                "8-device host platform via compat.force_host_devices)"
            ),
        )
    if inject_violation:
        report = audit_comm_spec(
            "tp2x4",
            chain_steps=chain_steps,
            rules=_MISRULED_TP_RULES,
            tolerance=model_tolerance,
            injected=True,
        )
        report.spec = "tp2x4(mis-ruled)"
        return CommAuditReport(specs=[report], injected=True)
    reports: list[CommSpecReport] = []
    for spec in AUDIT_MESH_SPECS:
        report = audit_comm_spec(
            spec, chain_steps=chain_steps, tolerance=model_tolerance
        )
        if baseline is not None:
            entries = baseline.get("entries", {})
            if spec not in entries:
                report.findings.append(
                    {
                        "kind": "no-baseline",
                        "op": "",
                        "bytes": report.single.total_bytes,
                        "axes": (),
                        "leaf": None,
                        "rule": None,
                        "detail": (
                            f"no COMM_BASELINE.json entry {spec!r} — record "
                            "one with scripts/static_audit.py "
                            "--update-comm-baseline"
                        ),
                    }
                )
            else:
                tol = baseline.get("tolerance", {}).get(spec, BASELINE_TOLERANCE)
                report.gate = gate_check(
                    report.single.total_bytes,
                    float(entries[spec]["comm_bytes_per_step"]),
                    float(tol),
                    key=spec,
                    metric="comm_bytes_per_step",
                )
        reports.append(report)
    return CommAuditReport(specs=reports, injected=False)


def record_comm_baseline(
    path: str = COMM_BASELINE_PATH,
    *,
    chain_steps: int = 4,
    tolerance: float = BASELINE_TOLERANCE,
) -> CommAuditReport:
    """The ``--update`` ritual: re-measure every audited spec and persist
    its totals (refusing to record a failing audit — a baseline must never
    memorialize a mis-ruled program). Uses ``profiling.gate``'s writer, so
    the file format, atomic replace, and torn-file recovery match
    ``PERF_BASELINE.json`` exactly."""
    report = run_comm_audit(chain_steps=chain_steps, baseline=None)
    if not report.ok or report.skipped is not None:
        raise ValueError(
            "refusing to record COMM_BASELINE.json from a failing or "
            "skipped audit:\n" + report.describe()
        )
    for spec_report in report.specs:
        update_baseline(
            path, spec_report.spec, spec_report.measurement(), tolerance=tolerance
        )
    return report


def load_comm_baseline(path: str = COMM_BASELINE_PATH) -> dict:
    """``profiling.gate.load_baseline`` on the comm file — one loader, one
    schema (``{"entries": ..., "tolerance": ...}``)."""
    return load_baseline(path)


def comm_fields(compiled, mesh) -> dict:
    """Bench-facing summary of one compiled executable's collectives — the
    SAME inventory code path the gate checks, so a ``BENCH_MESH`` sweep
    entry and the audit argue about identical numbers. For a rolled-scan
    chained executable (``compile_chained_train_steps``) the loop body — and
    so each collective — appears once, making this a per-step figure by the
    same convention ``cost_analysis()`` uses. Never raises: a parse failure
    costs only these fields (the bench-profile degradation contract)."""
    try:
        inventory = collective_inventory(compiled.as_text(), mesh)
        return {
            "comm_bytes_per_step": int(inventory.total_bytes),
            "comm_collectives": len(inventory.collectives),
            "comm": {op: int(v) for op, v in sorted(inventory.by_op().items())},
        }
    except Exception as e:  # pragma: no cover - defensive: bench must not die
        import warnings

        warnings.warn(f"comm_fields: inventory failed ({e}); fields omitted")
        return {}
