"""Generic lint layer: ruff when available, a stdlib fallback otherwise.

jaxlint (``analysis.lint``) carries only project-specific rules; the
generic hygiene layer (pyflakes/pycodestyle-class checks, import sorting)
belongs to ``ruff``, configured in ``pyproject.toml`` ``[tool.ruff]`` so
every environment that has it runs the same rule set. Hermetic CI images
that do not ship ruff still get a floor: a stdlib fallback that catches the
two highest-value F-class defects with zero dependencies —

* **syntax errors** (a module that cannot parse fails here in milliseconds
  instead of as a collection error ten minutes into tier-1), and
* **unused module-level imports** (F401): dead imports are where stale
  dependencies hide, and the one generic defect class that creeps back
  weekly without a gate.

The fallback honors ``# noqa`` on the import's line (the same escape ruff
uses) and skips ``__init__.py`` re-export modules, mirroring the
``per-file-ignores`` in pyproject — the two layers must agree on what
clean means or the gate would flap depending on which machine ran it.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import shutil
import subprocess
from typing import Iterable

__all__ = ["GenericFinding", "GenericReport", "run_generic", "ruff_available"]


@dataclasses.dataclass
class GenericFinding:
    path: str
    line: int
    code: str
    message: str

    def describe(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


@dataclasses.dataclass
class GenericReport:
    findings: list[GenericFinding]
    tool: str  # "ruff" or "builtin"

    @property
    def ok(self) -> bool:
        return not self.findings


def ruff_available() -> bool:
    return shutil.which("ruff") is not None


def _python_files(paths: Iterable[str]) -> list[str]:
    files: list[str] = []
    for root in paths:
        if os.path.isdir(root):
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [
                    d for d in dirnames if d not in ("__pycache__", ".git")
                ]
                files.extend(
                    os.path.join(dirpath, f)
                    for f in filenames
                    if f.endswith(".py")
                )
        elif root.endswith(".py"):
            files.append(root)
    return sorted(files)


def _run_ruff(paths: list[str]) -> GenericReport:
    proc = subprocess.run(
        ["ruff", "check", "--output-format", "json", *paths],
        capture_output=True,
        text=True,
        check=False,
    )
    findings: list[GenericFinding] = []
    try:
        rows = json.loads(proc.stdout or "[]")
    except json.JSONDecodeError:
        rows = []
        if proc.returncode not in (0, 1):
            findings.append(
                GenericFinding(
                    path="<ruff>", line=0, code="RUFF",
                    message=f"ruff failed: {proc.stderr.strip()[:200]}",
                )
            )
    for row in rows:
        findings.append(
            GenericFinding(
                path=os.path.relpath(row.get("filename", "?")),
                line=int((row.get("location") or {}).get("row", 0)),
                code=str(row.get("code")),
                message=str(row.get("message")),
            )
        )
    return GenericReport(findings=findings, tool="ruff")


def _unused_imports(tree: ast.Module, source: str, path: str) -> list[GenericFinding]:
    lines = source.splitlines()

    def noqa(lineno: int) -> bool:
        return 0 < lineno <= len(lines) and "# noqa" in lines[lineno - 1]

    imported: dict[str, tuple[int, str]] = {}  # bound name -> (line, shown)
    for node in tree.body:  # module level only: locals are ruff's business
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                imported[bound] = (node.lineno, alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue  # compiler directives, never "used" by name
            for alias in node.names:
                if alias.name == "*":
                    continue
                # `import x as x` is the explicit re-export idiom — keep.
                if alias.asname is not None and alias.asname == alias.name:
                    continue
                bound = alias.asname or alias.name
                imported[bound] = (node.lineno, alias.name)
    if not imported:
        return []
    # Any Name reference counts as use (an Attribute's root Name is reached
    # by the same walk). String mentions do NOT count — except __all__
    # entries below, the one string convention that genuinely re-exports.
    used: set[str] = {
        node.id for node in ast.walk(tree) if isinstance(node, ast.Name)
    }
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "__all__" for t in node.targets
        ):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    used.add(sub.value)
    findings = []
    for bound, (lineno, shown) in imported.items():
        if bound in used or noqa(lineno):
            continue
        findings.append(
            GenericFinding(
                path=path, line=lineno, code="F401",
                message=f"{shown!r} imported but unused",
            )
        )
    return findings


def _run_builtin(files: list[str]) -> GenericReport:
    findings: list[GenericFinding] = []
    for file in files:
        with open(file, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=file)
        except SyntaxError as e:
            findings.append(
                GenericFinding(
                    path=file, line=e.lineno or 0, code="E999",
                    message=f"syntax error: {e.msg}",
                )
            )
            continue
        if os.path.basename(file) == "__init__.py":
            continue  # re-export modules: per-file-ignores F401 (pyproject)
        findings.extend(_unused_imports(tree, source, file))
    return GenericReport(findings=findings, tool="builtin")


def run_generic(paths: Iterable[str]) -> GenericReport:
    """Run the generic layer over files/directories: ruff with the repo
    config when installed, the stdlib fallback otherwise."""
    files = _python_files(paths)
    if ruff_available():
        return _run_ruff(files)
    return _run_builtin(files)
