"""Static analysis subsystem (ISSUE 7; docs/static_analysis.md).

Three complementary layers, all wired into ``scripts/static_audit.py`` and
run as a ``scripts/verify.sh`` gate:

* ``analysis.generic`` — generic hygiene (ruff when installed, a stdlib
  fallback with syntax + unused-import checks otherwise);
* ``analysis.lint`` — **jaxlint**, AST rules for the JAX-specific bug
  classes this repo has actually shipped (host syncs in compiled regions,
  un-rank-gated file writes, unlocked cross-thread mutation, wall-clock in
  jitted code, bare excepts, undonated state jits), with audited inline
  waivers (``analysis.waivers``);
* ``analysis.hlo_audit`` — invariants checked on the *compiled/lowered*
  programs themselves: full param/opt-state buffer donation, no fp32 MXU
  ops under a low-precision policy, no host callbacks in chained windows;
* ``analysis.comm_audit`` — the SPMD communication audit (ISSUE 11): a
  static collective inventory of the partitioned single-step and chained
  programs (per-op bytes, mesh-axis attribution), an analytic expected-comm
  model with accidental-gather / model-exceeded failure modes, and a
  ``COMM_BASELINE.json`` regression gate mirroring the perf gate's ritual;
* ``analysis.diff`` — structural A/B diffing (ISSUE 14) on the same
  ``compile_step_probe`` lowerings: optimized-HLO op-category/fusion-count
  deltas and per-axis collective-inventory byte deltas with replica-group
  changes named (``scripts/run_compare.py`` is the CLI surface).
"""

from distributed_training_pytorch_tpu.analysis.generic import (
    GenericFinding,
    GenericReport,
    run_generic,
    ruff_available,
)
from distributed_training_pytorch_tpu.analysis.hlo_audit import (
    CallbackReport,
    DonationReport,
    HloAuditReport,
    PrecisionReport,
    audit_donation,
    audit_host_callbacks,
    audit_precision_leaks,
    build_audit_engine,
    parse_input_output_aliases,
    run_hlo_audit,
)
from distributed_training_pytorch_tpu.analysis.lint import (
    RULES,
    Finding,
    LintResult,
    lint_paths,
    lint_source,
)
from distributed_training_pytorch_tpu.analysis.comm_audit import (
    CommAuditReport,
    CommInventory,
    ExpectedComm,
    collective_inventory,
    comm_fields,
    expected_comm,
    run_comm_audit,
)
from distributed_training_pytorch_tpu.analysis.diff import (
    CommDiff,
    HloSignature,
    HloStructuralDiff,
    diff_comm,
    diff_hlo,
    hlo_signature,
)
from distributed_training_pytorch_tpu.analysis.waivers import Waiver, scan_waivers

__all__ = [
    "CommAuditReport",
    "CommDiff",
    "CommInventory",
    "ExpectedComm",
    "HloSignature",
    "HloStructuralDiff",
    "diff_comm",
    "diff_hlo",
    "hlo_signature",
    "collective_inventory",
    "comm_fields",
    "expected_comm",
    "run_comm_audit",
    "GenericFinding",
    "GenericReport",
    "run_generic",
    "ruff_available",
    "CallbackReport",
    "DonationReport",
    "HloAuditReport",
    "PrecisionReport",
    "audit_donation",
    "audit_host_callbacks",
    "audit_precision_leaks",
    "build_audit_engine",
    "parse_input_output_aliases",
    "run_hlo_audit",
    "RULES",
    "Finding",
    "LintResult",
    "lint_paths",
    "lint_source",
    "Waiver",
    "scan_waivers",
]
