"""Inline lint waivers: ``# jaxlint: disable=<rule>[,<rule>] -- <reason>``.

A waiver is an *audited exception*, not an escape hatch: the reason after
``--`` is mandatory (a disable comment without one does not waive anything —
it surfaces as its own ``waiver-missing-reason`` finding), the waiver only
applies to the physical line the finding anchors on (for a multi-line call,
that is the line the call opens on), and ``scripts/static_audit.py`` counts
and prints every waiver in effect so reviewers see the full exception list
on every run, not just the diff that introduced one.

Why same-line only: a file- or block-scoped disable silently covers code
added later — exactly the "reviewer-remembered invariant" failure mode this
subsystem exists to remove. One waiver, one line, one reason.
"""

from __future__ import annotations

import dataclasses
import re

__all__ = ["Waiver", "scan_waivers", "WAIVER_RE"]

WAIVER_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([\w\-]+(?:\s*,\s*[\w\-]+)*)(?:\s*--\s*(.*\S))?"
)


@dataclasses.dataclass
class Waiver:
    """One inline waiver comment."""

    path: str
    line: int
    rules: tuple[str, ...]
    reason: str | None
    used: bool = False  # set when a finding actually matched it

    def covers(self, rule: str) -> bool:
        return rule in self.rules or "all" in self.rules


def scan_waivers(source: str, path: str = "<string>") -> dict[int, Waiver]:
    """Map line number -> :class:`Waiver` for every disable comment.

    Scans raw source lines rather than the AST so a waiver inside a
    multi-line expression is still found on its own physical line. A
    ``jaxlint: disable`` inside a string literal would false-positive here;
    that costs a phantom *unused* waiver in the report, never a silently
    suppressed finding.
    """
    waivers: dict[int, Waiver] = {}
    for lineno, text in enumerate(source.splitlines(), 1):
        m = WAIVER_RE.search(text)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        waivers[lineno] = Waiver(
            path=path, line=lineno, rules=rules, reason=m.group(2)
        )
    return waivers
