// Native data-loader runtime for distributed_training_pytorch_tpu.
//
// The reference delegates its host-side image work to prebuilt native code
// (OpenCV decode/resize, dataset/example_dataset.py:57-60; albumentations'
// SIMD kernels) and its loader parallelism to torch DataLoader workers
// (trainer/trainer.py:209-217). This library is the TPU build's equivalent
// native runtime: JPEG/PNG decode (libjpeg/libpng), cv2-compatible bilinear
// resize (half-pixel centers), normalization, and a deterministic
// crop/flip/normalize augmenter — all batch-level, internally multithreaded,
// and GIL-free (called from Python via ctypes; one call per batch).
//
// Determinism: augmentation randomness is Philox4x32 keyed by
// (seed, epoch<<40 | record_index) — the same key layout as the Python
// pipeline (data/transforms.py philox_key), so results are reproducible
// across hosts and resumes regardless of thread scheduling.

#include <algorithm>
#include <cmath>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>
#include <csetjmp>

extern "C" {

// ---------------------------------------------------------------- Philox4x32
// Counter-based RNG (Salmon et al. 2011), 10 rounds. Key = 2x32, ctr = 4x32.
struct Philox {
  uint32_t key[2];
  uint32_t ctr[4];
  uint32_t out[4];
  int have = 0;

  static void round_(uint32_t* c, const uint32_t* k) {
    const uint64_t m0 = 0xD2511F53, m1 = 0xCD9E8D57;
    uint64_t p0 = m0 * c[0], p1 = m1 * c[2];
    uint32_t n0 = (uint32_t)(p1 >> 32) ^ c[1] ^ k[0];
    uint32_t n1 = (uint32_t)p1;
    uint32_t n2 = (uint32_t)(p0 >> 32) ^ c[3] ^ k[1];
    uint32_t n3 = (uint32_t)p0;
    c[0] = n0; c[1] = n1; c[2] = n2; c[3] = n3;
  }

  void init(uint64_t seed, uint64_t stream) {
    key[0] = (uint32_t)seed;
    key[1] = (uint32_t)(seed >> 32);
    ctr[0] = (uint32_t)stream;
    ctr[1] = (uint32_t)(stream >> 32);
    ctr[2] = 0; ctr[3] = 0;
    have = 0;
  }

  uint32_t next() {
    if (!have) {
      uint32_t c[4] = {ctr[0], ctr[1], ctr[2], ctr[3]};
      uint32_t k[2] = {key[0], key[1]};
      const uint32_t w0 = 0x9E3779B9, w1 = 0xBB67AE85;
      for (int r = 0; r < 10; ++r) {
        round_(c, k);
        k[0] += w0; k[1] += w1;
      }
      out[0] = c[0]; out[1] = c[1]; out[2] = c[2]; out[3] = c[3];
      have = 4;
      if (++ctr[2] == 0) ++ctr[3];  // bump counter for the next block
    }
    return out[--have];
  }

  // Uniform in [0, 1).
  double uniform() { return next() * (1.0 / 4294967296.0); }
  // Uniform integer in [0, n).
  uint32_t randint(uint32_t n) { return (uint32_t)(uniform() * n); }
};

// ------------------------------------------------------------------- resize
// Bilinear with half-pixel centers (cv2 INTER_LINEAR convention), RGB u8.
static void bilinear_resize_u8(const uint8_t* src, int sh, int sw,
                               uint8_t* dst, int dh, int dw) {
  if (sh == dh && sw == dw) {
    std::memcpy(dst, src, (size_t)sh * sw * 3);
    return;
  }
  const double sy = (double)sh / dh, sx = (double)sw / dw;
  for (int y = 0; y < dh; ++y) {
    double fy = (y + 0.5) * sy - 0.5;
    int y0 = (int)fy; double wy = fy - y0;
    if (fy < 0) { y0 = 0; wy = 0.0; }
    int y1 = std::min(y0 + 1, sh - 1);
    for (int x = 0; x < dw; ++x) {
      double fx = (x + 0.5) * sx - 0.5;
      int x0 = (int)fx; double wx = fx - x0;
      if (fx < 0) { x0 = 0; wx = 0.0; }
      int x1 = std::min(x0 + 1, sw - 1);
      const uint8_t* p00 = src + ((size_t)y0 * sw + x0) * 3;
      const uint8_t* p01 = src + ((size_t)y0 * sw + x1) * 3;
      const uint8_t* p10 = src + ((size_t)y1 * sw + x0) * 3;
      const uint8_t* p11 = src + ((size_t)y1 * sw + x1) * 3;
      uint8_t* d = dst + ((size_t)y * dw + x) * 3;
      for (int c = 0; c < 3; ++c) {
        double v = p00[c] * (1 - wy) * (1 - wx) + p01[c] * (1 - wy) * wx +
                   p10[c] * wy * (1 - wx) + p11[c] * wy * wx;
        d[c] = (uint8_t)(v + 0.5);
      }
    }
  }
}

// Bilinear resize sampling a WINDOW (x0, y0, cw, ch) of the source — the
// crop+resize core of random-resized-crop; optional horizontal mirror of the
// destination. Same half-pixel-center convention as bilinear_resize_u8.
static void bilinear_resize_window_u8(const uint8_t* src, int sh, int sw,
                                      int x0, int y0, int cw, int ch,
                                      uint8_t* dst, int dh, int dw, bool mirror) {
  const double sy = (double)ch / dh, sx = (double)cw / dw;
  for (int y = 0; y < dh; ++y) {
    double fy = (y + 0.5) * sy - 0.5;
    int iy0 = (int)fy; double wy = fy - iy0;
    if (fy < 0) { iy0 = 0; wy = 0.0; }
    int iy1 = iy0 + 1 < ch ? iy0 + 1 : ch - 1;
    for (int x = 0; x < dw; ++x) {
      int gx = mirror ? (dw - 1 - x) : x;
      double fx = (gx + 0.5) * sx - 0.5;
      int ix0 = (int)fx; double wx = fx - ix0;
      if (fx < 0) { ix0 = 0; wx = 0.0; }
      int ix1 = ix0 + 1 < cw ? ix0 + 1 : cw - 1;
      const uint8_t* p00 = src + ((size_t)(y0 + iy0) * sw + x0 + ix0) * 3;
      const uint8_t* p01 = src + ((size_t)(y0 + iy0) * sw + x0 + ix1) * 3;
      const uint8_t* p10 = src + ((size_t)(y0 + iy1) * sw + x0 + ix0) * 3;
      const uint8_t* p11 = src + ((size_t)(y0 + iy1) * sw + x0 + ix1) * 3;
      uint8_t* d = dst + ((size_t)y * dw + x) * 3;
      for (int c = 0; c < 3; ++c) {
        double v = p00[c] * (1 - wy) * (1 - wx) + p01[c] * (1 - wy) * wx +
                   p10[c] * wy * (1 - wx) + p11[c] * wy * wx;
        d[c] = (uint8_t)(v + 0.5);
      }
    }
  }
}

// ------------------------------------------------------------------- decode
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

static void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = (JpegErr*)cinfo->err;
  longjmp(err->jb, 1);
}

// ---- in-memory decoders (file path slurps and delegates) ------------------

static uint8_t* decode_jpeg_mem(const uint8_t* data, size_t len, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  uint8_t* volatile buf = nullptr;  // setjmp liveness, see decode_jpeg
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    free(buf);
    return nullptr;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, (unsigned long)len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  buf = (uint8_t*)malloc((size_t)(*w) * (*h) * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = buf + (size_t)cinfo.output_scanline * (*w) * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return buf;
}

struct PngMemReader {
  const uint8_t* data;
  size_t len, pos;
};

static void png_mem_read(png_structp png, png_bytep out, png_size_t count) {
  PngMemReader* r = (PngMemReader*)png_get_io_ptr(png);
  if (r->pos + count > r->len) png_error(png, "png: read past end of buffer");
  memcpy(out, r->data + r->pos, count);
  r->pos += count;
}

static uint8_t* decode_png_mem(const uint8_t* data, size_t len, int* h, int* w) {
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) return nullptr;
  png_infop info = png_create_info_struct(png);
  uint8_t* volatile buf = nullptr;
  png_bytep* volatile rows = nullptr;
  PngMemReader reader{data, len, 0};
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    free(buf);
    free(rows);
    return nullptr;
  }
  png_set_read_fn(png, &reader, png_mem_read);
  png_read_info(png, info);
  *w = png_get_image_width(png, info);
  *h = png_get_image_height(png, info);
  png_byte color = png_get_color_type(png, info);
  png_byte depth = png_get_bit_depth(png, info);
  if (depth == 16) png_set_strip_16(png);
  if (color == PNG_COLOR_TYPE_PALETTE) png_set_palette_to_rgb(png);
  if (color == PNG_COLOR_TYPE_GRAY && depth < 8) png_set_expand_gray_1_2_4_to_8(png);
  if (png_get_valid(png, info, PNG_INFO_tRNS)) png_set_tRNS_to_alpha(png);
  if (color == PNG_COLOR_TYPE_GRAY || color == PNG_COLOR_TYPE_GRAY_ALPHA)
    png_set_gray_to_rgb(png);
  if (color & PNG_COLOR_MASK_ALPHA || png_get_valid(png, info, PNG_INFO_tRNS))
    png_set_strip_alpha(png);
  png_read_update_info(png, info);
  buf = (uint8_t*)malloc((size_t)(*w) * (*h) * 3);
  rows = (png_bytep*)malloc((size_t)(*h) * sizeof(png_bytep));
  for (int y = 0; y < *h; ++y) rows[y] = buf + (size_t)y * (*w) * 3;
  png_read_image(png, rows);
  png_destroy_read_struct(&png, &info, nullptr);
  free(rows);
  return buf;
}

static uint8_t* decode_bytes(const uint8_t* data, size_t len, int* h, int* w) {
  if (len >= 2 && data[0] == 0xFF && data[1] == 0xD8)
    return decode_jpeg_mem(data, len, h, w);
  if (len >= 8 && png_sig_cmp(const_cast<png_bytep>(data), 0, 8) == 0)
    return decode_png_mem(data, len, h, w);
  return nullptr;
}

// File path: slurp and delegate, so there is exactly ONE decoder per format
// (the mem/file paths previously duplicated the setjmp/transform logic).
static uint8_t* decode_file(const char* path, int* h, int* w) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  rewind(f);
  if (size <= 0) { fclose(f); return nullptr; }
  uint8_t* data = (uint8_t*)malloc((size_t)size);
  if (!data) { fclose(f); return nullptr; }
  size_t got = fread(data, 1, (size_t)size, f);
  fclose(f);
  uint8_t* out = (got == (size_t)size) ? decode_bytes(data, got, h, w) : nullptr;
  free(data);
  return out;
}

// ------------------------------------------------------------------ helpers
static void run_parallel(int64_t n, int threads, void (*fn)(int64_t, void*), void* arg) {
  if (threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i, arg);
    return;
  }
  std::vector<std::thread> pool;
  std::atomic<int64_t>* next = new std::atomic<int64_t>(0);
  int t = (int)std::min<int64_t>(threads, n);
  for (int i = 0; i < t; ++i) {
    pool.emplace_back([=] {
      for (;;) {
        int64_t j = next->fetch_add(1);
        if (j >= n) break;
        fn(j, arg);
      }
    });
  }
  for (auto& th : pool) th.join();
  delete next;
}

// ------------------------------------------------------------------- public

// Decode + resize + normalize a batch of image files.
//   paths:  n file paths
//   out:    [n, out_h, out_w, 3] float32
//   mean/stdv: per-channel (RGB), applied as (px/255 - mean) / stdv
// Returns 0 on success, or (1 + index) of the first file that failed.
struct DecodeArgs {
  const char* const* paths;
  int out_h, out_w;
  const float* mean;
  const float* stdv;
  float* out;
  std::atomic<int64_t>* failed;
};

// img (h x w RGB, freed here) -> resized + normalized floats at out slot i.
static void resize_normalize_into(uint8_t* img, int h, int w, int out_h,
                                  int out_w, const float* mean,
                                  const float* stdv, float* out, int64_t i) {
  std::vector<uint8_t> resized((size_t)out_h * out_w * 3);
  bilinear_resize_u8(img, h, w, resized.data(), out_h, out_w);
  free(img);
  float* dst = out + (size_t)i * out_h * out_w * 3;
  const size_t npx = (size_t)out_h * out_w;
  for (size_t px = 0; px < npx; ++px)
    for (int c = 0; c < 3; ++c)
      dst[px * 3 + c] = (resized[px * 3 + c] / 255.0f - mean[c]) / stdv[c];
}

static void decode_one(int64_t i, void* p) {
  DecodeArgs* a = (DecodeArgs*)p;
  int h = 0, w = 0;
  uint8_t* img = decode_file(a->paths[i], &h, &w);
  if (!img) {
    int64_t expect = -1;
    a->failed->compare_exchange_strong(expect, i);
    return;
  }
  resize_normalize_into(img, h, w, a->out_h, a->out_w, a->mean, a->stdv, a->out, i);
}

int64_t dtp_decode_resize_normalize(const char* const* paths, int64_t n,
                                    int out_h, int out_w, const float* mean,
                                    const float* stdv, float* out, int threads) {
  std::atomic<int64_t> failed(-1);
  DecodeArgs a{paths, out_h, out_w, mean, stdv, out, &failed};
  run_parallel(n, threads, decode_one, &a);
  return failed.load() >= 0 ? failed.load() + 1 : 0;
}

// Same batch kernel over in-memory payloads (record-file shards): per-record
// pointers + lengths (zero-copy from the caller's buffers, same shape as the
// path-based entry).
struct DecodeBytesArgs {
  const uint8_t* const* bufs;
  const int64_t* lengths;
  int out_h, out_w;
  const float* mean;
  const float* stdv;
  float* out;
  std::atomic<int64_t>* failed;
};

static void decode_bytes_one(int64_t i, void* p) {
  DecodeBytesArgs* a = (DecodeBytesArgs*)p;
  int h = 0, w = 0;
  uint8_t* img = decode_bytes(a->bufs[i], (size_t)a->lengths[i], &h, &w);
  if (!img) {
    int64_t expect = -1;
    a->failed->compare_exchange_strong(expect, i);
    return;
  }
  resize_normalize_into(img, h, w, a->out_h, a->out_w, a->mean, a->stdv, a->out, i);
}

int64_t dtp_decode_resize_normalize_bytes(
    const uint8_t* const* bufs, const int64_t* lengths, int64_t n, int out_h,
    int out_w, const float* mean, const float* stdv, float* out, int threads) {
  std::atomic<int64_t> failed(-1);
  DecodeBytesArgs a{bufs, lengths, out_h, out_w, mean, stdv, out, &failed};
  run_parallel(n, threads, decode_bytes_one, &a);
  return failed.load() >= 0 ? failed.load() + 1 : 0;
}

// Decode + resize only, uint8 out — the ship-uint8 TRAIN path over record
// payloads: decode -> resize stays uint8, augmentation stays uint8
// (dtp_augment_crop_flip_u8), normalization runs on device
// (models.InputNormalizer fuses it into the first conv). The float decode
// entries above keep host-side normalize for val/eval pipelines.
struct DecodeU8Args {
  const uint8_t* const* bufs;
  const int64_t* lengths;
  int out_h, out_w;
  uint8_t* out;
  std::atomic<int64_t>* failed;
};

static void decode_u8_one(int64_t i, void* p) {
  DecodeU8Args* a = (DecodeU8Args*)p;
  int h = 0, w = 0;
  uint8_t* img = decode_bytes(a->bufs[i], (size_t)a->lengths[i], &h, &w);
  if (!img) {
    int64_t expect = -1;
    a->failed->compare_exchange_strong(expect, i);
    return;
  }
  bilinear_resize_u8(img, h, w,
                     a->out + (size_t)i * a->out_h * a->out_w * 3,
                     a->out_h, a->out_w);
  free(img);
}

int64_t dtp_decode_resize_u8_bytes(const uint8_t* const* bufs,
                                   const int64_t* lengths, int64_t n,
                                   int out_h, int out_w, uint8_t* out,
                                   int threads) {
  std::atomic<int64_t> failed(-1);
  DecodeU8Args a{bufs, lengths, out_h, out_w, out, &failed};
  run_parallel(n, threads, decode_u8_one, &a);
  return failed.load() >= 0 ? failed.load() + 1 : 0;
}

// Decode + RANDOM-RESIZED-CROP + optional hflip, uint8 out — the ImageNet
// train augmentation: 10 attempts sampling an area fraction in
// [scale_lo, scale_hi] and a log-uniform aspect ratio in [ratio_lo,
// ratio_hi], center-SQUARE fallback — matching this repo's
// transforms.random_resized_crop (torchvision instead clamps the fallback
// crop to the ratio bounds; the distributions differ only on extreme-aspect
// images that exhaust all 10 attempts). Fused with the decode so the
// full-size image never leaves this call. Philox keyed (seed,
// epoch<<40 | index[i]) like every other augmenter here.
struct DecodeRrcArgs {
  const uint8_t* const* bufs;
  const int64_t* lengths;
  int out_h, out_w;
  uint64_t seed, epoch;
  const int64_t* indices;
  int hflip;
  float scale_lo, scale_hi, ratio_lo, ratio_hi;
  uint8_t* out;
  std::atomic<int64_t>* failed;
};

static void decode_rrc_one(int64_t i, void* p) {
  DecodeRrcArgs* a = (DecodeRrcArgs*)p;
  int h = 0, w = 0;
  uint8_t* img = decode_bytes(a->bufs[i], (size_t)a->lengths[i], &h, &w);
  if (!img) {
    int64_t expect = -1;
    a->failed->compare_exchange_strong(expect, i);
    return;
  }
  Philox rng;
  rng.init(a->seed, (a->epoch << 40) | (uint64_t)a->indices[i]);
  const double area = (double)h * w;
  const double log_rlo = std::log((double)a->ratio_lo);
  const double log_rhi = std::log((double)a->ratio_hi);
  int x0 = 0, y0 = 0, cw = w, ch = h;
  bool found = false;
  for (int att = 0; att < 10 && !found; ++att) {
    double target = area * (a->scale_lo + rng.uniform() * (a->scale_hi - a->scale_lo));
    double r = std::exp(log_rlo + rng.uniform() * (log_rhi - log_rlo));
    int tw = (int)std::lround(std::sqrt(target * r));
    int th = (int)std::lround(std::sqrt(target / r));
    if (tw > 0 && tw <= w && th > 0 && th <= h) {
      y0 = (int)rng.randint((uint32_t)(h - th + 1));
      x0 = (int)rng.randint((uint32_t)(w - tw + 1));
      cw = tw; ch = th;
      found = true;
    }
  }
  if (!found) {  // center-square fallback (transforms.random_resized_crop)
    int side = h < w ? h : w;
    y0 = (h - side) / 2; x0 = (w - side) / 2;
    cw = side; ch = side;
  }
  bool flip = a->hflip && rng.uniform() < 0.5;
  bilinear_resize_window_u8(img, h, w, x0, y0, cw, ch,
                            a->out + (size_t)i * a->out_h * a->out_w * 3,
                            a->out_h, a->out_w, flip);
  free(img);
}

int64_t dtp_decode_rrc_flip_u8_bytes(
    const uint8_t* const* bufs, const int64_t* lengths, int64_t n, int out_h,
    int out_w, uint64_t seed, uint64_t epoch, const int64_t* indices,
    int hflip, float scale_lo, float scale_hi, float ratio_lo, float ratio_hi,
    uint8_t* out, int threads) {
  std::atomic<int64_t> failed(-1);
  DecodeRrcArgs a{bufs, lengths, out_h, out_w, seed, epoch, indices, hflip,
                  scale_lo, scale_hi, ratio_lo, ratio_hi, out, &failed};
  run_parallel(n, threads, decode_rrc_one, &a);
  return failed.load() >= 0 ? failed.load() + 1 : 0;
}

// Deterministic CIFAR-style augmentation over an in-memory uint8 batch:
// reflect-pad by `pad`, random crop back to (h, w), optional horizontal
// flip (p=0.5), normalize. Randomness keyed by (seed, epoch<<40 | index[i]).
struct AugArgs {
  const uint8_t* in;
  int h, w, pad;
  uint64_t seed, epoch;
  const int64_t* indices;
  const float* mean;
  const float* stdv;
  int hflip;
  float* out;
};

static void augment_one(int64_t i, void* p) {
  AugArgs* a = (AugArgs*)p;
  const int h = a->h, w = a->w, pad = a->pad;
  Philox rng;
  rng.init(a->seed, (a->epoch << 40) | (uint64_t)a->indices[i]);
  int dy = pad ? (int)rng.randint(2 * pad + 1) : 0;
  int dx = pad ? (int)rng.randint(2 * pad + 1) : 0;
  bool flip = a->hflip && rng.uniform() < 0.5;
  const uint8_t* src = a->in + (size_t)i * h * w * 3;
  float* dst = a->out + (size_t)i * h * w * 3;
  for (int y = 0; y < h; ++y) {
    // Reflect-pad source row index (numpy 'reflect': no edge duplication).
    int sy = y + dy - pad;
    if (sy < 0) sy = -sy;
    if (sy >= h) sy = 2 * h - 2 - sy;
    for (int x = 0; x < w; ++x) {
      int gx = flip ? (w - 1 - x) : x;
      int sx = gx + dx - pad;
      if (sx < 0) sx = -sx;
      if (sx >= w) sx = 2 * w - 2 - sx;
      const uint8_t* s = src + ((size_t)sy * w + sx) * 3;
      float* d = dst + ((size_t)y * w + x) * 3;
      for (int c = 0; c < 3; ++c)
        d[c] = (s[c] / 255.0f - a->mean[c]) / a->stdv[c];
    }
  }
}

int64_t dtp_augment_crop_flip(const uint8_t* in, int64_t n, int h, int w,
                              int pad, uint64_t seed, uint64_t epoch,
                              const int64_t* indices, const float* mean,
                              const float* stdv, int hflip, float* out,
                              int threads) {
  AugArgs a{in, h, w, pad, seed, epoch, indices, mean, stdv, hflip, out};
  run_parallel(n, threads, augment_one, &a);
  return 0;
}

// uint8-out augment: same crop/flip (same Philox stream), no normalize —
// for pipelines that ship uint8 over the host->device link (4x fewer bytes)
// and normalize on-device, where XLA fuses it into the first conv.
struct AugU8Args {
  const uint8_t* in;
  int h, w, pad;
  uint64_t seed, epoch;
  const int64_t* indices;
  int hflip;
  uint8_t* out;
};

static void augment_one_u8(int64_t i, void* p) {
  AugU8Args* a = (AugU8Args*)p;
  const int h = a->h, w = a->w, pad = a->pad;
  Philox rng;
  rng.init(a->seed, (a->epoch << 40) | (uint64_t)a->indices[i]);
  int dy = pad ? (int)rng.randint(2 * pad + 1) : 0;
  int dx = pad ? (int)rng.randint(2 * pad + 1) : 0;
  bool flip = a->hflip && rng.uniform() < 0.5;
  const uint8_t* src = a->in + (size_t)i * h * w * 3;
  uint8_t* dst = a->out + (size_t)i * h * w * 3;
  for (int y = 0; y < h; ++y) {
    int sy = y + dy - pad;
    if (sy < 0) sy = -sy;
    if (sy >= h) sy = 2 * h - 2 - sy;
    for (int x = 0; x < w; ++x) {
      int gx = flip ? (w - 1 - x) : x;
      int sx = gx + dx - pad;
      if (sx < 0) sx = -sx;
      if (sx >= w) sx = 2 * w - 2 - sx;
      std::memcpy(dst + ((size_t)y * w + x) * 3,
                  src + ((size_t)sy * w + sx) * 3, 3);
    }
  }
}

int64_t dtp_augment_crop_flip_u8(const uint8_t* in, int64_t n, int h, int w,
                                 int pad, uint64_t seed, uint64_t epoch,
                                 const int64_t* indices, int hflip,
                                 uint8_t* out, int threads) {
  AugU8Args a{in, h, w, pad, seed, epoch, indices, hflip, out};
  run_parallel(n, threads, augment_one_u8, &a);
  return 0;
}

// Normalize-only batch (uint8 NHWC -> float32), the val-path hot loop.
struct NormArgs {
  const uint8_t* in;
  int h, w;
  const float* mean;
  const float* stdv;
  float* out;
};

static void normalize_one(int64_t i, void* p) {
  NormArgs* a = (NormArgs*)p;
  const size_t npx = (size_t)a->h * a->w;
  const uint8_t* src = a->in + (size_t)i * npx * 3;
  float* dst = a->out + (size_t)i * npx * 3;
  for (size_t px = 0; px < npx; ++px)
    for (int c = 0; c < 3; ++c)
      dst[px * 3 + c] = (src[px * 3 + c] / 255.0f - a->mean[c]) / a->stdv[c];
}

int64_t dtp_normalize(const uint8_t* in, int64_t n, int h, int w,
                      const float* mean, const float* stdv, float* out,
                      int threads) {
  NormArgs a{in, h, w, mean, stdv, out};
  run_parallel(n, threads, normalize_one, &a);
  return 0;
}

int dtp_version() { return 1; }

}  // extern "C"
