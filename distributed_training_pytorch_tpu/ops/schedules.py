"""Learning-rate schedules.

``multistep_lr`` reproduces the reference's ``MultiStepLR(milestones, gamma)``
scheduler (``example_trainer.py:65-66``); schedules here are *per-step*
functions (optax convention) while the reference steps per epoch
(``trainer/trainer.py:159``), so constructors take ``steps_per_epoch`` and
epoch-denominated milestones to preserve the epoch semantics exactly.
"""

from __future__ import annotations

from typing import Sequence

import optax


def multistep_lr(
    base_lr: float,
    milestones: Sequence[int],
    gamma: float = 0.1,
    steps_per_epoch: int = 1,
) -> optax.Schedule:
    """LR = base_lr * gamma^(number of milestones passed), milestones in epochs."""
    boundaries = {int(m) * steps_per_epoch: gamma for m in milestones}
    return optax.piecewise_constant_schedule(base_lr, boundaries)


def warmup_cosine_lr(
    base_lr: float,
    total_epochs: int,
    steps_per_epoch: int,
    warmup_epochs: int = 5,
    end_lr: float = 0.0,
) -> optax.Schedule:
    """Linear warmup + cosine decay (the standard recipe for the ViT/ConvNeXt
    configs in BASELINE.json; not present in the reference). Warmup is clamped
    below the run length so degenerate short runs still get a cosine phase."""
    total_steps = max(2, total_epochs * steps_per_epoch)
    warmup_steps = max(1, min(warmup_epochs * steps_per_epoch, total_steps - 1))
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=base_lr,
        warmup_steps=warmup_steps,
        decay_steps=total_steps,
        end_value=end_lr,
    )
