"""Pallas TPU kernels: fused flash attention.

The reference gets its fused kernels from cuDNN via torch
(``/root/reference/requirements.txt:12-24``, ``model/vgg16.py:9-14``); the
TPU-native equivalent obligation (SURVEY.md §2b) is custom Pallas kernels
where plain XLA underperforms — attention being the canonical case: a
materialized ``[B, H, T, T]`` score tensor is HBM-bandwidth-bound, while the
flash formulation streams K/V blocks through VMEM with an online softmax and
never materializes the scores.

Public surface:

* :func:`flash_attention` — ``[B, T, H, D]`` q/k/v -> ``[B, T, H, D]``, same
  contract as ``models.vit.dot_product_attention`` (scale = D**-0.5, optional
  causal mask), differentiable (custom VJP, flash backward kernels).
* :func:`make_attention_fn` — adapter for ``models.vit.MultiHeadAttention``'s
  ``attention_fn`` hook; picks the kernel on TPU and the plain XLA path
  elsewhere.

Kernel design (see /opt/skills/guides/pallas_guide.md): grid over
``(batch, head, q-block)``; K/V live in VMEM as whole ``[T, D]`` slabs per
(batch, head) — fine through ~32k tokens at D=64/128; beyond that, sequence
parallelism (``parallel.ring_attention``) shards T across chips and each shard
re-enters this kernel. Softmax statistics are carried in float32; matmuls run
on the MXU with ``preferred_element_type=float32``. The backward pass is the
standard flash decomposition: a delta precompute (``rowsum(dO * O)``), a
dq kernel gridded over q-blocks, and a dk/dv kernel gridded over k-blocks —
so the [T, T] score matrix is never materialized in either direction.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30  # large-negative logit for masked positions (f32-safe)

# Swept on v5e (GPT-small shapes, fwd+bwd, bf16, D=64): 1024-blocks are
# 2.9x faster than 128-blocks at T=1024 and 4.3x at T=8192 (128: 105/294 ms;
# 1024: 36.8/67.8 ms) — bigger q-tiles amortize the K/V streaming loop and
# fill the MXU; (bq,bk) beyond (1024,1024) exceeds scoped VMEM at long T.
# Blocks auto-clamp to T (rounded up to the 128-lane tile, _block_size),
# so short sequences are unaffected.
_DEFAULT_BLOCK_Q = 1024
_DEFAULT_BLOCK_K = 1024


def _block_size(block: int, t: int) -> int:
    """Clamp a block size to the sequence, rounded up to the MXU tile.

    A raw ``min(block, t)`` leaves ragged blocks at short T (ViT-B's 197),
    and a 197-wide tile maps terribly onto the 128-lane MXU / (8,128) VMEM
    tiling — re-measured on v5e at T=197: aligned 256-blocks run the
    fwd+bwd kernels 2.3x faster than 197-blocks. Padded rows/cols are
    masked by ``seq_len`` inside the kernels (K side) or sliced off by the
    callers (q side), so alignment costs only the pad FLOPs.
    """
    if t >= block:
        return block
    return min(block, ((max(t, 1) + 127) // 128) * 128)


def _pad_to(x: jax.Array, size: int, axis: int) -> jax.Array:
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_k, seq_len, causal):
    """One q-block against all k-blocks, online softmax. Refs are
    (1, 1, bq, D) / (1, 1, Tp, D) blocks; statistics in f32."""
    bq = q_ref.shape[2]
    d = q_ref.shape[3]
    t_pad = k_ref.shape[2]
    n_k = t_pad // block_k

    # Matmuls run in the input dtype (bf16 in production — one MXU pass; an
    # f32 cast would force the 3x-slower f32 path) with f32 accumulation;
    # softmax statistics and the scale multiply stay f32.
    q = q_ref[0, 0]  # [bq, D]
    q_idx = pl.program_id(2) * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]  # [bk, D]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]  # [bk, D]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk] f32
        k_idx = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = k_idx < seq_len
        if causal:
            mask = jnp.logical_and(mask, q_idx >= k_idx)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))  # [bq, 1]
        p = jnp.exp(s - m_new)  # [bq, bk]
        alpha = jnp.exp(m - m_new)  # [bq, 1]
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_k, body, (acc0, m0, l0))
    # Padded q rows (and fully-masked causal rows cannot occur: row i always
    # sees k=i) have l=0 only when the whole row was padding; guard the divide.
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    # Stats live as [1, bq] lane-major rows: a [B, H, 1, T] buffer pads only
    # its singleton sublane dim (8x on 1), where a [..., T, 1] layout would
    # pad the lane dim 128x (measured 384MB/layer on ViT-B — OOM).
    lse_ref[0, 0] = jnp.transpose(m + jnp.log(l_safe), (1, 0))  # [1, bq]


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, block_k, seq_len, causal
):
    """dq for one q-block: dq_i = scale * sum_j (p_ij * (dp_ij - delta_i)) k_j."""
    bq = q_ref.shape[2]
    d = q_ref.shape[3]
    t_pad = k_ref.shape[2]
    n_k = t_pad // block_k

    q = q_ref[0, 0]
    do = do_ref[0, 0]  # [bq, D]
    lse = jnp.transpose(lse_ref[0, 0], (1, 0))  # [1, bq] -> [bq, 1]
    delta = jnp.transpose(delta_ref[0, 0], (1, 0))
    q_idx = pl.program_id(2) * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        k_idx = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = k_idx < seq_len
        if causal:
            mask = jnp.logical_and(mask, q_idx >= k_idx)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(k.dtype)
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, n_k, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale, block_q, seq_len, causal
):
    """dk/dv for one k-block, looping over q-blocks:
    dv_j = sum_i p_ij^T do_i ; dk_j = scale * sum_i (p_ij * (dp_ij - delta_i))^T q_i."""
    bk = k_ref.shape[2]
    d = k_ref.shape[3]
    t_pad = q_ref.shape[2]
    n_q = t_pad // block_q

    k = k_ref[0, 0]  # [bk, D]
    v = v_ref[0, 0]
    k_idx = pl.program_id(2) * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, 0, pl.ds(i * block_q, block_q), :]
        lse = jnp.transpose(lse_ref[0, 0, :, pl.ds(i * block_q, block_q)], (1, 0))
        delta = jnp.transpose(delta_ref[0, 0, :, pl.ds(i * block_q, block_q)], (1, 0))
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk] f32
        q_idx = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
        mask = k_idx < seq_len
        if causal:
            mask = jnp.logical_and(mask, q_idx >= k_idx)
        s = jnp.where(mask, s, NEG_INF)
        # [bq, bk]. Padded q rows (zero q, zero-padded lse) give s=0, lse=0,
        # p=1 — NOT p=0. Their dv/dk contributions still vanish only because
        # dO and delta are zero-padded (dv += p^T·dO = 0; ds = p*(dp-delta)
        # has dp = dO·v^T = 0 and delta = 0). Keep the dO/delta zero-padding.
        p = jnp.exp(s - lse)
        dv_new = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bk, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bk, D]
        return dk_new, dv_new

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n_q, body, (dk0, dv0))
    dk_ref[0, 0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# Wrapper with custom VJP
# ---------------------------------------------------------------------------


def _to_bhtd(x):
    return jnp.transpose(x, (0, 2, 1, 3))  # [B,T,H,D] -> [B,H,T,D]


def _from_bhtd(x):
    return jnp.transpose(x, (0, 2, 1, 3))


def _fwd_call(qt, kt, vt, t_k, causal, bq, bk, interpret):
    """Forward pallas call on padded [B, H, T*, D] operands -> (o, lse) in the
    padded layout. Shared by flash_attention (square T) and the ring block
    path (Tq from the resident shard, Tk from the visiting block)."""
    b, h, tq_pad, d = qt.shape
    tk_pad = kt.shape[2]
    kernel = functools.partial(
        _fwd_kernel, scale=d**-0.5, block_k=bk, seq_len=t_k, causal=causal
    )
    return pl.pallas_call(
        kernel,
        grid=(b, h, tq_pad // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, tk_pad, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, tk_pad, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda bi, hi, qi: (bi, hi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq_pad, d), qt.dtype),
            jax.ShapeDtypeStruct((b, h, 1, tq_pad), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)


def _fwd_impl(q, k, v, causal, block_q, block_k, interpret, valid_len=None):
    b, t, h, d = q.shape
    t_k = t if valid_len is None else valid_len  # kernels mask keys >= t_k
    qt, kt, vt, bq, bk = _ring_pad(q, k, v, block_q, block_k)
    o, lse = _fwd_call(qt, kt, vt, t_k, causal, bq, bk, interpret)
    return o[:, :, :t, :], lse[:, :, :, :t], (qt, kt, vt)


def _dq_call(qt, kt, vt, do, lse_p, delta, t_q, t_k, causal, bq, bk, interpret):
    """dq pallas call on padded [B, H, T*, D] operands. ``t_k`` masks padded
    K rows; ``t_q`` is unused by the kernel (padded q rows produce garbage dq
    rows that callers slice off) but kept for call-site clarity."""
    b, h, tq_pad, d = qt.shape
    tk_pad = kt.shape[2]
    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=d**-0.5, block_k=bk, seq_len=t_k, causal=causal
    )
    return pl.pallas_call(
        dq_kernel,
        grid=(b, h, tq_pad // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, tk_pad, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, tk_pad, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda bi, hi, qi: (bi, hi, 0, qi)),
            pl.BlockSpec((1, 1, 1, bq), lambda bi, hi, qi: (bi, hi, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, tq_pad, d), qt.dtype),
        interpret=interpret,
    )(qt, kt, vt, do, lse_p, delta)


def _dkv_call(qt, kt, vt, do, lse_p, delta, t_q, t_k, causal, bq, bk, interpret):
    """dk/dv pallas call on padded [B, H, T*, D] operands. Padded q rows are
    harmless because ``do``/``delta`` are zero-padded (see _bwd_dkv_kernel);
    ``t_k`` masks padded K rows."""
    b, h, tq_pad, d = qt.shape
    tk_pad = kt.shape[2]
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=d**-0.5, block_q=bq, seq_len=t_k, causal=causal
    )
    return pl.pallas_call(
        dkv_kernel,
        grid=(b, h, tk_pad // bk),
        in_specs=[
            pl.BlockSpec((1, 1, tq_pad, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, tq_pad, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, 1, tq_pad), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, 1, tq_pad), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tk_pad, d), kt.dtype),
            jax.ShapeDtypeStruct((b, h, tk_pad, d), vt.dtype),
        ],
        interpret=interpret,
    )(qt, kt, vt, do, lse_p, delta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_k, interpret, valid_len):
    o, _, _ = _fwd_impl(q, k, v, causal, block_q, block_k, interpret, valid_len)
    return _from_bhtd(o)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, valid_len):
    o, lse, (qt, kt, vt) = _fwd_impl(
        q, k, v, causal, block_q, block_k, interpret, valid_len
    )
    return _from_bhtd(o), (qt, kt, vt, o, lse, q.shape)


def _flash_bwd(causal, block_q, block_k, interpret, valid_len, res, g):
    qt, kt, vt, o, lse, q_shape = res
    b, t, h, d = q_shape
    t_k = t if valid_len is None else valid_len
    bq = _block_size(block_q, t)
    bk = _block_size(block_k, t)
    tq_pad = qt.shape[2]

    do = _pad_to(_to_bhtd(g), tq_pad, 2)
    # delta_i = rowsum(dO_i * O_i) — tiny elementwise precompute, plain XLA.
    delta = jnp.sum(
        do.astype(jnp.float32) * _pad_to(o, tq_pad, 2).astype(jnp.float32),
        axis=-1,
    )[:, :, None, :]  # [B, H, 1, Tq_pad]
    lse_p = _pad_to(lse, tq_pad, 3)

    dq = _dq_call(qt, kt, vt, do, lse_p, delta, t, t_k, causal, bq, bk, interpret)
    dk, dv = _dkv_call(qt, kt, vt, do, lse_p, delta, t, t_k, causal, bq, bk, interpret)

    return (
        _from_bhtd(dq[:, :, :t, :]),
        _from_bhtd(dk[:, :, :t, :]),
        _from_bhtd(dv[:, :, :t, :]),
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Block-level entry points for ring attention (parallel.ring_attention)
# ---------------------------------------------------------------------------
#
# The ring path differentiates at the RING level (one custom VJP around the
# whole rotation schedule), so these wrappers are plain functions: the forward
# returns the per-block (normalized o, lse) the online merge consumes, and the
# backward wrappers compute one block's dq / dk/dv contributions given the
# *global* lse/delta of the resident q shard — exactly the flash
# decomposition, applied blockwise across devices. All take/return
# ``[B, T, H, D]`` (lse/delta ``[B, H, T]``).


def _ring_pad(q, k, v, block_q, block_k):
    tq, tk = q.shape[1], k.shape[1]
    bq = _block_size(block_q, tq)
    bk = _block_size(block_k, tk)
    qt = _pad_to(_to_bhtd(q), pl.cdiv(tq, bq) * bq, 2)
    kt = _pad_to(_to_bhtd(k), pl.cdiv(tk, bk) * bk, 2)
    vt = _pad_to(_to_bhtd(v), pl.cdiv(tk, bk) * bk, 2)
    return qt, kt, vt, bq, bk


def flash_block_fwd(
    q, k, v, *, causal=False,
    block_q=_DEFAULT_BLOCK_Q, block_k=_DEFAULT_BLOCK_K, interpret=None,
):
    """One (q-shard x k/v-block) flash pass -> ``(o, lse)``; o is
    block-normalized, lse = log-sum-exp of this block's logits per q row
    (what the cross-block online merge needs). Not differentiable — the ring
    owns the VJP."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tq, tk = q.shape[1], k.shape[1]
    qt, kt, vt, bq, bk = _ring_pad(q, k, v, block_q, block_k)
    o, lse = _fwd_call(qt, kt, vt, tk, causal, bq, bk, interpret)
    return _from_bhtd(o[:, :, :tq, :]), lse[:, :, 0, :tq]


def flash_block_bwd(
    q, k, v, do, lse, delta, *, causal=False,
    block_q=_DEFAULT_BLOCK_Q, block_k=_DEFAULT_BLOCK_K, interpret=None,
):
    """One block's backward contributions ``(dq, dk, dv)`` given the global
    ``lse``/``delta`` ``[B, H, Tq]`` of the resident q shard."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, tq, h, d = q.shape
    tk = k.shape[1]
    qt, kt, vt, bq, bk = _ring_pad(q, k, v, block_q, block_k)
    tq_pad = qt.shape[2]
    dot = _pad_to(_to_bhtd(do), tq_pad, 2)
    lse_p = _pad_to(lse[:, :, None, :], tq_pad, 3)
    delta_p = _pad_to(delta[:, :, None, :], tq_pad, 3)
    dq = _dq_call(qt, kt, vt, dot, lse_p, delta_p, tq, tk, causal, bq, bk, interpret)
    dk, dv = _dkv_call(qt, kt, vt, dot, lse_p, delta_p, tq, tk, causal, bq, bk, interpret)
    return (
        _from_bhtd(dq[:, :, :tq, :]),
        _from_bhtd(dk[:, :, :tk, :]),
        _from_bhtd(dv[:, :, :tk, :]),
    )


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_q: int = _DEFAULT_BLOCK_Q,
    block_k: int = _DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
    valid_len: Optional[int] = None,
) -> jax.Array:
    """Fused flash attention on ``[B, T, H, D]`` tensors.

    Numerics match ``models.vit.dot_product_attention`` (softmax statistics in
    float32, scale ``D**-0.5``); memory is O(T) per (batch, head) instead of
    the O(T^2) score tensor. ``interpret=None`` auto-selects: compiled on TPU,
    Pallas interpreter elsewhere (slow — tests only). ``valid_len`` masks key
    positions >= it — for caller-padded sequences (``ViT.pad_seq_to``); the
    kernels' own seq_len masking does the work, no score tensor or bias mask
    is ever built.
    """
    if q.ndim != 4 or q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"expected matching [B,T,H,D] q/k/v, got {q.shape}/{k.shape}/{v.shape}")
    if valid_len is not None:
        if causal:
            raise ValueError("valid_len composes with non-causal attention only")
        if not 0 < valid_len <= q.shape[1]:
            raise ValueError(f"valid_len {valid_len} out of range for T={q.shape[1]}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, block_q, block_k, interpret, valid_len)


# Below this sequence length the plain O(T^2) XLA path wins: the score tensor
# is small enough to live in VMEM-friendly fusions, while the kernel pays
# layout transposes + block padding. Re-measured on v5e with the 1024-block
# tiles (fwd+bwd, bf16, D=64): T=197 (ViT-B) 0.75x, T=256 1.0x, T=512 1.2x,
# and the gap widens with T (the plain path OOMs outright at T=8192 beyond
# batch 1 — 12GB score tensors).
FLASH_MIN_SEQ_LEN = 512


def make_attention_fn(causal: bool = False, min_seq_len: int = FLASH_MIN_SEQ_LEN, **kwargs):
    """Adapter for ``MultiHeadAttention(attention_fn=...)`` (models/vit.py).

    Shape-aware: dispatches to the flash kernel when the (static) sequence
    length is long enough for it to beat XLA's fused softmax-attention, and to
    the plain path otherwise — the per-config choice is made once at trace
    time, so the compiled step contains exactly one implementation.
    """

    def attention_fn(q, k, v, valid_len=None):
        if causal and valid_len is not None:
            # Match flash_attention's guard on the short-T branch too — a
            # silently dropped valid_len would attend over pad keys.
            raise ValueError("valid_len composes with non-causal attention only")
        if q.shape[1] < min_seq_len:
            from distributed_training_pytorch_tpu.models.vit import dot_product_attention

            if causal:
                return _causal_plain(q, k, v)
            return dot_product_attention(q, k, v, dtype=q.dtype, valid_len=valid_len)
        return flash_attention(q, k, v, causal=causal, valid_len=valid_len, **kwargs)

    return attention_fn


def _causal_plain(q, k, v):
    t = q.shape[1]
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    idx = jnp.arange(t)
    logits = jnp.where((idx[:, None] >= idx[None, :])[None, None], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


# ---------------------------------------------------------------------------
# Fused 1x1-conv + BN-apply + ReLU (r4 VERDICT item 2: testing the ResNet
# "not reachable from user-level JAX" claim with the one tractable kernel).
#
# A 1x1 conv IS a GEMM: NHWC input flattened to [N, Cin] against [Cin, Cout],
# with the BatchNorm apply folded to a per-output-channel affine
# (a = gamma * rsqrt(var + eps), b = beta - mean * a) and the ReLU as the
# epilogue — one HBM read of x, one write of the activated output, nothing
# materialized in between. ResNet stage-1's 56x56x(64<->256) branches run
# ~28 FLOP/byte on a 240 FLOP/byte v5e — pure bandwidth — so the question is
# only whether a hand-tiled GEMM+epilogue moves more bytes/s than XLA's
# conv+fusion at these shapes (scripts/resnet_pallas_probe.py measures both;
# BASELINE.md records the verdict).


def _resolve_act(relu: bool, act: Optional[str]) -> Optional[str]:
    """Normalize the epilogue knobs: ``act`` (None/"relu"/"gelu") wins when
    given; otherwise the legacy ``relu`` bool maps to "relu"/identity."""
    if act is None:
        return "relu" if relu else None
    if act not in ("relu", "gelu"):
        raise ValueError(f"act must be None, 'relu', or 'gelu' (got {act!r})")
    return act


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    """``interpret=None`` auto-selects like flash_attention: compiled on TPU,
    Pallas interpreter elsewhere — the CPU fallback that lets the fused paths
    run (slowly) under JAX_PLATFORMS=cpu for parity tests."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _conv1x1_kernel(x_ref, w_ref, a_ref, b_ref, o_ref, *, act):
    acc = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    y = acc * a_ref[:] + b_ref[:]
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    elif act == "gelu":
        # tanh approximation — matches flax ``nn.gelu`` (approximate=True),
        # the ConvNeXt expand-Dense epilogue this fusion serves. Computed on
        # the f32 pre-activation, so the plain-path parity gap is only the
        # compute-dtype difference (documented tolerance in tests).
        y = jax.nn.gelu(y, approximate=True)
    o_ref[:] = y.astype(o_ref.dtype)


def conv1x1_bn_act(
    x: jax.Array,
    w: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    *,
    relu: bool = True,
    act: Optional[str] = None,
    block_rows: int = 1024,
    out_dtype=None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """``act((x @ w) * scale + bias)`` fused in one Pallas pass.

    ``x``: ``[..., Cin]`` (e.g. NHWC — leading dims flatten to rows);
    ``w``: ``[Cin, Cout]`` (a 1x1 conv kernel squeezed); ``scale``/``bias``:
    ``[Cout]`` — the folded BN apply (identity: ones/zeros). The epilogue
    activation is ``act`` (``"relu"``/``"gelu"``/``None``); when ``act`` is
    unset the legacy ``relu`` bool picks relu vs identity. Grid over row
    blocks; Cin/Cout stay whole (<= a few hundred channels at ResNet shapes,
    so the weight slab and one x tile sit comfortably in VMEM). Matmul on
    the MXU in f32 accumulation; epilogue on the VPU; output cast to
    ``out_dtype`` (default: x.dtype). ``interpret=None`` auto-selects:
    compiled on TPU, Pallas interpreter elsewhere."""
    act = _resolve_act(relu, act)
    interpret = _resolve_interpret(interpret)
    lead = x.shape[:-1]
    cin = x.shape[-1]
    if w.shape[0] != cin:
        raise ValueError(f"w {w.shape} does not match x Cin {cin}")
    cout = w.shape[1]
    n = 1
    for d in lead:
        n *= d
    out_dtype = out_dtype or x.dtype
    x2 = x.reshape(n, cin)
    n_pad = -(-n // block_rows) * block_rows
    if n_pad != n:
        x2 = jnp.pad(x2, ((0, n_pad - n), (0, 0)))
    a2 = scale.reshape(1, cout).astype(jnp.float32)
    b2 = bias.reshape(1, cout).astype(jnp.float32)
    out = pl.pallas_call(
        functools.partial(_conv1x1_kernel, act=act),
        grid=(n_pad // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, cin), lambda i: (i, 0)),
            pl.BlockSpec((cin, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, cout), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, cout), out_dtype),
        interpret=interpret,
    )(x2, w, a2, b2)
    return out[:n].reshape(*lead, cout)


def _conv1x1_fwd(x, w, scale, bias, act, block_rows, out_dtype, interpret, affine_grads):
    y = conv1x1_bn_act(
        x, w, scale, bias, act=act, relu=False, block_rows=block_rows,
        out_dtype=out_dtype, interpret=interpret,
    )
    return y, (x, w, scale, bias, y)


def _conv1x1_bwd(act, block_rows, out_dtype, interpret, affine_grads, res, g):
    """Standard GEMM backward in XLA dots (same shapes, MXU-friendly).

    relu: dz = g * 1{y>0} * scale — the live mask comes free from the saved
    output, no pre-activation needed. gelu: gelu' needs the pre-activation
    ``u = z*scale + bias`` — z is RECOMPUTED as x @ w (inverting the epilogue
    from y divides by scale, which breaks on the zero-init-gamma BN folds
    this kernel exists to serve) and the exact derivative comes from
    ``jax.vjp`` of the same tanh-approximate gelu the forward ran. Then
    dx = dz @ w^T; dw = x^T @ dz; dscale/dbias reduce the epilogue grads."""
    x, w, scale, bias, y = res
    lead = x.shape[:-1]
    cin, cout = w.shape
    g2 = g.reshape(-1, cout).astype(jnp.float32)
    x2 = x.reshape(-1, cin)
    z = None
    if act == "gelu":
        z = jnp.dot(x2, w, preferred_element_type=jnp.float32)
        u = z * scale.astype(jnp.float32) + bias.astype(jnp.float32)
        _, act_vjp = jax.vjp(lambda t: jax.nn.gelu(t, approximate=True), u)
        (gz,) = act_vjp(g2)  # grad wrt the pre-activation u
    elif act == "relu":
        y2 = y.reshape(-1, cout).astype(jnp.float32)
        gz = jnp.where(y2 > 0, g2, 0.0)
    else:
        gz = g2
    if affine_grads:
        dbias = jnp.sum(gz, axis=0)
        if z is None:
            z = jnp.dot(x2, w, preferred_element_type=jnp.float32)
        dscale = jnp.sum(gz * z, axis=0)
    else:
        # Epilogue declared non-trainable (identity constants): skip the z
        # recompute GEMM entirely (relu/identity only — gelu already paid it).
        dbias = jnp.zeros_like(bias)
        dscale = jnp.zeros_like(scale)
    dz = gz * scale  # [N, cout] f32
    dx = (dz.astype(x.dtype) @ w.T.astype(x.dtype)).reshape(*lead, cin)
    dw = jnp.dot(
        x2.T, dz.astype(x.dtype), preferred_element_type=jnp.float32
    ).astype(w.dtype)
    return dx.astype(x.dtype), dw, dscale.astype(scale.dtype), dbias.astype(bias.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _conv1x1_diff(x, w, scale, bias, act, block_rows, out_dtype, interpret, affine_grads):
    return conv1x1_bn_act(
        x, w, scale, bias, act=act, relu=False, block_rows=block_rows,
        out_dtype=out_dtype, interpret=interpret,
    )


_conv1x1_diff.defvjp(_conv1x1_fwd, _conv1x1_bwd)


def conv1x1_bn_act_diff(
    x: jax.Array,
    w: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    *,
    relu: bool = True,
    act: Optional[str] = None,
    block_rows: int = 1024,
    out_dtype=None,
    interpret: Optional[bool] = None,
    affine_grads: bool = True,
) -> jax.Array:
    """Differentiable :func:`conv1x1_bn_act`: Pallas forward, standard-GEMM
    XLA backward (custom VJP above). The primal output is the only residual
    beyond the inputs — nothing autodiff would not already keep.

    ``affine_grads=False`` declares scale/bias non-trainable constants (the
    ``PallasConv1x1`` identity-epilogue use) and returns zero gradients for
    them, skipping the backward's z-recompute GEMM (relu/identity epilogues;
    gelu recomputes z for its derivative regardless)."""
    return _conv1x1_diff(
        x, w, scale, bias, _resolve_act(relu, act), block_rows,
        out_dtype or x.dtype, _resolve_interpret(interpret), affine_grads,
    )
