from distributed_training_pytorch_tpu.ops.losses import (  # noqa: F401
    cross_entropy_loss,
    softmax_cross_entropy_with_integer_labels,
    weighted_mean,
)
from distributed_training_pytorch_tpu.ops.metrics import accuracy, top_k_accuracy  # noqa: F401
from distributed_training_pytorch_tpu.ops.schedules import (  # noqa: F401
    multistep_lr,
    warmup_cosine_lr,
)
