from distributed_training_pytorch_tpu.ops.losses import (  # noqa: F401
    cross_entropy_loss,
    softmax_cross_entropy_with_integer_labels,
    tied_cross_entropy,
    weighted_mean,
)
from distributed_training_pytorch_tpu.ops.metrics import accuracy, top_k_accuracy  # noqa: F401


def __getattr__(name):
    # Lazy re-export: pulling in jax.experimental.pallas costs real import
    # time, and most ops consumers only want losses/metrics/schedules.
    if name in ("flash_attention", "make_attention_fn"):
        from distributed_training_pytorch_tpu.ops import pallas

        return getattr(pallas, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from distributed_training_pytorch_tpu.ops.schedules import (  # noqa: F401
    multistep_lr,
    warmup_cosine_lr,
)
