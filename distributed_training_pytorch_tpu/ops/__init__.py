from distributed_training_pytorch_tpu.ops.losses import (  # noqa: F401
    cross_entropy_loss,
    softmax_cross_entropy_with_integer_labels,
    tied_cross_entropy,
    weighted_mean,
)
from distributed_training_pytorch_tpu.ops.metrics import accuracy, top_k_accuracy  # noqa: F401


def __getattr__(name):
    # Lazy re-export: pulling in jax.experimental.pallas costs real import
    # time, and most ops consumers only want losses/metrics/schedules.
    if name in ("flash_attention", "make_attention_fn", "conv1x1_bn_act", "conv1x1_bn_act_diff"):
        from distributed_training_pytorch_tpu.ops import pallas

        return getattr(pallas, name)
    if name in ("pallas_from_env", "kernel_dispatch"):
        # The dispatch policy layer (ops/dispatch.py) is pure stdlib — cheap —
        # but kept lazy for symmetry; ``kernel_dispatch`` returns the module.
        from distributed_training_pytorch_tpu.ops import dispatch

        if name == "kernel_dispatch":
            return dispatch
        return getattr(dispatch, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from distributed_training_pytorch_tpu.ops.schedules import (  # noqa: F401
    multistep_lr,
    warmup_cosine_lr,
)
