"""Evaluation metrics.

Covers the reference's two metric computations: batch accuracy inside
``validate_step`` (``example_trainer.py:92-102``) and offline top-k accuracy
(``eval.py:69-72``, computed there via sklearn). Everything is a pure jnp
function so it can live inside a jitted eval step and be globally reduced for
free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_training_pytorch_tpu.ops.losses import weighted_mean


def accuracy(
    logits: jax.Array, labels: jax.Array, weights: jax.Array | None = None
) -> jax.Array:
    """Top-1 accuracy over the batch (scalar in [0, 1]). ``weights`` (e.g. the
    loader's pad ``mask``) makes it a weighted mean over real rows only."""
    return weighted_mean(jnp.argmax(logits, axis=-1) == labels, weights)


def top_k_accuracy(
    logits: jax.Array, labels: jax.Array, k: int = 1, weights: jax.Array | None = None
) -> jax.Array:
    """Top-k accuracy: fraction of rows whose true label is among the k
    highest-scoring classes. Equivalent to sklearn's ``top_k_accuracy_score``
    used by the reference's offline evaluator (``eval.py:69-70``)."""
    _, top_idx = jax.lax.top_k(logits, k)
    hit = (top_idx == labels[..., None]).any(axis=-1)
    return weighted_mean(hit, weights)


def correct_count(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Number of correct top-1 predictions (for exact dataset-level accuracy
    when the last batch is padded)."""
    return (jnp.argmax(logits, axis=-1) == labels).sum()
