"""Loss functions.

Replaces the reference's criterion hook output (``example_trainer.py:55-58`` —
a closure over ``F.cross_entropy`` on raw logits). Losses always accumulate in
float32 even when activations are bfloat16, so bf16 training (BASELINE config 5)
keeps a stable loss scale without GradScaler machinery.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy_with_integer_labels(
    logits: jax.Array,
    labels: jax.Array,
    *,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Per-example stable softmax CE from integer labels. Returns shape [B]."""
    logits = logits.astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, labels[..., None], axis=-1)[..., 0]
    if label_smoothing:
        smooth = -log_probs.mean(axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    return nll


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    *,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Mean CE over the (global) batch — under ``jit`` with a data-sharded
    batch this mean is computed collectively, so the reported loss is the
    *global* loss, fixing the reference's local-only reporting
    (``trainer/trainer.py:175-178``)."""
    return softmax_cross_entropy_with_integer_labels(
        logits, labels, label_smoothing=label_smoothing
    ).mean()
