"""Loss functions.

Replaces the reference's criterion hook output (``example_trainer.py:55-58`` —
a closure over ``F.cross_entropy`` on raw logits). Losses always accumulate in
float32 even when activations are bfloat16, so bf16 training (BASELINE config 5)
keeps a stable loss scale without GradScaler machinery.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy_with_integer_labels(
    logits: jax.Array,
    labels: jax.Array,
    *,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Per-example stable softmax CE from integer labels. Returns shape [B]."""
    logits = logits.astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, labels[..., None], axis=-1)[..., 0]
    if label_smoothing:
        smooth = -log_probs.mean(axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    return nll


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    *,
    label_smoothing: float = 0.0,
    weights: jax.Array | None = None,
) -> jax.Array:
    """Mean CE over the (global) batch — under ``jit`` with a data-sharded
    batch this mean is computed collectively, so the reported loss is the
    *global* loss, fixing the reference's local-only reporting
    (``trainer/trainer.py:175-178``).

    ``weights`` (shape [B], e.g. the loader's pad ``mask``) turns the mean into
    a weighted mean so padded rows contribute nothing."""
    nll = softmax_cross_entropy_with_integer_labels(
        logits, labels, label_smoothing=label_smoothing
    )
    return weighted_mean(nll, weights)


def weighted_mean(values: jax.Array, weights: jax.Array | None = None) -> jax.Array:
    """Mean of per-example values, optionally weighted (pad-mask aware).
    An all-zero weight vector yields 0, not NaN; fractional weights divide by
    their true sum."""
    values = values.astype(jnp.float32)
    if weights is None:
        return values.mean()
    weights = weights.astype(jnp.float32)
    total = weights.sum()
    return jnp.where(total > 0, (values * weights).sum() / jnp.maximum(total, 1e-8), 0.0)
