"""Loss functions.

Replaces the reference's criterion hook output (``example_trainer.py:55-58`` —
a closure over ``F.cross_entropy`` on raw logits). Losses always accumulate in
float32 even when activations are bfloat16, so bf16 training (BASELINE config 5)
keeps a stable loss scale without GradScaler machinery.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy_with_integer_labels(
    logits: jax.Array,
    labels: jax.Array,
    *,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Per-example stable softmax CE from integer labels. Returns shape [B]."""
    logits = logits.astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, labels[..., None], axis=-1)[..., 0]
    if label_smoothing:
        smooth = -log_probs.mean(axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    return nll


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    *,
    label_smoothing: float = 0.0,
    weights: jax.Array | None = None,
) -> jax.Array:
    """Mean CE over the (global) batch — under ``jit`` with a data-sharded
    batch this mean is computed collectively, so the reported loss is the
    *global* loss, fixing the reference's local-only reporting
    (``trainer/trainer.py:175-178``).

    ``weights`` (shape [B], e.g. the loader's pad ``mask``) turns the mean into
    a weighted mean so padded rows contribute nothing."""
    nll = softmax_cross_entropy_with_integer_labels(
        logits, labels, label_smoothing=label_smoothing
    )
    return weighted_mean(nll, weights)


def weighted_mean(values: jax.Array, weights: jax.Array | None = None) -> jax.Array:
    """Mean of per-example values, optionally weighted (pad-mask aware).
    An all-zero weight vector yields 0, not NaN; fractional weights divide by
    their true sum."""
    values = values.astype(jnp.float32)
    if weights is None:
        return values.mean()
    weights = weights.astype(jnp.float32)
    total = weights.sum()
    return jnp.where(total > 0, (values * weights).sum() / jnp.maximum(total, 1e-8), 0.0)


def tied_cross_entropy(
    hidden: jax.Array,
    embedding: jax.Array,
    targets: jax.Array,
    *,
    chunk_size: int = 8192,
) -> jax.Array:
    """Per-token NLL for a tied-embedding LM head WITHOUT materializing the
    full logits tensor.

    ``hidden``: ``[..., d]`` final hidden states; ``embedding``: ``[V, d]``
    (the tied token embedding); ``targets``: integer ids of exactly
    ``hidden``'s leading shape. Returns per-token NLL of that leading shape.
    Chunk logits are computed float32 (both operands upcast), matching the
    model's own ``x.astype(f32) @ E.T.astype(f32)`` head bit-for-bit in
    convention — FUSED_CE on/off runs stay numerically comparable.

    The naive path computes ``hidden @ embedding.T`` — ``[B, T, V]`` float32,
    13 GB for GPT-2-small at batch 64 / T 1024 (an observed single-chip OOM).
    Here the vocabulary is scanned in ``chunk_size`` slices with an online
    logsumexp, so peak memory is O(N * chunk_size); each chunk is wrapped in
    ``jax.checkpoint`` so the backward pass recomputes its logits instead of
    storing them.
    """
    lead_shape = hidden.shape[:-1]
    d = hidden.shape[-1]
    v = embedding.shape[0]
    if targets.shape != lead_shape:
        raise ValueError(f"targets {targets.shape} must match hidden leading {lead_shape}")
    x = hidden.reshape(-1, d).astype(jnp.float32)
    tgt = targets.reshape(-1)
    n = x.shape[0]
    # Never chunk wider than the (lane-aligned) vocab: a small vocab under the
    # default chunk_size would otherwise pad 256 -> 8192 rows and compute 32x
    # the naive head's work.
    chunk_size = min(chunk_size, -(-v // 128) * 128)
    n_chunks = -(-v // chunk_size)
    v_pad = n_chunks * chunk_size
    emb = jnp.pad(embedding, ((0, v_pad - v), (0, 0))).reshape(n_chunks, chunk_size, d)

    @jax.checkpoint
    def chunk(carry, args):
        m, l, tgt_logit = carry
        emb_c, base = args
        # [N, C] logits for this vocab slice — f32 operands, matching the
        # model head's convention (see docstring).
        logits = jnp.einsum(
            "nd,cd->nc", x, emb_c.astype(jnp.float32), preferred_element_type=jnp.float32
        )
        # padded vocab rows must not win the max or contribute to the sum
        col = base + jnp.arange(chunk_size)
        logits = jnp.where(col[None, :] < v, logits, -1e30)
        m_new = jnp.maximum(m, logits.max(axis=1))
        l = l * jnp.exp(m - m_new) + jnp.exp(logits - m_new[:, None]).sum(axis=1)
        in_chunk = (tgt >= base) & (tgt < base + chunk_size)
        local = jnp.clip(tgt - base, 0, chunk_size - 1)
        picked = jnp.take_along_axis(logits, local[:, None], axis=1)[:, 0]
        tgt_logit = jnp.where(in_chunk, picked, tgt_logit)
        return (m_new, l, tgt_logit), None

    init = (
        jnp.full((n,), -1e30, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    bases = jnp.arange(n_chunks) * chunk_size
    (m, l, tgt_logit), _ = jax.lax.scan(chunk, init, (emb, bases))
    nll = m + jnp.log(jnp.maximum(l, 1e-30)) - tgt_logit
    return nll.reshape(lead_shape)
