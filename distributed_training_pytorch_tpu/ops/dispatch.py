"""Kernel dispatch policy — the single decision point for the Pallas hot paths.

Every model that *could* run a fused Pallas kernel (flash attention for
ViT/TransformerLM, the fused ``conv1x1_bn_act`` GEMM+epilogue for
ResNet/ConvNeXt) resolves which path it actually takes through this module,
so the policy lives in exactly one place and every resolution is observable.

The knob convention (the ``telemetry=None`` pillar applied to kernels):

* Each model takes a ``pallas: Optional[bool] = None`` constructor knob.
  ``True`` forces the fused kernels, ``False`` forces the plain XLA paths,
  and ``None`` (the default) means *auto* — the per-model policy below,
  which is exactly the historical behavior, so an unset knob is
  bit-identical with the pre-dispatch program (test-enforced in
  tests/test_dispatch.py).
* The library never reads environment variables.  Example entries read the
  ``PALLAS`` env via :func:`pallas_from_env` (the DTYPE/CHAIN_STEPS/MESH
  convention) and pass the result down as the constructor knob.

Per-model auto policies (who gets a kernel when the knob is ``None``):

=============  =======================  =========================================
model          op                       auto resolution
=============  =======================  =========================================
vit            attention                historical ``use_flash`` tri-state
                                        (default off; ViTB16 passes auto →
                                        flash on TPU when ``T >= 512``)
transformer_lm attention                historical ``attention_impl`` string
                                        (default "auto" → flash on TPU)
resnet         conv1x1_bn_act           **off** — measured slower end-to-end
                                        (fusion-barrier cost, BASELINE.md r5);
                                        also changes the param tree, so it is
                                        opt-in for fresh inits only
convnext       dense_gelu epilogue      **off** — opt in via ``pallas=True`` /
                                        ``PALLAS=1`` (autotuner evidence,
                                        docs/performance.md "Autotuning")
vgg16          (none)                   no fused-kernel coverage (3x3 convs);
                                        every resolution lands on plain
=============  =======================  =========================================

Observability (the silent-fall-through fix): each resolution is recorded as
a one-time ``kernel_dispatch`` decision — ``(model, op, path, reason)``
deduplicated per process — and forwarded to an installed event sink
(normally ``EventLog.emit``, installed by the Trainer for the duration of a
run).  Decisions recorded before a sink exists are buffered and flushed on
install, so the resolutions made while building the model still land in the
run's event log.  Recording happens in host Python at trace/build time and
never touches the compiled program: ``PALLAS=0`` / ``pallas=False`` (and the
unset default) reproduce the historical executable bit-exactly.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "pallas_from_env",
    "resolve",
    "attention_fn",
    "lm_attention_impl",
    "conv1x1_policy",
    "record",
    "records",
    "set_event_sink",
    "clear_event_sink",
    "reset",
]

_EVENT = "kernel_dispatch"

_lock = threading.Lock()
_seen: Dict[Tuple[str, str, str, str], Dict[str, Any]] = {}
_pending: List[Dict[str, Any]] = []
_sink: Optional[Callable[..., Any]] = None


def pallas_from_env(env: Optional[dict] = None, *, default: Optional[bool] = None):
    """Parse the ``PALLAS`` env knob: ``"1"`` → True, ``"0"`` → False,
    unset/empty → ``default`` (normally ``None`` = per-model auto).

    Entry-level only — library code takes the returned value as an explicit
    constructor knob and never reads the environment itself.
    """
    if env is None:
        import os

        env = os.environ
    raw = env.get("PALLAS", "")
    if raw == "":
        return default
    if raw not in ("0", "1"):
        raise ValueError(f"PALLAS must be '0' or '1' (got {raw!r})")
    return raw == "1"


def resolve(knob: Optional[bool], fallback):
    """Three-state resolution: an explicit ``pallas=`` knob wins; ``None``
    defers to the model's historical/legacy control (``fallback``)."""
    return fallback if knob is None else knob


# ---------------------------------------------------------------------------
# decision recording
# ---------------------------------------------------------------------------


def record(model: str, op: str, path: str, *, reason: str = "", **detail) -> bool:
    """Record one dispatch decision; dedup on ``(model, op, path, reason)``.

    Returns True when this was the first time the decision was seen (and so
    was emitted/buffered), False for a dedup hit.  Host-side only — safe to
    call from inside a traced ``__call__`` (it runs at trace time).
    """
    key = (model, op, path, reason)
    fields = {"model": model, "op": op, "path": path, "reason": reason}
    fields.update(detail)
    with _lock:
        if key in _seen:
            return False
        _seen[key] = fields
        sink = _sink
        if sink is None:
            _pending.append(fields)
            return True
    # Emit outside the lock: the sink (EventLog.emit) takes its own lock.
    sink(_EVENT, **fields)
    return True


def records() -> List[Dict[str, Any]]:
    """Snapshot of every decision recorded so far (tests / doctor)."""
    with _lock:
        return [dict(f) for f in _seen.values()]


def set_event_sink(emit: Callable[..., Any]) -> None:
    """Install ``emit(event, **fields)`` (normally ``EventLog.emit``) and
    flush any decisions buffered before a sink existed."""
    global _sink
    with _lock:
        _sink = emit
        pending, _pending[:] = list(_pending), []
    for fields in pending:
        emit(_EVENT, **fields)


def clear_event_sink() -> None:
    """Uninstall the sink (Trainer teardown).  Dedup state is kept — the
    one-time contract is per process, not per run."""
    global _sink
    with _lock:
        _sink = None


def reset() -> None:
    """Testing hook: forget all decisions, buffers, and the sink."""
    global _sink
    with _lock:
        _seen.clear()
        _pending[:] = []
        _sink = None


# ---------------------------------------------------------------------------
# attention (vit / transformer_lm)
# ---------------------------------------------------------------------------


def attention_fn(
    model: str,
    use_flash: Optional[bool],
    *,
    causal: bool = False,
    **kwargs,
):
    """Resolve the attention path for ``model`` and return an attention
    callable, or ``None`` meaning *use the caller's historical plain path*.

    ``use_flash`` is the already-resolved tri-state (the model's ``pallas``
    knob overriding its legacy ``use_flash``/``attention_impl`` control):
    ``False`` → plain, ``True`` → flash for every length, ``None`` → auto
    (flash on TPU for ``T >= FLASH_MIN_SEQ_LEN``, plain elsewhere).

    The returned callable records which path each *actual* sequence length
    resolved to — including the silent below-``FLASH_MIN_SEQ_LEN``
    fall-through that previously dropped to plain with no signal.
    ``kwargs`` (block_q/block_k/interpret/…) pass through to
    :func:`~distributed_training_pytorch_tpu.ops.pallas.make_attention_fn`.
    """
    if use_flash is False:
        record(model, "attention", "plain", reason="pallas=False")
        return None
    import jax

    if use_flash is None and jax.default_backend() != "tpu":
        record(
            model,
            "attention",
            "plain",
            reason=f"auto: backend={jax.default_backend()} (flash is TPU-default only)",
        )
        return None

    from .pallas import FLASH_MIN_SEQ_LEN, make_attention_fn

    min_seq_len = 1 if use_flash is True else FLASH_MIN_SEQ_LEN
    inner = make_attention_fn(causal=causal, min_seq_len=min_seq_len, **kwargs)

    def dispatching_attention(q, k, v, valid_len=None):
        seq = q.shape[1]
        if seq < min_seq_len:
            # The formerly-silent fall-through: make_attention_fn drops to
            # the plain path below min_seq_len.  Same routing — now named.
            record(
                model,
                "attention",
                "plain",
                reason=f"T={seq} < FLASH_MIN_SEQ_LEN={min_seq_len}",
                seq_len=seq,
            )
        else:
            record(
                model,
                "attention",
                "flash",
                reason="pallas=True (forced)" if use_flash is True else f"auto: T={seq} >= {min_seq_len}",
                seq_len=seq,
            )
        if valid_len is None:
            return inner(q, k, v)
        return inner(q, k, v, valid_len=valid_len)

    return dispatching_attention


def lm_attention_impl(attention_impl: str, pallas: Optional[bool]) -> str:
    """Map TransformerLM's ``pallas`` knob onto its legacy ``attention_impl``
    string: True → "flash", False → "plain", None → keep the legacy value
    (the historical program)."""
    if pallas is True:
        return "flash"
    if pallas is False:
        return "plain"
    return attention_impl


# ---------------------------------------------------------------------------
# fused conv1x1 / dense epilogues (resnet / convnext)
# ---------------------------------------------------------------------------


def conv1x1_policy(
    model: str,
    pallas: Optional[bool],
    *,
    legacy: bool = False,
    op: str = "conv1x1_bn_act",
    auto_off_reason: str = "auto: measured slower end-to-end (BASELINE.md r5) — opt in with pallas=True",
) -> bool:
    """Resolve + record the fused-GEMM-epilogue policy for ``model``.

    Auto (``pallas=None`` and ``legacy`` False) stays **off**: the fused
    1x1-conv path measured slower end-to-end than XLA's own fusions
    (BASELINE.md r5), so promotion is evidence-gated — the autotuner or an
    explicit ``pallas=True`` flips it, never a silent default.
    """
    on = resolve(pallas, legacy)
    if on:
        reason = "pallas=True" if pallas is True else "legacy knob"
        record(model, op, "pallas", reason=reason)
    else:
        reason = "pallas=False" if pallas is False else auto_off_reason
        record(model, op, "plain", reason=reason)
    return bool(on)
