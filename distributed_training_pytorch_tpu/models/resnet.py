"""ResNet in Flax — BASELINE.json config 3 (ResNet-50 / ImageNet-1k, DP).

The reference's only model is VGG16 (``model/vgg16.py``); ResNet extends the
zoo per the driver's scale-out configs (SURVEY.md §7 step 8). TPU-first
choices: NHWC layout, bfloat16 activation knob with float32 params and
float32 BatchNorm statistics, and *global* batch statistics for free — under
``jit`` with a data-sharded batch, BN's mean/var reductions span the global
batch (XLA inserts the cross-device collective), which DDP only approximates
with SyncBatchNorm.

BatchNorm running stats live in the ``batch_stats`` collection and flow
through ``TrainState.model_state`` (the engine threads mutable collections —
``train/engine.py`` ``make_supervised_loss``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class PallasConv1x1(nn.Module):
    """1x1 conv as a Pallas GEMM (``ops.pallas.conv1x1_bn_act_diff`` with an
    identity epilogue) — the r5 probe measured XLA's conv kernel at ~45% of
    the HBM bandwidth floor on ResNet stage-1's 56x56x(64<->256) shapes while
    the hand-tiled GEMM reaches ~72% (BASELINE.md "ResNet-50" r5 row); this
    module swaps the bandwidth-bound 1x1s onto that kernel. Kernel param
    keeps nn.Conv's ``[1, 1, Cin, Cout]`` layout; stride subsamples rows
    before the GEMM (a strided 1x1 conv reads only those pixels)."""

    features: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from distributed_training_pytorch_tpu.ops.pallas import conv1x1_bn_act_diff

        cin = x.shape[-1]
        kernel = self.param(
            "kernel", conv_kernel_init, (1, 1, cin, self.features), jnp.float32
        )
        if self.strides > 1:
            x = x[:, :: self.strides, :: self.strides, :]
        return conv1x1_bn_act_diff(
            x.astype(self.dtype),
            kernel.reshape(cin, self.features).astype(self.dtype),
            jnp.ones((self.features,), jnp.float32),
            jnp.zeros((self.features,), jnp.float32),
            relu=False,
            affine_grads=False,  # identity epilogue: constants, not params
        )


class BottleneckBlock(nn.Module):
    """1x1 reduce -> 3x3 -> 1x1 expand (x4), residual add, post-add ReLU."""

    features: int
    strides: int = 1
    dtype: Any = jnp.float32
    pallas_1x1: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, kernel_init=conv_kernel_init
        )

        def conv1x1(features, strides=1):
            def apply(inp):
                # Kernel only where the GEMM is bandwidth-bound (stage-1's
                # 56x56 maps, ~28 FLOP/byte); the deeper stages' 1x1s are
                # compute-bound and XLA's conv + fusion wins there.
                if self.pallas_1x1 and inp.shape[1] >= 56:
                    return PallasConv1x1(
                        features, strides=strides, dtype=self.dtype
                    )(inp)
                return conv(features, (1, 1), strides=(strides, strides))(inp)

            return apply
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        residual = x
        y = conv1x1(self.features)(x)
        y = nn.relu(norm()(y))
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv1x1(self.features * 4)(y)
        # Zero-init the last BN scale: identity residual at init (He et al.).
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv1x1(self.features * 4, strides=self.strides)(residual)
            residual = norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Bottleneck ResNet; ``stage_sizes=(3, 4, 6, 3)`` is ResNet-50."""

    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    width: int = 64
    dtype: Any = jnp.float32
    # Route the bandwidth-bound stage-1 1x1 convs (input spatial >= 56, see
    # BottleneckBlock.conv1x1's gate) through the Pallas GEMM (PallasConv1x1).
    # Changes the param tree (module names), so flip only on fresh inits.
    # Measured slower end-to-end (fusion-barrier cost, BASELINE.md r5) — a
    # measurement knob, not a perf default.
    pallas_1x1: bool = False
    # The unified kernel-policy knob (ops/dispatch.py): overrides pallas_1x1
    # when not None. Auto (None) resolves to OFF — the fused 1x1 path is
    # measured slower end-to-end, so promotion stays evidence-gated.
    pallas: Optional[bool] = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        from distributed_training_pytorch_tpu.ops import dispatch

        pallas_1x1 = dispatch.conv1x1_policy(
            "resnet", self.pallas, legacy=self.pallas_1x1
        )
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.width,
            (7, 7),
            strides=(2, 2),
            padding=[(3, 3), (3, 3)],
            use_bias=False,
            dtype=self.dtype,
            kernel_init=conv_kernel_init,
        )(x)
        x = nn.relu(
            nn.BatchNorm(
                use_running_average=not train,
                momentum=0.9,
                epsilon=1e-5,
                dtype=self.dtype,
                param_dtype=jnp.float32,
            )(x)
        )
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for stage, num_blocks in enumerate(self.stage_sizes):
            for block in range(num_blocks):
                x = BottleneckBlock(
                    self.width * (2**stage),
                    strides=2 if stage > 0 and block == 0 else 1,
                    dtype=self.dtype,
                    pallas_1x1=pallas_1x1,
                )(x, train=train)
        x = x.mean(axis=(1, 2))  # global average pool
        x = nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            kernel_init=nn.initializers.normal(0.01),
        )(x)
        return x.astype(jnp.float32)


def ResNet50(
    num_classes: int = 1000,
    dtype: Any = jnp.float32,
    pallas_1x1: bool = False,
    pallas: Optional[bool] = None,
) -> ResNet:
    return ResNet(
        num_classes=num_classes, stage_sizes=(3, 4, 6, 3), dtype=dtype,
        pallas_1x1=pallas_1x1, pallas=pallas,
    )


def ResNet18Slim(num_classes: int = 10, dtype: Any = jnp.float32, **kw) -> ResNet:
    """Small bottleneck variant for tests/smoke runs (not torch ResNet-18)."""
    return ResNet(
        num_classes=num_classes, stage_sizes=(1, 1, 1, 1), width=16, dtype=dtype, **kw
    )
