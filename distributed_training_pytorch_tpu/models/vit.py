"""Vision Transformer in Flax — BASELINE.json config 4 (ViT-B/16 / ImageNet).

Not in the reference (its only model is VGG16); built per the driver's
scale-out configs. TPU-first choices: bfloat16 activation knob, attention as
batched MXU matmuls, and an optional fused-attention path (``ops.pallas``)
the module picks when the kernel supports the shapes; sequence dimension kept
shardable for the ``seq`` mesh axis (ring attention lives in ``parallel``;
ViT's 197-token sequences don't need it — SURVEY.md §5).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


class MlpBlock(nn.Module):
    mlp_dim: int
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        out_dim = x.shape[-1]
        x = nn.Dense(self.mlp_dim, dtype=self.dtype)(x)
        x = nn.gelu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(out_dim, dtype=self.dtype)(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x


def dot_product_attention(q, k, v, *, dtype=jnp.float32, valid_len=None):
    """Plain softmax attention: [B, T, H, D] inputs, MXU-batched matmuls,
    float32 softmax accumulation. ``valid_len`` masks key positions >= it
    (the tail of a tile-padded sequence, see ``ViT.pad_seq_to``)."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if valid_len is not None and valid_len < k.shape[1]:
        mask = jnp.arange(k.shape[1]) < valid_len  # [Tk]
        logits = jnp.where(mask[None, None, None, :], logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def default_attention_fn(
    use_flash: Optional[bool] = None, *, model: str = "vit"
) -> Optional[Callable]:
    """Resolve the attention path: the Pallas flash kernel (``ops.pallas``)
    when ``use_flash`` is True (forced, any sequence length), or None (plain
    XLA softmax attention) when False. ``None`` auto-selects: on TPU backends,
    the shape-aware adapter that uses the kernel where it beats XLA
    (T >= ``ops.pallas.FLASH_MIN_SEQ_LEN``) and the plain path below that.

    The resolution goes through the ``ops/dispatch.py`` policy layer, which
    records it as a one-time ``kernel_dispatch`` decision — including the
    formerly-silent below-``FLASH_MIN_SEQ_LEN`` fall-through to plain.

    Call only at trace/apply time (it touches ``jax.default_backend()``, which
    initializes backends — too early at model-construction time for
    ``jax.distributed`` setups).
    """
    from distributed_training_pytorch_tpu.ops import dispatch

    return dispatch.attention_fn(model, use_flash)


class MultiHeadAttention(nn.Module):
    num_heads: int
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    # Optional fused kernel: (q, k, v) -> out, same [B, T, H, D] layout.
    attention_fn: Optional[Callable] = None
    # Real sequence length when the stream is tile-padded (ViT.pad_seq_to);
    # None = every position is a valid key.
    valid_len: Optional[int] = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        dim = x.shape[-1]
        assert dim % self.num_heads == 0
        head_dim = dim // self.num_heads
        qkv = nn.DenseGeneral(
            (3, self.num_heads, head_dim), axis=-1, dtype=self.dtype, name="qkv"
        )(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        if self.attention_fn is not None:
            # Only pass valid_len when set — custom attention fns (ring,
            # Ulysses) keep their plain (q, k, v) signature.
            out = (
                self.attention_fn(q, k, v)
                if self.valid_len is None
                else self.attention_fn(q, k, v, valid_len=self.valid_len)
            )
        else:
            out = dot_product_attention(
                q, k, v, dtype=self.dtype, valid_len=self.valid_len
            )
        out = nn.DenseGeneral(dim, axis=(-2, -1), dtype=self.dtype, name="out")(out)
        return nn.Dropout(self.dropout_rate, deterministic=not train)(out)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None
    valid_len: Optional[int] = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MultiHeadAttention(
            self.num_heads,
            self.dropout_rate,
            dtype=self.dtype,
            attention_fn=self.attention_fn,
            valid_len=self.valid_len,
        )(y, train=train)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MlpBlock(self.mlp_dim, self.dropout_rate, dtype=self.dtype)(y, train=train)
        return x + y


class ViT(nn.Module):
    """ViT with learned position embeddings and a class token."""

    num_classes: int = 1000
    patch_size: int = 16
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None
    # tri-state flash knob, resolved lazily at apply time when attention_fn is
    # not given: True = force the Pallas kernel, False = plain XLA, None =
    # auto (kernel on TPU for long sequences). Lazy so that merely
    # constructing a model never initializes JAX backends (which would break
    # a later jax.distributed.initialize()).
    use_flash: Optional[bool] = False
    # The unified kernel-policy knob (ops/dispatch.py): overrides use_flash
    # when not None (True = force the Pallas kernels, False = plain XLA).
    # None (default) defers to use_flash — the historical program, bit-exact.
    pallas: Optional[bool] = None
    # Pad the token stream (cls + patches) up to this length with zero rows
    # right after position embedding — ViT-B's T=197 maps poorly onto the
    # 128-lane MXU/VMEM tiling, and padding to 256 makes every GEMM,
    # transpose, and score tile in the encoder alignment-friendly. Exact
    # semantics: pad positions are masked out as attention keys (valid_len),
    # the head reads token 0, and pad rows influence nothing else (per-token
    # LN/MLP), so their activations AND gradients are inert. None = off.
    pad_seq_to: Optional[int] = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        B, H, W, _ = x.shape
        p = self.patch_size
        if H % p or W % p:
            raise ValueError(f"input {H}x{W} not divisible by patch size {p}")
        x = x.astype(self.dtype)
        # Patch embedding as a strided conv (one MXU matmul per patch grid).
        x = nn.Conv(
            self.hidden_dim,
            (p, p),
            strides=(p, p),
            padding="VALID",
            dtype=self.dtype,
            name="patch_embed",
        )(x)
        x = x.reshape(B, -1, self.hidden_dim)  # [B, T, D]
        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, self.hidden_dim), jnp.float32
        )
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, self.hidden_dim)).astype(x.dtype), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], self.hidden_dim),
            jnp.float32,
        )
        x = x + pos.astype(x.dtype)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        valid_len = None
        if self.pad_seq_to is not None and x.shape[1] < self.pad_seq_to:
            if self.attention_fn is not None:
                # Fail at the pad site, not deep inside block 1: custom
                # attention fns (ring/Ulysses adapters) take plain (q, k, v)
                # and would reject the valid_len kwarg the pad requires.
                raise ValueError(
                    "pad_seq_to requires the built-in attention paths "
                    "(attention_fn=None / use_flash) — a custom attention_fn "
                    "does not take the valid_len pad mask"
                )
            valid_len = x.shape[1]
            x = jnp.pad(x, ((0, 0), (0, self.pad_seq_to - valid_len), (0, 0)))
        attention_fn = self.attention_fn
        if attention_fn is None:
            use_flash = self.use_flash if self.pallas is None else self.pallas
            if use_flash is not False:
                attention_fn = default_attention_fn(use_flash)
            else:
                from distributed_training_pytorch_tpu.ops import dispatch

                dispatch.record(
                    "vit", "attention", "plain", reason="pallas/use_flash=False"
                )
        for _ in range(self.depth):
            x = EncoderBlock(
                self.num_heads,
                self.mlp_dim,
                self.dropout_rate,
                dtype=self.dtype,
                attention_fn=attention_fn,
                valid_len=valid_len,
            )(x, train=train)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = x[:, 0]  # class token
        x = nn.Dense(self.num_classes, kernel_init=nn.initializers.zeros)(x.astype(jnp.float32))
        return x


def ViTB16(
    num_classes: int = 1000,
    dtype: Any = jnp.float32,
    use_flash: Optional[bool] = None,
    **kw,
) -> ViT:
    """BASELINE config 4. ``use_flash=None`` (auto) routes attention through
    the shape-aware Pallas adapter on TPU — at this model's T=197 that resolves
    to the plain XLA path (measured faster below ``FLASH_MIN_SEQ_LEN``);
    ``use_flash=True`` forces the fused kernel regardless of shape. The
    unified ``pallas=`` knob (via ``**kw``) overrides the tri-state when set
    — see ops/dispatch.py."""
    return ViT(
        use_flash=use_flash,
        num_classes=num_classes,
        patch_size=16,
        hidden_dim=768,
        depth=12,
        num_heads=12,
        mlp_dim=3072,
        dtype=dtype,
        **kw,
    )


def ViTTiny(num_classes: int = 10, dtype: Any = jnp.float32, **kw) -> ViT:
    """Small variant for tests."""
    return ViT(
        num_classes=num_classes,
        patch_size=4,
        hidden_dim=32,
        depth=2,
        num_heads=4,
        mlp_dim=64,
        dtype=dtype,
        **kw,
    )
