from distributed_training_pytorch_tpu.models.vgg import VGG16, ConvBlock  # noqa: F401


def create_model(name: str, num_classes: int, **kwargs):
    """Model-zoo factory. Names match BASELINE.json configs."""
    name = name.lower()
    if name in ("vgg16", "vgg"):
        return VGG16(num_classes=num_classes, **kwargs)
    if name in ("resnet50", "resnet"):
        raise NotImplementedError("resnet50 is not implemented yet")
    if name in ("vit", "vit-b/16", "vit_b16", "vitb16"):
        raise NotImplementedError("vit-b/16 is not implemented yet")
    if name in ("convnext-l", "convnext_l", "convnextl", "convnext"):
        raise NotImplementedError("convnext-l is not implemented yet")
    raise ValueError(f"unknown model {name!r}")
