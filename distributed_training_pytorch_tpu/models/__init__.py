from distributed_training_pytorch_tpu.models.vgg import VGG16, ConvBlock  # noqa: F401
from distributed_training_pytorch_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18Slim,
    ResNet50,
)
from distributed_training_pytorch_tpu.models.vit import ViT, ViTB16, ViTTiny  # noqa: F401
from distributed_training_pytorch_tpu.models.convnext import (  # noqa: F401
    ConvNeXt,
    ConvNeXtL,
    ConvNeXtTiny,
)
from distributed_training_pytorch_tpu.models.wrappers import InputNormalizer  # noqa: F401
from distributed_training_pytorch_tpu.models.transformer_lm import (  # noqa: F401
    DecoderBlock,
    GPTSmall,
    LMTiny,
    TransformerLM,
)


def create_model(name: str, num_classes: int, **kwargs):
    """Model-zoo factory. Names match BASELINE.json configs."""
    name = name.lower()
    if name in ("vgg16", "vgg"):
        return VGG16(num_classes=num_classes, **kwargs)
    if name in ("resnet50", "resnet"):
        return ResNet50(num_classes=num_classes, **kwargs)
    if name in ("vit", "vit-b/16", "vit_b16", "vitb16"):
        return ViTB16(num_classes=num_classes, **kwargs)
    if name in ("convnext-l", "convnext_l", "convnextl", "convnext"):
        return ConvNeXtL(num_classes=num_classes, **kwargs)
    if name in ("convnext-tiny", "convnext_tiny"):
        return ConvNeXtTiny(num_classes=num_classes, **kwargs)
    if name in ("resnet18_slim", "resnet18-slim"):
        return ResNet18Slim(num_classes=num_classes, **kwargs)
    if name in ("vit_tiny", "vit-tiny"):
        return ViTTiny(num_classes=num_classes, **kwargs)
    raise ValueError(f"unknown model {name!r}")
