from distributed_training_pytorch_tpu.models.vgg import VGG16, ConvBlock  # noqa: F401
from distributed_training_pytorch_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18Slim,
    ResNet50,
)
from distributed_training_pytorch_tpu.models.vit import ViT, ViTB16, ViTTiny  # noqa: F401
from distributed_training_pytorch_tpu.models.convnext import (  # noqa: F401
    ConvNeXt,
    ConvNeXtL,
    ConvNeXtTiny,
)
from distributed_training_pytorch_tpu.models.wrappers import InputNormalizer  # noqa: F401
from distributed_training_pytorch_tpu.models.transformer_lm import (  # noqa: F401
    DecoderBlock,
    GPTSmall,
    LMTiny,
    TransformerLM,
)


def create_model(name: str, num_classes: int, **kwargs):
    """Model-zoo factory. Names match BASELINE.json configs.

    Every model accepts the unified ``pallas=`` kernel-policy knob
    (ops/dispatch.py). VGG16 has no fused-kernel coverage (3x3 convs), so the
    knob is consumed here and the plain resolution recorded — entries can
    pass ``pallas=`` uniformly without special-casing the model."""
    name = name.lower()
    if name in ("vgg16", "vgg"):
        pallas = kwargs.pop("pallas", None)
        if pallas is not None:
            from distributed_training_pytorch_tpu.ops import dispatch

            dispatch.record(
                "vgg16",
                "conv",
                "plain",
                reason="no fused-kernel coverage (3x3 convs) — pallas knob is a no-op",
            )
        return VGG16(num_classes=num_classes, **kwargs)
    if name in ("resnet50", "resnet"):
        return ResNet50(num_classes=num_classes, **kwargs)
    if name in ("vit", "vit-b/16", "vit_b16", "vitb16"):
        return ViTB16(num_classes=num_classes, **kwargs)
    if name in ("convnext-l", "convnext_l", "convnextl", "convnext"):
        return ConvNeXtL(num_classes=num_classes, **kwargs)
    if name in ("convnext-tiny", "convnext_tiny"):
        return ConvNeXtTiny(num_classes=num_classes, **kwargs)
    if name in ("resnet18_slim", "resnet18-slim"):
        return ResNet18Slim(num_classes=num_classes, **kwargs)
    if name in ("vit_tiny", "vit-tiny"):
        return ViTTiny(num_classes=num_classes, **kwargs)
    raise ValueError(f"unknown model {name!r}")
