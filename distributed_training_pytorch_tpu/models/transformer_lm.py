"""Decoder-only transformer LM — the long-context showcase model family.

Beyond the reference's scope (its only model is VGG16, ``model/vgg16.py``);
this family exists so the framework's long-context and distributed machinery
has a first-class consumer, wired end-to-end:

* causal attention via the Pallas flash kernel (``ops.pallas``, auto on TPU
  for long sequences), ring attention (``parallel.ring_attention``) when the
  sequence is sharded over a ``seq`` mesh axis, or plain XLA attention;
* homogeneous pre-LN blocks — exactly the stacked-stage shape
  ``parallel.pipeline.pipeline_apply`` consumes for pipeline parallelism;
* optional Mixture-of-Experts FFNs (``parallel.moe.MoEMlp``) every
  ``moe_every``-th block for expert parallelism;
* bf16 activation knob with float32 params/logits, like the vision zoo.

Attention selection (``attention_impl``): ``"auto"`` = shape-aware flash on
TPU / plain elsewhere; ``"flash"`` = force the kernel; ``"plain"`` = XLA
softmax attention; ``"ring"`` = exact ring attention over the ``seq`` axis of
the ambient mesh (pass ``mesh=``).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from distributed_training_pytorch_tpu.parallel.moe import MoEMlp


def _causal_attention_fn(attention_impl: str, mesh):
    """Resolve ``attention_impl`` to a (q, k, v) -> out callable at apply time
    (lazily, so constructing a model never initializes jax backends). Flash vs
    plain goes through the ``ops/dispatch.py`` policy layer, which records the
    resolution — including the silent below-``FLASH_MIN_SEQ_LEN``
    fall-through — as a one-time ``kernel_dispatch`` decision."""
    from distributed_training_pytorch_tpu.ops import dispatch

    if attention_impl == "ring":
        if mesh is None:
            raise ValueError('attention_impl="ring" needs mesh=')
        from distributed_training_pytorch_tpu.parallel.ring_attention import ring_attention

        dispatch.record("transformer_lm", "attention", "ring", reason="attention_impl=ring")
        return lambda q, k, v: ring_attention(q, k, v, mesh, causal=True)
    if attention_impl in ("auto", "flash", "plain"):
        use_flash = {"auto": None, "flash": True, "plain": False}[attention_impl]
        fn = dispatch.attention_fn("transformer_lm", use_flash, causal=True)
        if fn is not None:
            return fn
        from distributed_training_pytorch_tpu.ops.pallas import _causal_plain

        return _causal_plain
    raise ValueError(f"unknown attention_impl {attention_impl!r}")


class DecoderBlock(nn.Module):
    """Pre-LN decoder block: x + attn(ln(x)); x + ffn(ln(x)).

    ``decode=True`` runs single-token autoregressive mode: ``x`` is
    ``[B, 1, d]``, and the block keeps a KV cache (``'cache'`` collection,
    ``[B, max_len, H, Dh]`` per projection) updated in place with one
    ``dynamic_update_slice`` per step — the standard TPU decode layout (static
    shapes; the growing sequence is a write index, not a growing tensor).
    ``max_len`` bounds the cache and is required for decode.
    """

    num_heads: int
    mlp_dim: int
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    attention_impl: str = "auto"
    mesh: Any = None
    use_moe: bool = False
    num_experts: int = 8
    moe_num_groups: int = 1
    moe_capacity_factor: float = 1.25
    moe_dispatch_impl: str = "einsum"
    max_len: int = 2048

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        train: bool = False,
        decode: bool = False,
        decode_index: jax.Array | None = None,
    ) -> jax.Array:
        dim = x.shape[-1]
        if dim % self.num_heads:
            raise ValueError(f"hidden dim {dim} not divisible by {self.num_heads} heads")
        head_dim = dim // self.num_heads

        y = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.DenseGeneral(
            (3, self.num_heads, head_dim), axis=-1, dtype=self.dtype, name="qkv"
        )(y)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        if decode:
            if x.shape[1] != 1:
                raise ValueError(f"decode mode consumes one token at a time, got T={x.shape[1]}")
            if decode_index is None:
                raise ValueError("decode=True requires decode_index (the model's step counter)")
            b = x.shape[0]
            cached_k = self.variable(
                "cache",
                "cached_key",
                lambda: jnp.zeros((b, self.max_len, self.num_heads, head_dim), self.dtype),
            )
            cached_v = self.variable(
                "cache",
                "cached_value",
                lambda: jnp.zeros((b, self.max_len, self.num_heads, head_dim), self.dtype),
            )
            # One step counter lives on the model (the 'position' cache var);
            # per-block copies would be redundant state with a desync hazard.
            i = decode_index
            cached_k.value = jax.lax.dynamic_update_slice_in_dim(cached_k.value, k, i, 1)
            cached_v.value = jax.lax.dynamic_update_slice_in_dim(cached_v.value, v, i, 1)
            # q [B,1,H,Dh] against the cache prefix: mask positions > i.
            scale = head_dim**-0.5
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, cached_k.value).astype(jnp.float32)
            valid = jnp.arange(self.max_len) <= i
            logits = jnp.where(valid[None, None, None, :], logits * scale, -1e30)
            weights = jax.nn.softmax(logits, axis=-1).astype(self.dtype)
            y = jnp.einsum("bhqk,bkhd->bqhd", weights, cached_v.value)
        else:
            attn_fn = _causal_attention_fn(self.attention_impl, self.mesh)
            y = attn_fn(q, k, v)
        y = nn.DenseGeneral(dim, axis=(-2, -1), dtype=self.dtype, name="attn_out")(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        x = x + y

        y = nn.LayerNorm(dtype=self.dtype)(x)
        if self.use_moe:
            # Decode routes capacity-free (per-token expert gather — no
            # buffers, no drops), so KV-cache generation works for MoE LMs
            # with the same parameters the capacity-routed training saved.
            y = MoEMlp(
                num_experts=self.num_experts,
                hidden_dim=self.mlp_dim,
                num_groups=self.moe_num_groups,
                capacity_factor=self.moe_capacity_factor,
                dispatch_impl=self.moe_dispatch_impl,
                dtype=self.dtype,
                name="moe",
            )(y, decode=decode)
        else:
            y = nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_in")(y)
            y = nn.gelu(y)
            y = nn.Dense(dim, dtype=self.dtype, name="mlp_out")(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return x + y


class TransformerLM(nn.Module):
    """Token-in, next-token-logits-out causal LM.

    ``moe_every=k`` makes every k-th block (1-indexed) a MoE block; 0 = dense.
    """

    vocab_size: int
    hidden_dim: int = 512
    depth: int = 8
    num_heads: int = 8
    mlp_dim: int = 2048
    max_len: int = 2048
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    attention_impl: str = "auto"
    # The unified kernel-policy knob (ops/dispatch.py): True -> "flash",
    # False -> "plain", None -> keep attention_impl (the historical program).
    pallas: Any = None
    mesh: Any = None
    moe_every: int = 0
    num_experts: int = 8
    moe_num_groups: int = 1
    moe_capacity_factor: float = 1.25
    moe_dispatch_impl: str = "einsum"
    tie_embeddings: bool = True

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        *,
        train: bool = False,
        decode: bool = False,
        return_hidden: bool = False,
    ) -> jax.Array:
        """``return_hidden=True`` skips the vocab projection and returns the
        final-LN hidden states ``[B, T, d]`` — pair with
        ``ops.losses.tied_cross_entropy`` (and the ``embed`` param) so training
        never materializes the [B, T, V] float32 logits."""
        b, t = tokens.shape
        if t > self.max_len:
            raise ValueError(f"sequence {t} exceeds max_len {self.max_len}")
        embed = nn.Embed(
            self.vocab_size,
            self.hidden_dim,
            embedding_init=nn.initializers.normal(stddev=0.02),
            name="embed",
        )
        x = embed(tokens).astype(self.dtype)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, self.max_len, self.hidden_dim),
            jnp.float32,
        )
        decode_index = None
        if decode:
            # single-token step: ONE position counter for the whole model
            position = self.variable("cache", "position", lambda: jnp.zeros((), jnp.int32))
            decode_index = position.value
            x = x + jax.lax.dynamic_slice_in_dim(pos, decode_index, 1, 1).astype(x.dtype)
            position.value = decode_index + 1
        else:
            x = x + jax.lax.dynamic_slice_in_dim(pos, 0, t, 1).astype(x.dtype)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        from distributed_training_pytorch_tpu.ops import dispatch

        attention_impl = dispatch.lm_attention_impl(self.attention_impl, self.pallas)
        for i in range(self.depth):
            x = DecoderBlock(
                self.num_heads,
                self.mlp_dim,
                self.dropout_rate,
                dtype=self.dtype,
                attention_impl=attention_impl,
                mesh=self.mesh,
                use_moe=self.moe_every > 0 and (i + 1) % self.moe_every == 0,
                num_experts=self.num_experts,
                moe_num_groups=self.moe_num_groups,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_dispatch_impl=self.moe_dispatch_impl,
                max_len=self.max_len,
            )(x, train=train, decode=decode, decode_index=decode_index)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if return_hidden:
            if not self.tie_embeddings:
                raise ValueError("return_hidden requires tie_embeddings=True")
            return x
        if self.tie_embeddings:
            logits = x.astype(jnp.float32) @ embed.embedding.T.astype(jnp.float32)
        else:
            logits = nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")(
                x.astype(jnp.float32)
            )
        return logits


def make_fused_lm_loss(
    model: TransformerLM,
    *,
    aux_loss_coef: float = 0.01,
    z_loss_coef: float = 1e-3,
):
    """Engine LossFn for next-token training through the fused tied-embedding
    CE (``ops.losses.tied_cross_entropy``) — the [B, T, V] float32 logits
    never materialize. Batch contract: ``image`` = input tokens, ``label`` =
    next tokens, optional ``mask`` [B] pad weights. ONE implementation shared
    by the training entry and the benchmark so they measure the same
    computation.

    For MoE models (``moe_every > 0``) the routers' sown aux losses join the
    objective: Switch load-balance * ``aux_loss_coef`` + router-z *
    ``z_loss_coef`` (standard coefficients; without them routing collapses
    onto a few experts)."""
    from distributed_training_pytorch_tpu.ops.losses import (
        tied_cross_entropy,
        weighted_mean,
    )

    has_moe = model.moe_every > 0

    def loss_fn(params, model_state, batch, rng, train):
        kwargs = {"rngs": {"dropout": rng}} if train else {}
        if has_moe:
            hidden, inter = model.apply(
                {"params": params},
                batch["image"],
                train=train,
                return_hidden=True,
                mutable=["intermediates"],
                **kwargs,
            )
        else:
            hidden = model.apply(
                {"params": params}, batch["image"], train=train, return_hidden=True, **kwargs
            )
        nll = tied_cross_entropy(
            hidden, params["embed"]["embedding"], batch["label"]
        ).mean(axis=-1)  # [B]
        loss = weighted_mean(nll, batch.get("mask"))
        metrics = {"loss": loss, "nll": loss, "ppl": jnp.exp(loss)}
        if has_moe:
            # mean of each sown metric across the MoE blocks, selected by name
            def collect(name):
                vals = [
                    v
                    for path, v in jax.tree_util.tree_flatten_with_path(
                        inter["intermediates"]
                    )[0]
                    if name in jax.tree_util.keystr(path)
                ]
                return jnp.mean(jnp.stack([jnp.asarray(v) for v in vals])) if vals else 0.0

            lb = collect("load_balance_loss")
            zl = collect("router_z_loss")
            loss = loss + aux_loss_coef * lb + z_loss_coef * zl
            metrics["moe_load_balance"] = lb
            metrics["moe_router_z"] = zl
            metrics["loss"] = loss
        return loss, (metrics, model_state)

    return loss_fn


def generate(
    model: TransformerLM,
    variables,
    prompt: jax.Array,
    num_steps: int,
    rng: jax.Array,
    *,
    temperature: float = 0.0,
) -> jax.Array:
    """Autoregressive sampling with the KV-cache decode path.

    ``prompt`` is ``[B, P]`` int32; returns ``[B, P + num_steps]``. One
    ``lax.scan`` covers prefill and generation — every step is a single-token
    cached decode (static shapes throughout). The whole decode is jitted
    (model/num_steps/temperature static), so a repeat call with the same
    shapes is ONE device dispatch — unjitted, ``lax.scan`` re-traces the
    decoder body on every call, which costs seconds of host time per sample
    and dominates through a remote-dispatch link.
    ``temperature=0`` is greedy; otherwise softmax sampling at that
    temperature.
    """
    total = prompt.shape[1] + num_steps
    if total > model.max_len:
        raise ValueError(
            f"prompt {prompt.shape[1]} + steps {num_steps} exceeds max_len {model.max_len}"
        )
    return _generate_jit(model, variables, prompt, num_steps, rng, temperature)


@functools.partial(jax.jit, static_argnums=(0, 3, 5))
def _generate_jit(model, variables, prompt, num_steps, rng, temperature):
    b, p = prompt.shape
    total = p + num_steps
    params = {k: v for k, v in variables.items() if k != "cache"}

    # The cache initializes to zeros (its variable defaults), so its structure
    # from eval_shape IS its initial value.
    cache_shapes = jax.eval_shape(
        lambda: model.apply(params, prompt[:, :1], decode=True, mutable=["cache"])
    )[1]["cache"]
    cache0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)

    def step(carry, t):
        token, cache, rng = carry
        logits, updated = model.apply(
            {**params, "cache": cache}, token, decode=True, mutable=["cache"]
        )
        logits = logits[:, 0, :]  # [B, V]
        rng, sample_rng = jax.random.split(rng)
        if temperature > 0.0:
            sampled = jax.random.categorical(sample_rng, logits / temperature, axis=-1)
        else:
            sampled = jnp.argmax(logits, axis=-1)
        # While still inside the prompt, feed the ground-truth next token.
        next_idx = jnp.minimum(t + 1, p - 1)
        in_prompt = (t + 1) < p
        next_token = jnp.where(
            in_prompt, jax.lax.dynamic_index_in_dim(prompt, next_idx, 1), sampled[:, None]
        )
        return (next_token, updated["cache"], rng), next_token[:, 0]

    (_, _, _), produced = jax.lax.scan(
        step, (prompt[:, :1], cache0, rng), jnp.arange(total - 1)
    )
    # produced[t] is the token at position t+1.
    return jnp.concatenate([prompt[:, :1], produced.T], axis=1)


def GPTSmall(vocab_size: int = 50257, dtype: Any = jnp.float32, **kw) -> TransformerLM:
    """GPT-2-small-shaped config (117M dense params)."""
    kw.setdefault("max_len", 1024)
    return TransformerLM(
        vocab_size=vocab_size,
        hidden_dim=768,
        depth=12,
        num_heads=12,
        mlp_dim=3072,
        dtype=dtype,
        **kw,
    )


def LMTiny(vocab_size: int = 256, dtype: Any = jnp.float32, **kw) -> TransformerLM:
    """Small variant for tests."""
    kw.setdefault("max_len", 128)
    return TransformerLM(
        vocab_size=vocab_size,
        hidden_dim=32,
        depth=2,
        num_heads=4,
        mlp_dim=64,
        dtype=dtype,
        **kw,
    )
