"""ConvNeXt in Flax — BASELINE.json config 5 (ConvNeXt-L / ImageNet-21k,
bf16 + gradient accumulation).

Not in the reference (its only model is VGG16); built per the driver's
scale-out configs. Block = 7x7 depthwise conv -> LayerNorm -> 1x1 expand (4x)
-> GELU -> 1x1 project, with a learnable per-channel LayerScale and stochastic
depth on the residual branch (Liu et al. 2022 recipe). TPU-first choices:
NHWC, depthwise conv via ``feature_group_count`` (lowers to XLA:TPU's native
grouped conv), bf16 activation knob with float32 params/LN statistics, and
stochastic depth as a per-sample Bernoulli mask fused into the residual add.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


class DropPath(nn.Module):
    """Stochastic depth: drop the whole residual branch per sample."""

    rate: float

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        if not train or self.rate == 0.0:
            return x
        keep = 1.0 - self.rate
        rng = self.make_rng("droppath")
        mask_shape = (x.shape[0],) + (1,) * (x.ndim - 1)
        mask = jax.random.bernoulli(rng, keep, mask_shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class PallasDenseAct(nn.Module):
    """``nn.Dense(features)`` + activation computed by the fused Pallas
    GEMM+epilogue kernel (``ops.pallas.conv1x1_bn_act_diff`` — a Dense over
    the last axis IS a 1x1 conv).

    Param names, shapes, dtypes, and initializers match ``nn.Dense`` exactly
    ("kernel" ``[Cin, Cout]`` lecun_normal, "bias" ``[Cout]`` zeros), and the
    caller instantiates it under the auto-name the plain Dense would have
    received — so flipping the kernel knob changes the *program*, never the
    param tree: inits are bit-identical and checkpoints restore either way
    (test-enforced in tests/test_dispatch.py)."""

    features: int
    act: Optional[str] = None  # None | "relu" | "gelu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from distributed_training_pytorch_tpu.ops.pallas import conv1x1_bn_act_diff

        cin = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (cin, self.features), jnp.float32
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
        x = x.astype(self.dtype)
        # The Dense bias rides the kernel's affine epilogue (scale=1); the
        # ones-scale is a literal constant, so its returned cotangent drops
        # out of the param grads on its own.
        return conv1x1_bn_act_diff(
            x,
            kernel.astype(self.dtype),
            jnp.ones((self.features,), jnp.float32),
            bias,
            relu=False,
            act=self.act,
            affine_grads=True,
        )


class ConvNeXtBlock(nn.Module):
    dim: int
    drop_path: float = 0.0
    layer_scale_init: float = 1e-6
    dtype: Any = jnp.float32
    # ops/dispatch.py kernel knob: True fuses the expand Dense + GELU (the
    # roofline-named norm+activation epilogue) into one Pallas GEMM pass.
    # None/False = the historical two-op XLA path, bit-exact.
    pallas: Optional[bool] = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        y = nn.Conv(
            self.dim,
            (7, 7),
            padding=[(3, 3), (3, 3)],
            feature_group_count=self.dim,  # depthwise
            dtype=self.dtype,
        )(x)
        y = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, param_dtype=jnp.float32)(y)
        if self.pallas is True:
            # Explicit names pin the auto-names the plain branch would get,
            # keeping the param tree identical across the knob.
            y = PallasDenseAct(
                4 * self.dim, act="gelu", dtype=self.dtype, name="Dense_0"
            )(y)
            y = nn.Dense(self.dim, dtype=self.dtype, name="Dense_1")(y)
        else:
            y = nn.Dense(4 * self.dim, dtype=self.dtype)(y)
            y = nn.gelu(y)
            y = nn.Dense(self.dim, dtype=self.dtype)(y)
        gamma = self.param(
            "layer_scale",
            nn.initializers.constant(self.layer_scale_init),
            (self.dim,),
            jnp.float32,
        )
        y = y * gamma.astype(y.dtype)
        y = DropPath(self.drop_path)(y, train=train)
        return x + y


class ConvNeXt(nn.Module):
    """ConvNeXt; ``depths=(3, 3, 27, 3), dims=(192, 384, 768, 1536)`` is -L."""

    num_classes: int = 1000
    depths: Sequence[int] = (3, 3, 27, 3)
    dims: Sequence[int] = (192, 384, 768, 1536)
    drop_path_rate: float = 0.0
    dtype: Any = jnp.float32
    # ops/dispatch.py kernel knob: True = fused Pallas expand-Dense+GELU in
    # every block; False/None = the historical plain program (auto stays off
    # — promotion is evidence-gated through the autotuner, see
    # docs/performance.md "Autotuning").
    pallas: Optional[bool] = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        from distributed_training_pytorch_tpu.ops import dispatch

        use_pallas = dispatch.conv1x1_policy(
            "convnext",
            self.pallas,
            op="dense_gelu",
            auto_off_reason=(
                "auto: opt-in epilogue fusion — flip with pallas=True/PALLAS=1 "
                "(docs/performance.md)"
            ),
        )
        x = x.astype(self.dtype)
        # Stem: 4x4 stride-4 patchify conv + LN.
        x = nn.Conv(self.dims[0], (4, 4), strides=(4, 4), dtype=self.dtype)(x)
        x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, param_dtype=jnp.float32)(x)
        # Linearly increasing stochastic-depth schedule over all blocks.
        total_blocks = sum(self.depths)
        rates = np.linspace(0.0, self.drop_path_rate, total_blocks)  # static schedule
        block = 0
        for stage, (depth, dim) in enumerate(zip(self.depths, self.dims, strict=True)):
            if stage > 0:
                x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, param_dtype=jnp.float32)(x)
                x = nn.Conv(dim, (2, 2), strides=(2, 2), dtype=self.dtype)(x)
            for _ in range(depth):
                x = ConvNeXtBlock(
                    dim,
                    drop_path=float(rates[block]),
                    dtype=self.dtype,
                    pallas=True if use_pallas else None,
                )(x, train=train)
                block += 1
        x = x.mean(axis=(1, 2))
        x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = nn.Dense(self.num_classes, kernel_init=nn.initializers.normal(0.02))(
            x.astype(jnp.float32)
        )
        return x


def ConvNeXtL(num_classes: int = 21841, dtype: Any = jnp.float32, **kw) -> ConvNeXt:
    """ConvNeXt-Large; default head sized for ImageNet-21k (BASELINE config 5)."""
    return ConvNeXt(
        num_classes=num_classes,
        depths=(3, 3, 27, 3),
        dims=(192, 384, 768, 1536),
        dtype=dtype,
        **kw,
    )


def ConvNeXtTiny(num_classes: int = 10, dtype: Any = jnp.float32, **kw) -> ConvNeXt:
    """Small variant for tests (not the official ConvNeXt-T)."""
    return ConvNeXt(
        num_classes=num_classes, depths=(1, 1, 2, 1), dims=(16, 32, 64, 128), dtype=dtype, **kw
    )
