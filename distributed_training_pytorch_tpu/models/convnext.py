"""ConvNeXt in Flax — BASELINE.json config 5 (ConvNeXt-L / ImageNet-21k,
bf16 + gradient accumulation).

Not in the reference (its only model is VGG16); built per the driver's
scale-out configs. Block = 7x7 depthwise conv -> LayerNorm -> 1x1 expand (4x)
-> GELU -> 1x1 project, with a learnable per-channel LayerScale and stochastic
depth on the residual branch (Liu et al. 2022 recipe). TPU-first choices:
NHWC, depthwise conv via ``feature_group_count`` (lowers to XLA:TPU's native
grouped conv), bf16 activation knob with float32 params/LN statistics, and
stochastic depth as a per-sample Bernoulli mask fused into the residual add.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


class DropPath(nn.Module):
    """Stochastic depth: drop the whole residual branch per sample."""

    rate: float

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        if not train or self.rate == 0.0:
            return x
        keep = 1.0 - self.rate
        rng = self.make_rng("droppath")
        mask_shape = (x.shape[0],) + (1,) * (x.ndim - 1)
        mask = jax.random.bernoulli(rng, keep, mask_shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class ConvNeXtBlock(nn.Module):
    dim: int
    drop_path: float = 0.0
    layer_scale_init: float = 1e-6
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        y = nn.Conv(
            self.dim,
            (7, 7),
            padding=[(3, 3), (3, 3)],
            feature_group_count=self.dim,  # depthwise
            dtype=self.dtype,
        )(x)
        y = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, param_dtype=jnp.float32)(y)
        y = nn.Dense(4 * self.dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.dim, dtype=self.dtype)(y)
        gamma = self.param(
            "layer_scale",
            nn.initializers.constant(self.layer_scale_init),
            (self.dim,),
            jnp.float32,
        )
        y = y * gamma.astype(y.dtype)
        y = DropPath(self.drop_path)(y, train=train)
        return x + y


class ConvNeXt(nn.Module):
    """ConvNeXt; ``depths=(3, 3, 27, 3), dims=(192, 384, 768, 1536)`` is -L."""

    num_classes: int = 1000
    depths: Sequence[int] = (3, 3, 27, 3)
    dims: Sequence[int] = (192, 384, 768, 1536)
    drop_path_rate: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        x = x.astype(self.dtype)
        # Stem: 4x4 stride-4 patchify conv + LN.
        x = nn.Conv(self.dims[0], (4, 4), strides=(4, 4), dtype=self.dtype)(x)
        x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, param_dtype=jnp.float32)(x)
        # Linearly increasing stochastic-depth schedule over all blocks.
        total_blocks = sum(self.depths)
        rates = np.linspace(0.0, self.drop_path_rate, total_blocks)  # static schedule
        block = 0
        for stage, (depth, dim) in enumerate(zip(self.depths, self.dims, strict=True)):
            if stage > 0:
                x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, param_dtype=jnp.float32)(x)
                x = nn.Conv(dim, (2, 2), strides=(2, 2), dtype=self.dtype)(x)
            for _ in range(depth):
                x = ConvNeXtBlock(
                    dim, drop_path=float(rates[block]), dtype=self.dtype
                )(x, train=train)
                block += 1
        x = x.mean(axis=(1, 2))
        x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = nn.Dense(self.num_classes, kernel_init=nn.initializers.normal(0.02))(
            x.astype(jnp.float32)
        )
        return x


def ConvNeXtL(num_classes: int = 21841, dtype: Any = jnp.float32, **kw) -> ConvNeXt:
    """ConvNeXt-Large; default head sized for ImageNet-21k (BASELINE config 5)."""
    return ConvNeXt(
        num_classes=num_classes,
        depths=(3, 3, 27, 3),
        dims=(192, 384, 768, 1536),
        dtype=dtype,
        **kw,
    )


def ConvNeXtTiny(num_classes: int = 10, dtype: Any = jnp.float32, **kw) -> ConvNeXt:
    """Small variant for tests (not the official ConvNeXt-T)."""
    return ConvNeXt(
        num_classes=num_classes, depths=(1, 1, 2, 1), dims=(16, 32, 64, 128), dtype=dtype, **kw
    )
