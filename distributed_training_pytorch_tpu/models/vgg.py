"""VGG16 in Flax — architecture parity with the reference ``model/vgg16.py``.

Mirrors: 5 conv stages of (64, 128, 256, 512, 512) channels with (2, 2, 3, 3, 3)
3x3 conv+ReLU layers each followed by 2x2 max-pool (``model/vgg16.py:5-17,24-28``),
adaptive average pool to 7x7 (``:34``), classifier 512*7*7 -> 4096 -> 4096 ->
num_classes with dropout 0.3 (``:37-43``), Kaiming-normal conv init and
N(0, 0.01) linear init (``:49-57``).

TPU-first differences (design, not behavior): NHWC layout (XLA:TPU's native conv
layout), a ``dtype`` knob for bfloat16 activations with float32 params, and the
adaptive pool expressed as two constant pooling matrices contracted with the
feature map — exact PyTorch ``AdaptiveAvgPool2d`` semantics, but lowered to MXU
matmuls instead of gather/scatter.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

# torch kaiming_normal_(relu): std = sqrt(2 / fan). VGG uses fan_out mode.
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")
dense_kernel_init = nn.initializers.normal(stddev=0.01)


def _adaptive_pool_matrix(in_size: int, out_size: int) -> np.ndarray:
    """Row-stochastic (out_size, in_size) matrix implementing torch's
    AdaptiveAvgPool1d bin assignment: bin i averages input range
    [floor(i*H/out), ceil((i+1)*H/out))."""
    mat = np.zeros((out_size, in_size), dtype=np.float32)
    for i in range(out_size):
        start = (i * in_size) // out_size
        end = -(-((i + 1) * in_size) // out_size)  # ceil division
        mat[i, start:end] = 1.0 / (end - start)
    return mat


def adaptive_avg_pool_2d(x: jax.Array, output_size: tuple[int, int]) -> jax.Array:
    """Exact ``nn.AdaptiveAvgPool2d`` for NHWC tensors, as two matmuls."""
    _, h, w, _ = x.shape
    oh, ow = output_size
    if (h, w) == (oh, ow):
        return x
    ph = jnp.asarray(_adaptive_pool_matrix(h, oh), dtype=x.dtype)
    pw = jnp.asarray(_adaptive_pool_matrix(w, ow), dtype=x.dtype)
    x = jnp.einsum("oh,bhwc->bowc", ph, x)
    x = jnp.einsum("pw,bowc->bopc", pw, x)
    return x


class ConvBlock(nn.Module):
    """N x (3x3 conv + ReLU) then 2x2 max-pool — ``model/vgg16.py:5-17``."""

    features: int
    num_layers: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for _ in range(self.num_layers):
            x = nn.Conv(
                self.features,
                (3, 3),
                padding=1,
                dtype=self.dtype,
                kernel_init=conv_kernel_init,
            )(x)
            x = nn.relu(x)
        return nn.max_pool(x, (2, 2), strides=(2, 2))


class VGG16(nn.Module):
    """VGG16 classifier. Input NHWC, spatial dims >= 32x32 (five 2x2 max-pools;
    the adaptive pool then maps any remaining size to 7x7)."""

    num_classes: int = 3
    stage_features: Sequence[int] = (64, 128, 256, 512, 512)
    stage_layers: Sequence[int] = (2, 2, 3, 3, 3)
    classifier_widths: Sequence[int] = (4096, 4096)
    dropout_rate: float = 0.3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        min_size = 2 ** len(self.stage_features)
        if x.shape[1] < min_size or x.shape[2] < min_size:
            raise ValueError(
                f"VGG16 input spatial dims must be >= {min_size}x{min_size} "
                f"({len(self.stage_features)} 2x2 max-pools), got {x.shape[1]}x{x.shape[2]}"
            )
        x = x.astype(self.dtype)
        for feats, layers in zip(self.stage_features, self.stage_layers, strict=True):
            x = ConvBlock(feats, layers, dtype=self.dtype)(x)
        x = adaptive_avg_pool_2d(x, (7, 7))
        x = x.reshape(x.shape[0], -1)
        for width in self.classifier_widths:
            x = nn.Dense(width, dtype=self.dtype, kernel_init=dense_kernel_init)(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, kernel_init=dense_kernel_init)(x)
        return x.astype(jnp.float32)
