"""Model wrappers."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


class InputNormalizer(nn.Module):
    """Wraps a classifier so raw uint8 NHWC batches normalize on device:
    ``(x/255 - mean)/std`` runs inside the jitted step, where XLA fuses it
    into the first conv — and the host->device link carries uint8 (4x fewer
    bytes than pre-normalized float32). Pair with the uint8 loader path
    (``data.native.NativeCropFlipU8`` / ``data.NativeRecordTrainSource``).

    Input contract (dispatch is static per input dtype):

    * **integer** input — raw 0-255 pixels; normalized here on device.
    * **float** input — taken as ALREADY normalized (e.g. a val source whose
      native decode normalizes in C++) and passed through untouched. Feeding
      un-normalized float32 0-255 images trains on a ~100x-misscaled input
      with no error from this wrapper; the ``Trainer`` emits a one-time
      warning when a float image batch's value range looks like raw pixels
      (``trainer.Trainer._check_image_range``).
    """

    inner: nn.Module
    mean: Sequence[float]
    std: Sequence[float]

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        if jnp.issubdtype(x.dtype, jnp.integer):
            mean = jnp.asarray(self.mean, jnp.float32)
            std = jnp.asarray(self.std, jnp.float32)
            x = (x.astype(jnp.float32) / 255.0 - mean) / std
        # float inputs are taken as already normalized (e.g. a val source
        # whose native decode normalizes in C++) and pass through — the
        # dispatch is static per input dtype, so mixed uint8-train /
        # f32-val pipelines trace one implementation each.
        return self.inner(x, train=train)
