"""JAX version-compat shims — the one place API moves are absorbed.

The framework targets the modern JAX surface (developed against 0.9), but
must import and run on any JAX back to 0.4.37 (the oldest the test matrix
carries). Four APIs moved or appeared between those versions; every call
site imports them from here instead of from ``jax`` directly:

* ``shard_map`` — promoted out of ``jax.experimental.shard_map`` to
  ``jax.shard_map``. The promoted API also renamed two parameters: the set
  of *manual* axes is ``axis_names=`` (the experimental API instead takes
  ``auto=``, the complementary set of axes left automatic), and replication
  checking is ``check_vma=`` (experimental: ``check_rep=``). The wrapper
  accepts the modern spelling and translates when falling back.
* ``jax.sharding.set_mesh`` — the ambient-mesh context manager. Old JAX
  spells it ``with mesh:`` (``Mesh`` is itself a context manager that sets
  the thread-resources env bare ``PartitionSpec``s resolve against).
* ``jax.sharding.get_abstract_mesh`` — the ambient (possibly abstract) mesh.
  Old JAX only has the concrete thread-resources mesh; an empty ``Mesh()``
  means "no ambient mesh", mirroring the modern empty ``AbstractMesh``.
* ``jax.lax.pcast`` — part of the varying-manual-axes (VMA) type system,
  which old JAX does not have; there the cast is semantically a no-op.
"""

from __future__ import annotations

import os
from typing import Any

import jax


def force_host_devices(n: int = 8) -> None:
    """Force an ``n``-device virtual CPU platform (the multi-chip test rig).

    The ONE implementation of the ``--xla_force_host_platform_device_count``
    setup that ``tests/conftest.py``, ``scripts/static_audit.py``,
    ``scripts/sharding_smoke.py``, and ``scripts/repro_triple_check.py``
    each used to hand-roll (ISSUE 11 satellite): appends the flag to
    ``XLA_FLAGS`` (never overwrites caller-supplied flags, and never doubles
    an existing count), pins ``JAX_PLATFORMS=cpu`` via env AND jax config
    (the environment may pre-import jax with a TPU plugin registered —
    sitecustomize — so both knobs are needed).

    Must run before jax first initializes its CPU client — the backend
    reads ``XLA_FLAGS`` exactly once, at its own first initialization.
    Merely *importing* jax (or this package) does not initialize it, so
    calling this right after imports is safe; calling it after something
    touched ``jax.devices()`` is too late and raises."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={int(n)}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    try:  # private probe, best-effort across the supported jax range
        from jax._src import xla_bridge

        initialized = xla_bridge.backends_are_initialized()
    except (ImportError, AttributeError):
        return
    if initialized:
        if (
            jax.device_count() == int(n)
            and jax.devices()[0].platform == "cpu"
        ):
            return  # already in the requested state — idempotent re-call
        raise RuntimeError(
            "force_host_devices called after the JAX backend initialized — "
            "the device count cannot change anymore; call it before anything "
            "touches jax.devices()"
        )

try:  # jax >= 0.6: shard_map is a top-level public API
    from jax import shard_map as _shard_map

    SHARD_MAP_MODERN = True
except ImportError:  # jax < 0.6: experimental module, auto=/check_rep= spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    SHARD_MAP_MODERN = False

# Partial-manual regions (manual over a strict subset of mesh axes) only
# work on the modern shard_map: the experimental `auto=` implementation
# aborts the process inside XLA (IsManualSubgroup CHECK failures) for the
# collective patterns pipeline/MoE composition needs. Feature-gate instead.
HAS_PARTIAL_MANUAL = SHARD_MAP_MODERN

# Multi-process CPU collectives: 0.4.x jaxlib's CPU backend rejects
# multiprocess computations outright ("not implemented on the CPU
# backend"); known-good on the 0.9 line the framework is developed against.
HAS_CPU_MULTIPROCESS = getattr(jax, "__version_info__", (0, 0, 0)) >= (0, 6, 0)

# Determinism contract: random bits must not depend on how an array is
# sharded (TP-vs-DP parity, resume across mesh layouts) — the library's
# augmentation/dropout reproducibility guarantees are stated under this
# flag. Modern JAX defaults it on; old JAX needs it flipped (newest JAX
# removed the flag after hard-enabling the behavior, hence the guard).
# Deliberate import-time side effect: on old JAX it changes sharded
# jax.random streams process-wide. Opt out AFTER import with
# jax.config.update("jax_threefry_partitionable", False) — at the cost of
# the parity guarantees above.
try:
    jax.config.update("jax_threefry_partitionable", True)
except (AttributeError, KeyError):
    pass


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None, check_vma=None):
    """``jax.shard_map`` with the modern keyword surface on every JAX.

    ``axis_names`` is the set of mesh axes the body is *manual* over (omit
    for fully manual, the modern default). ``check_vma`` toggles replication
    /varying checking. On old JAX these translate to ``auto=`` (complement
    of ``axis_names``) and ``check_rep=``; partial-manual regions there
    require replication checking off, so the fallback defaults it off
    unless explicitly requested.
    """
    if SHARD_MAP_MODERN:
        kwargs: dict[str, Any] = {}
        if axis_names is not None:
            kwargs["axis_names"] = frozenset(axis_names)
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
    kwargs = {"check_rep": bool(check_vma) if check_vma is not None else False}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            # Raise a catchable error instead of letting XLA abort the
            # process (see HAS_PARTIAL_MANUAL above).
            raise NotImplementedError(
                "partial-manual shard_map (manual over "
                f"{sorted(axis_names)} with {sorted(auto)} left automatic) "
                "requires jax >= 0.6 (jax.shard_map); this JAX only supports "
                "fully-manual regions. Gate callers on "
                "compat.HAS_PARTIAL_MANUAL."
            )
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def set_mesh(mesh: jax.sharding.Mesh):
    """Context manager making ``mesh`` the ambient mesh (bare-PartitionSpec
    resolution for ``with_sharding_constraint`` inside jitted bodies)."""
    if hasattr(jax.sharding, "set_mesh"):
        return jax.sharding.set_mesh(mesh)
    return mesh  # old JAX: Mesh is itself the context manager


def get_abstract_mesh():
    """The ambient mesh, or an empty mesh when none is set. Callers must
    treat ``axis_names == ()`` as "no ambient mesh" (both eras agree)."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return jax.sharding.get_abstract_mesh()
    from jax.interpreters import pxla

    return pxla.thread_resources.env.physical_mesh


def manual_axes_of(mesh) -> tuple:
    """Mesh axes currently *manual* (i.e. we are inside a ``shard_map``
    region over them). Modern JAX records this on the abstract mesh
    (``manual_axes``); old JAX instead binds manual axes as axis-env frames
    during the body trace, so we probe each mesh axis name there."""
    manual = getattr(mesh, "manual_axes", None)
    if manual is not None:
        return tuple(manual)
    try:
        from jax._src.core import axis_frame
    except ImportError:
        return ()
    bound = []
    for name in getattr(mesh, "axis_names", ()) or ():
        try:
            axis_frame(name)
        except Exception:
            continue
        bound.append(name)
    return tuple(bound)


def pcast(x, axis_name, *, to: str):
    """``jax.lax.pcast`` where the VMA type system exists; identity where it
    does not (pre-VMA JAX has no varying/replicated distinction to cast)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to=to)
    return x
