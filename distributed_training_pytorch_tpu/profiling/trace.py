"""Trace capture + headless per-op summaries.

TPU-native analog of the reference's observability hooks — the tqdm live
progress bars (``/root/reference/trainer/trainer.py:143,186``) and the NCCL
flight-recorder buffer (``/root/reference/run.sh:8``). On TPU the equivalent
is an XLA/XProf device trace: ``jax.profiler`` captures per-op device
timelines (including collective ops), viewable in TensorBoard's profile
plugin or summarized directly with :func:`top_ops` /
:func:`~distributed_training_pytorch_tpu.profiling.report.analyze_trace`.
"""

from __future__ import annotations

import glob
import os
from contextlib import contextmanager
from typing import Iterator

import jax

from distributed_training_pytorch_tpu.profiling import xplane

__all__ = ["trace", "annotate", "top_ops", "latest_trace_file"]


@contextmanager
def trace(log_dir: str) -> Iterator[str]:
    """Capture a device+host trace of the enclosed block into ``log_dir``.

    Yields the log dir. The result is a standard XProf/TensorBoard trace
    (``plugins/profile/<run>/*.xplane.pb``); inspect with TensorBoard,
    :func:`top_ops`, or ``report.analyze_trace``.
    """
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace region (context manager): ``with annotate("train_step"):``.

    Thin alias of ``jax.profiler.TraceAnnotation`` so user code only imports
    this module.
    """
    return jax.profiler.TraceAnnotation(name)


def latest_trace_file(log_dir: str) -> str | None:
    """Path of the newest ``*.xplane.pb`` under ``log_dir`` (or None)."""
    paths = glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"), recursive=True)
    return max(paths, key=os.path.getmtime) if paths else None


def top_ops(
    log_dir: str, *, limit: int = 20, line: str | None = None
) -> list[tuple[str, float, int]]:
    """Summarize the newest trace in ``log_dir``: device ops by total time.

    Returns ``[(op_name, total_time_us, occurrences), ...]`` over the device
    (TPU/GPU) planes, sorted descending — a headless op profile; no
    TensorBoard server needed.

    ``line`` filters to one named trace line. The TPU device plane carries
    several: ``"XLA Ops"`` is the synchronous critical path (its events sum
    to wall step time), ``"Async XLA Ops"`` holds overlapped DMA/prefetch
    copies whose durations span their async windows — summing across both
    double-counts overlap, so per-op accounting should pass
    ``line="XLA Ops"``. Default (None) keeps every line, preserving the
    "everything the device did" view.
    """
    path = latest_trace_file(log_dir)
    if path is None:
        raise FileNotFoundError(f"no *.xplane.pb under {log_dir}")
    totals: dict[str, list[float]] = {}
    for plane in xplane.read_trace(path):
        if "TPU" not in plane.name and "GPU" not in plane.name:
            continue
        for trace_line in plane.lines:
            if line is not None and trace_line.name != line:
                continue
            for event in trace_line.events:
                acc = totals.setdefault(event.name, [0.0, 0])
                acc[0] += event.duration_ps / 1e6  # ps -> us
                acc[1] += 1
    ranked = sorted(totals.items(), key=lambda kv: -kv[1][0])
    return [(name, round(t, 1), int(n)) for name, (t, n) in ranked[:limit]]
