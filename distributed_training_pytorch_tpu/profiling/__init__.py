"""Profiling subsystem (ISSUE 6): device-time attribution, dispatch/overlap
audit, hot-path capture, and the perf-regression gate.

Telemetry (``telemetry/``, docs/observability.md) answers *how much* of a
run's wall time was productive; this package answers *where the rest went* —
and keeps it from regressing silently:

* :mod:`~.trace`      — ``trace``/``annotate`` capture context managers +
  headless ``top_ops`` summaries (no TensorBoard server needed);
* :mod:`~.xplane`     — minimal ``*.xplane.pb`` wire codec (offsets AND
  durations, so traces support interval analysis);
* :mod:`~.categories` — the ONE HLO-op categorizer (shared by the report,
  ``scripts/profile_step.py``, and bench's ``BENCH_PROFILE`` fields);
* :mod:`~.report`     — ``analyze_trace`` -> :class:`StepProfile`: device
  wall attributed across op categories + the ``idle`` dispatch gap
  (fractions sum to 1), top-op table joined with per-op FLOPs/bytes/
  arithmetic intensity from ``utils.hlo_flops`` (roofline position);
* :mod:`~.capture`    — ``Trainer(profile=ProfileConfig(...))``: traces a
  window of REAL training steps (compile-skipping, chained-window aware,
  rank-0 owned, bit-exact/trace-count-neutral when off) and emits a
  ``profile_capture`` event;
* :mod:`~.gate`       — perf-regression gate logic behind
  ``scripts/perf_gate.py`` and the verify.sh stage (committed
  ``PERF_BASELINE.json``, relative tolerance, CPU-viable calibrated ratio);
* :mod:`~.diff`       — the across-runs layer (ISSUE 14):
  ``diff_profiles(before, after)`` -> :class:`ProfileDiff` with ranked
  per-category step-delta attribution (fractions of delta sum to 1),
  matched/new/removed op deltas and roofline shifts, plus the ONE generic
  ``attribute_delta`` used by ``scripts/run_compare.py`` and perf_gate's
  FAIL diagnosis.

``utils.profiling`` remains as a thin re-export shim for existing imports.
See docs/profiling.md for the capture -> report -> act workflow.
"""

from distributed_training_pytorch_tpu.profiling.capture import (  # noqa: F401
    ProfileConfig,
    StepTraceCapture,
    resolve_profile,
)
from distributed_training_pytorch_tpu.profiling.categories import (  # noqa: F401
    CATEGORIES,
    IDLE,
    categorize,
)
from distributed_training_pytorch_tpu.profiling.diff import (  # noqa: F401
    DeltaRow,
    OpDelta,
    ProfileDiff,
    attribute_delta,
    attribute_entry_delta,
    describe_rows,
    diff_profiles,
)
from distributed_training_pytorch_tpu.profiling.gate import (  # noqa: F401
    GateResult,
    load_baseline,
    update_baseline,
)
from distributed_training_pytorch_tpu.profiling.report import (  # noqa: F401
    REPORT_FIELDS,
    OpRow,
    StepProfile,
    analyze_trace,
    flops_index,
)
from distributed_training_pytorch_tpu.profiling.trace import (  # noqa: F401
    annotate,
    latest_trace_file,
    top_ops,
    trace,
)

__all__ = [
    "CATEGORIES",
    "DeltaRow",
    "GateResult",
    "IDLE",
    "OpDelta",
    "OpRow",
    "ProfileConfig",
    "ProfileDiff",
    "REPORT_FIELDS",
    "StepProfile",
    "StepTraceCapture",
    "analyze_trace",
    "annotate",
    "attribute_delta",
    "attribute_entry_delta",
    "categorize",
    "describe_rows",
    "diff_profiles",
    "flops_index",
    "latest_trace_file",
    "load_baseline",
    "resolve_profile",
    "top_ops",
    "trace",
    "update_baseline",
]
