"""Minimal XSpace (``*.xplane.pb``) wire-format codec.

The XProf trace jax.profiler captures is an XSpace protobuf
(tensorflow/tsl ``xplane.proto``). The pinned tensorboard_plugin_profile's
generated protos are incompatible with the installed protobuf runtime, so the
wire format is decoded directly — the schema subset a headless op profile
needs is tiny:

.. code-block:: none

    XSpace.planes = 1
    XPlane  { id=1, name=2, lines=3, event_metadata=4 (map<int64, XEventMetadata>) }
    XLine   { id=1, name=2, timestamp_ns=3, events=4 }
    XEvent  { metadata_id=1, offset_ps=2, duration_ps=3 }
    XEventMetadata (map-entry value) { id=1, name=2 }

Durations AND offsets are parsed (the seed parser read durations only), so a
trace supports *interval* analysis — busy-vs-idle attribution and the
dispatch-gap audit in :mod:`~.report` — not just per-op totals.

:func:`encode_xspace` is the write-side inverse for the same subset. It exists
so tests and fixtures can synthesize byte-exact traces with known attribution
(``tests/test_profiling.py`` checks category fractions against a checked-in
synthetic ``.xplane.pb`` built with it) — it is not a general XSpace writer.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

__all__ = [
    "TraceEvent",
    "TraceLine",
    "TracePlane",
    "read_trace",
    "encode_xspace",
]


# -- wire-format primitives ---------------------------------------------------


def _varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(buf: bytes) -> Iterator[tuple[int, int, int | bytes]]:
    """Yield ``(field_number, wire_type, value)`` for one protobuf message.

    A declared payload running past the buffer end raises ``ValueError``
    (a Python slice would silently truncate it — a torn write would then
    parse into a confidently wrong partial trace instead of an error)."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, i = _varint(buf, i)
        elif wire == 2:
            ln, i = _varint(buf, i)
            if i + ln > n:
                raise ValueError("length-delimited field runs past buffer end")
            val = buf[i : i + ln]
            i += ln
        elif wire == 5:
            if i + 4 > n:
                raise ValueError("fixed32 field runs past buffer end")
            val = buf[i : i + 4]
            i += 4
        elif wire == 1:
            if i + 8 > n:
                raise ValueError("fixed64 field runs past buffer end")
            val = buf[i : i + 8]
            i += 8
        else:  # groups (3/4) never appear in xplane
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


# -- read side ----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TraceEvent:
    """One timed occurrence of an op/region on a trace line.

    ``start_ps`` as parsed is the line-LOCAL offset (``XEvent.offset_ps`` is
    relative to its line's ``timestamp_ns``); cross-line interval analysis
    must rebase by the line's timestamp first (``report._abs_events``)."""

    name: str
    start_ps: int
    duration_ps: int

    @property
    def end_ps(self) -> int:
        return self.start_ps + self.duration_ps


@dataclasses.dataclass(frozen=True)
class TraceLine:
    name: str
    timestamp_ns: int
    events: tuple[TraceEvent, ...]


@dataclasses.dataclass(frozen=True)
class TracePlane:
    name: str
    lines: tuple[TraceLine, ...]


def read_trace(path: str) -> list[TracePlane]:
    """Parse one ``*.xplane.pb`` into planes -> lines -> timed events.

    Raises ``ValueError`` on truncated/corrupt bytes (a torn write from a
    crashed profiler, disk-full) — the error type every consumer's
    analysis-failure net already catches, so a bad trace degrades to a
    warning instead of killing the run."""
    with open(path, "rb") as f:
        space = f.read()
    try:
        return _decode_space(space)
    except (IndexError, ValueError) as e:  # varint/payload past the buffer end
        raise ValueError(f"{path}: truncated or corrupt xplane bytes") from e


def _decode_space(space: bytes) -> list[TracePlane]:
    planes: list[TracePlane] = []
    for field, _, plane_buf in _fields(space):
        if field != 1:  # XSpace.planes
            continue
        plane_name, meta_names, line_bufs = "", {}, []
        for pf, _, pv in _fields(plane_buf):
            if pf == 2:
                plane_name = pv.decode("utf-8", "replace")
            elif pf == 3:
                line_bufs.append(pv)
            elif pf == 4:  # map<int64, XEventMetadata> entry
                mid, mname = 0, ""
                for ef, _, ev in _fields(pv):
                    if ef == 2:  # value: XEventMetadata
                        for mf, _, mv in _fields(ev):
                            if mf == 1:
                                mid = mv
                            elif mf == 2:
                                mname = mv.decode("utf-8", "replace")
                meta_names[mid] = mname
        lines = []
        for line_buf in line_bufs:
            line_name, timestamp_ns, events = "", 0, []
            for lf, _, lv in _fields(line_buf):
                if lf == 2:
                    line_name = lv.decode("utf-8", "replace")
                elif lf == 3:
                    timestamp_ns = lv
                elif lf == 4:  # XLine.events
                    mid = offset_ps = dur_ps = 0
                    for ef, _, ev in _fields(lv):
                        if ef == 1:
                            mid = ev
                        elif ef == 2:
                            offset_ps = ev
                        elif ef == 3:
                            dur_ps = ev
                    events.append(
                        TraceEvent(
                            name=meta_names.get(mid, f"op#{mid}"),
                            start_ps=offset_ps,
                            duration_ps=dur_ps,
                        )
                    )
            lines.append(
                TraceLine(name=line_name, timestamp_ns=timestamp_ns, events=tuple(events))
            )
        planes.append(TracePlane(name=plane_name, lines=tuple(lines)))
    return planes


# -- write side (fixture synthesis) ------------------------------------------


def _enc_varint(value: int) -> bytes:
    if value < 0:
        # Arithmetic right-shift floors at -1: the loop below would append
        # 0xFF bytes forever. No XSpace field we synthesize is negative.
        raise ValueError(f"varint fields must be >= 0, got {value}")
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _enc_field(field: int, wire: int, payload: bytes | int) -> bytes:
    key = _enc_varint((field << 3) | wire)
    if wire == 0:
        return key + _enc_varint(payload)
    return key + _enc_varint(len(payload)) + payload


def encode_xspace(planes: list[dict]) -> bytes:
    """Encode ``[{name, lines: [{name, timestamp_ns, events: [(op_name,
    start_ps, duration_ps), ...]}, ...]}, ...]`` into XSpace bytes that
    :func:`read_trace` (and the seed parser) decode back exactly. Metadata
    ids are assigned per plane, one per distinct op name."""
    space = bytearray()
    for plane in planes:
        plane_buf = bytearray()
        plane_buf += _enc_field(2, 2, str(plane["name"]).encode())
        meta_ids: dict[str, int] = {}
        for line in plane.get("lines", ()):
            for op_name, _, _ in line.get("events", ()):
                meta_ids.setdefault(str(op_name), len(meta_ids) + 1)
        for line in plane.get("lines", ()):
            line_buf = bytearray()
            line_buf += _enc_field(2, 2, str(line["name"]).encode())
            line_buf += _enc_field(3, 0, int(line.get("timestamp_ns", 0)))
            for op_name, start_ps, duration_ps in line.get("events", ()):
                event_buf = (
                    _enc_field(1, 0, meta_ids[str(op_name)])
                    + _enc_field(2, 0, int(start_ps))
                    + _enc_field(3, 0, int(duration_ps))
                )
                line_buf += _enc_field(4, 2, bytes(event_buf))
            plane_buf += _enc_field(3, 2, bytes(line_buf))
        for op_name, mid in meta_ids.items():
            meta_buf = _enc_field(1, 0, mid) + _enc_field(2, 2, op_name.encode())
            entry_buf = _enc_field(1, 0, mid) + _enc_field(2, 2, bytes(meta_buf))
            plane_buf += _enc_field(4, 2, bytes(entry_buf))
        space += _enc_field(1, 2, bytes(plane_buf))
    return bytes(space)
