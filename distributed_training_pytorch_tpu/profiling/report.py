"""Trace analysis: device-time attribution + dispatch-gap audit (ISSUE 6).

Telemetry (PR 4) answers *how much* of the run's wall time was productive;
this module answers *where the device's own wall went*: a captured XLA trace
is parsed into a :class:`StepProfile` that attributes device wall across op
categories (matmul/conv compute, fusions, copies, collectives, infeed) plus
the ``idle`` gap between device programs — the fractions sum to 1 by
construction, so nothing can leak out of the attribution. The per-op top-k
table joins each hot op against ``utils.hlo_flops``'s per-instruction
itemization, so a hot op carries FLOPs + bytes + arithmetic intensity — its
roofline position: is this op compute-bound (intensity above the chip's
ridge point) or memory-bound?

The ``idle`` bucket is the dispatch-gap audit: the prime suspect for the
BENCH ``mfu`` 0.70 vs ``mfu_exec`` 0.49 gap is device wall spent *between*
programs (per-step dispatch, H2D waits), which no per-op table can show —
only the gaps between event intervals can.

Sources, in preference order:

* **device planes** (TPU/GPU): the ``"XLA Ops"`` line is the synchronous
  critical path — events are sequential, so busy time is the plain sum and
  every gap is real device idleness. On a multi-chip host, ONE representative
  chip plane (the busiest) is analyzed: attribution is per chip, like
  ``step_ms``/MFU.
* **host XLA-runtime threads** (CPU fallback, ``tf_XLA*`` lines): the CPU
  backend has no device plane, but its runtime threads carry per-HLO-op
  events. Threads overlap, so busy time is the *interval union* (summing
  would double-count parallel execution) and runtime bookkeeping events
  (``ThreadpoolListener::*`` etc.) are excluded. This keeps the whole
  capture -> report -> gate pipeline CPU-viable for verify.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Mapping

from distributed_training_pytorch_tpu.profiling import xplane
from distributed_training_pytorch_tpu.profiling.categories import IDLE, categorize
from distributed_training_pytorch_tpu.profiling.trace import latest_trace_file

__all__ = ["OpRow", "StepProfile", "REPORT_FIELDS", "analyze_trace", "flops_index"]

# Host-runtime bookkeeping events on the tf_XLA* thread lines — infrastructure,
# not HLO op execution; counted neither as busy time nor as ops.
_HOST_NOISE_PREFIXES = (
    "ThreadpoolListener",
    "ThunkExecutor",
    "TaskDispatcher",
    "Thunk::",
    "XlaModule",
    "BatchTimeUs",
)

# First HLO instruction token of a trace event name: "%fusion.3 = ..." or a
# bare "dot.3" (CPU runtime lines) both resolve to their instruction name.
_INSTR_RE = re.compile(r"^%?([\w.\-]+)")


@dataclasses.dataclass
class OpRow:
    """One per-op line of the attribution table."""

    name: str
    category: str
    total_us: float
    count: int
    frac_busy: float  # share of summed op time
    flops: float | None = None  # joined from utils.hlo_flops (matmul/conv only)
    bytes: float | None = None
    arith_intensity: float | None = None  # FLOPs/byte — roofline x-coordinate

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "category": self.category,
            "total_us": round(self.total_us, 1),
            "count": self.count,
            "frac_busy": round(self.frac_busy, 4),
        }
        if self.flops is not None:
            out["flops"] = self.flops
        if self.bytes is not None:
            out["bytes"] = self.bytes
        if self.arith_intensity is not None:
            out["arith_intensity"] = round(self.arith_intensity, 2)
        return out


# The stable report schema (test-enforced): every to_dict() carries exactly
# these keys. Consumers (bench JSON, profile_capture events, perf dashboards)
# may rely on them across PRs; additions append, never rename.
REPORT_FIELDS = (
    "trace_path",
    "source",
    "steps",
    "span_us",
    "busy_us",
    "idle_us",
    "step_us",
    "device_busy_frac",
    "dispatch_gap_frac",
    "categories",
    "category_us",
    "top_ops",
)


@dataclasses.dataclass
class StepProfile:
    """Device-time attribution for one traced window of steps.

    ``categories`` maps category -> fraction of the traced span (``idle``
    included) and sums to 1 +- float eps by construction; ``category_us``
    carries the same attribution in microseconds of op self-time (host
    sources can overlap threads, so op self-time may exceed the busy
    interval union — fractions are normalized through the union so the
    partition stays exhaustive)."""

    trace_path: str
    source: str  # "device" | "host-xla"
    steps: int | None
    span_us: float
    busy_us: float
    idle_us: float
    categories: dict[str, float]
    category_us: dict[str, float]
    top_ops: list[OpRow]
    step_us: float | None = None
    device_busy_frac: float = 0.0
    dispatch_gap_frac: float = 0.0

    def to_dict(self) -> dict:
        return {
            "trace_path": self.trace_path,
            "source": self.source,
            "steps": self.steps,
            "span_us": round(self.span_us, 1),
            "busy_us": round(self.busy_us, 1),
            "idle_us": round(self.idle_us, 1),
            "step_us": round(self.step_us, 1) if self.step_us is not None else None,
            "device_busy_frac": round(self.device_busy_frac, 4),
            "dispatch_gap_frac": round(self.dispatch_gap_frac, 4),
            "categories": {k: round(v, 4) for k, v in self.categories.items()},
            "category_us": {k: round(v, 1) for k, v in self.category_us.items()},
            "top_ops": [row.to_dict() for row in self.top_ops],
        }

    def summary(self) -> str:
        """One log line: busy/idle split + the two hottest categories."""
        hot = sorted(
            ((k, v) for k, v in self.categories.items() if k != IDLE),
            key=lambda kv: -kv[1],
        )[:2]
        hot_txt = ", ".join(f"{k} {100 * v:.0f}%" for k, v in hot)
        return (
            f"device busy {100 * self.device_busy_frac:.0f}% / "
            f"gap {100 * self.dispatch_gap_frac:.0f}% over {self.span_us / 1e3:.2f} ms"
            + (f" ({self.steps} steps)" if self.steps else "")
            + (f"; hottest: {hot_txt}" if hot_txt else "")
        )


def _union_us(intervals: list[tuple[int, int]]) -> float:
    """Total length (us) of the union of [start_ps, end_ps) intervals."""
    if not intervals:
        return 0.0
    intervals.sort()
    total_ps = 0
    cur_start, cur_end = intervals[0]
    for start, end in intervals[1:]:
        if start > cur_end:
            total_ps += cur_end - cur_start
            cur_start, cur_end = start, end
        else:
            cur_end = max(cur_end, end)
    total_ps += cur_end - cur_start
    return total_ps / 1e6


def _abs_events(line: xplane.TraceLine) -> list[xplane.TraceEvent]:
    """Rebase a line's events onto the shared trace clock: ``offset_ps`` is
    line-LOCAL (relative to ``XLine.timestamp_ns``), so interval analysis
    across lines — the host-thread union, gaps between device lines — must
    add the line's base first or timelines misalign."""
    base_ps = line.timestamp_ns * 1000  # ns -> ps
    if not base_ps:
        return list(line.events)
    return [
        dataclasses.replace(e, start_ps=e.start_ps + base_ps) for e in line.events
    ]


def _select_events(planes: list[xplane.TracePlane]) -> tuple[str, list[xplane.TraceEvent]]:
    """Pick the op-event stream: ONE representative device plane's
    critical-path lines, else the host XLA-runtime threads (CPU). Returns
    (source, events) with event starts rebased to the shared trace clock
    (see :func:`_abs_events`).

    A multi-chip host writes one plane per chip. Attribution is PER CHIP
    (step_ms/MFU are per-chip figures): pooling N planes into one timeline
    would sum op self-time N× against a single span and count ``idle`` only
    where every chip is simultaneously idle — hiding exactly the per-chip
    dispatch gaps the audit exists to expose. Under SPMD every chip runs the
    same program, so one plane is representative; the busiest plane (largest
    op self-time, name as the deterministic tie-break) is the chip gating
    the step."""
    # (op_self_time_ps, plane_name, events) per device plane, split by
    # whether the plane carries a real "XLA Ops" critical-path line.
    op_planes: list[tuple[int, str, list[xplane.TraceEvent]]] = []
    stream_planes: list[tuple[int, str, list[xplane.TraceEvent]]] = []
    for plane in planes:
        if "TPU" not in plane.name and "GPU" not in plane.name:
            continue
        has_op_line = any(line.name == "XLA Ops" for line in plane.lines)
        plane_events: list[xplane.TraceEvent] = []
        for line in plane.lines:
            if line.name == "XLA Ops":
                plane_events.extend(_abs_events(line))
            elif not has_op_line and "Async" not in line.name:
                # GPU stream lines carry op events without an "XLA Ops" line
                # name. Gated to planes WITHOUT one: on TPU the other lines
                # ("Async XLA Ops" DMA windows, "Steps", "XLA Modules") span
                # overlapped/aggregate intervals — promoting them to the
                # critical path would fabricate a near-1 busy fraction.
                plane_events.extend(_abs_events(line))
        if plane_events:
            bucket = op_planes if has_op_line else stream_planes
            bucket.append(
                (sum(e.duration_ps for e in plane_events), plane.name, plane_events)
            )
    for candidates in (op_planes, stream_planes):
        if candidates:
            _, _, events = max(candidates, key=lambda c: (c[0], c[1]))
            return "device", events
    host_events: list[xplane.TraceEvent] = []
    for plane in planes:
        for line in plane.lines:
            if not line.name.startswith("tf_XLA"):
                continue
            for event in _abs_events(line):
                if event.name.startswith(_HOST_NOISE_PREFIXES) or not event.duration_ps:
                    continue
                host_events.append(event)
    return "host-xla", host_events


def flops_index(compiled_or_hlo) -> dict[str, dict]:
    """Per-instruction roofline join table from a compiled executable (or raw
    HLO text): instruction name -> {flops, bytes, arith_intensity} for every
    conv/dot ``utils.hlo_flops`` itemizes. Fusions and custom calls are absent
    (their cost is opaque to the HLO walk) — joined rows simply carry None."""
    from distributed_training_pytorch_tpu.utils import hlo_flops

    text = compiled_or_hlo if isinstance(compiled_or_hlo, str) else compiled_or_hlo.as_text()
    index: dict[str, dict] = {}
    for row in hlo_flops.itemize_hlo_matmul_flops(text):
        entry = {"flops": row["flops"]}
        if row.get("bytes"):
            entry["bytes"] = row["bytes"]
            entry["arith_intensity"] = row["flops"] / row["bytes"]
        index[row["name"]] = entry
    return index


def analyze_trace(
    log_dir_or_file: str,
    *,
    steps: int | None = None,
    top_k: int = 20,
    flops_by_op: Mapping[str, dict] | None = None,
) -> StepProfile:
    """Parse the newest trace under ``log_dir_or_file`` into a StepProfile.

    ``steps`` (the number of train steps the trace covers) turns the span
    into a per-step figure; ``flops_by_op`` (see :func:`flops_index`) joins
    the top-op table with FLOPs/bytes/intensity. Raises ``FileNotFoundError``
    when no trace exists and ``ValueError`` when the trace carries no XLA op
    events at all (nothing to attribute)."""
    path = log_dir_or_file
    if not path.endswith(".xplane.pb"):
        found = latest_trace_file(path)
        if found is None:
            raise FileNotFoundError(f"no *.xplane.pb under {log_dir_or_file}")
        path = found
    source, events = _select_events(xplane.read_trace(path))
    if not events:
        raise ValueError(
            f"{path}: no XLA op events in any device plane or tf_XLA* host "
            "line — was anything dispatched inside the trace window?"
        )

    span_ps = max(e.end_ps for e in events) - min(e.start_ps for e in events)
    span_us = max(span_ps / 1e6, 1e-9)
    busy_us = min(_union_us([(e.start_ps, e.end_ps) for e in events]), span_us)
    idle_us = max(span_us - busy_us, 0.0)

    totals: dict[str, list[float]] = {}
    for event in events:
        acc = totals.setdefault(event.name, [0.0, 0])
        acc[0] += event.duration_ps / 1e6
        acc[1] += 1
    op_total_us = sum(t for t, _ in totals.values()) or 1e-9

    category_us: dict[str, float] = {}
    for name, (total, _) in totals.items():
        cat = categorize(name)
        category_us[cat] = category_us.get(cat, 0.0) + total
    # Fractions over the traced span: op categories share the busy fraction
    # proportionally to their self-time (identity on a sequential device
    # line where op time == busy time; on overlapping host threads this
    # normalizes through the interval union), and idle takes the rest — an
    # exhaustive partition, sum == 1 by construction.
    busy_frac = busy_us / span_us
    categories = {
        cat: (total / op_total_us) * busy_frac for cat, total in category_us.items()
    }
    categories[IDLE] = idle_us / span_us

    rows = []
    for name, (total, count) in sorted(totals.items(), key=lambda kv: -kv[1][0])[:top_k]:
        row = OpRow(
            name=name,
            category=categorize(name),
            total_us=total,
            count=count,
            frac_busy=total / op_total_us,
        )
        if flops_by_op:
            m = _INSTR_RE.match(name)
            joined = flops_by_op.get(m.group(1)) if m else None
            if joined:
                row.flops = joined.get("flops")
                row.bytes = joined.get("bytes")
                row.arith_intensity = joined.get("arith_intensity")
        rows.append(row)

    return StepProfile(
        trace_path=os.path.abspath(path),
        source=source,
        steps=steps,
        span_us=span_us,
        busy_us=busy_us,
        idle_us=idle_us,
        step_us=span_us / steps if steps else None,
        device_busy_frac=busy_frac,
        dispatch_gap_frac=idle_us / span_us,
        categories=categories,
        category_us=category_us,
        top_ops=rows,
    )
