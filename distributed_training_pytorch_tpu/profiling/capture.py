"""Hot-path trace capture: ``Trainer(profile=ProfileConfig(...))`` (ISSUE 6).

The capture is a tiny state machine the trainer drives at its existing unit
boundaries (a unit = one single step or one chained window), so it is

* **compile-skipping** — tracing starts at the first unit boundary after
  THIS process has dispatched ``skip_steps`` steps (default 1: the first
  dispatched unit, which pays XLA compilation, never pollutes the trace).
  The count is process-local and accumulates across epochs on purpose: a
  mid-epoch resume re-pays compilation on its first unit even though its
  epoch-local step index is large, and a ``skip_steps`` longer than an epoch
  simply starts tracing in a later epoch instead of never firing;
* **chained-window aware** — start/stop land on window boundaries, tracing
  whole windows of the REAL chained program. The legacy ``profile_dir`` knob
  forced the profiled prefix onto the single-step path; this capture traces
  the exact execution the run would perform anyway, which is why a
  ``profile=``-on run keeps ``TrainEngine.trace_counts`` and final params
  bit-identical to a ``profile=None`` run (test-enforced);
* **rank-0 owned** — only process 0 captures and writes, the logger/event-log
  file-ownership convention;
* **one-shot** — the first eligible window of the run is traced, then the
  machine parks in ``done`` and every later call is a cheap no-op.

On stop, the trace is summarized into a ``report.StepProfile`` and emitted as
a ``profile_capture`` telemetry event (the EventLog no-ops when telemetry is
off — the capture still writes the trace and logs the summary). Profiling
must never kill training: analysis failure, a trace dir that cannot be
created, and a profiler session that fails to start or stop are all warnings
that park the machine in ``done``.
"""

from __future__ import annotations

import dataclasses
import os

import jax

__all__ = ["ProfileConfig", "resolve_profile", "StepTraceCapture"]


@dataclasses.dataclass
class ProfileConfig:
    """``Trainer(profile=ProfileConfig(...))`` knobs.

    * ``dir``        — trace output dir (None = the trainer default,
      ``<save_folder>/profile``);
    * ``steps``      — train steps to trace (rounded up to whole windows
      under ``chain_steps``);
    * ``skip_steps`` — steps to let pass before tracing starts (default 1
      skips the compile step);
    * ``analyze``    — build a ``StepProfile`` + emit ``profile_capture``
      on stop (off = raw trace only);
    * ``top_k``      — rows kept in the report's per-op table.
    """

    dir: str | None = None
    steps: int = 5
    skip_steps: int = 1
    analyze: bool = True
    top_k: int = 10

    def __post_init__(self):
        if self.steps < 1:
            raise ValueError(f"ProfileConfig.steps must be >= 1, got {self.steps}")
        if self.skip_steps < 0:
            raise ValueError(
                f"ProfileConfig.skip_steps must be >= 0, got {self.skip_steps}"
            )


def resolve_profile(spec) -> ProfileConfig | None:
    """Trainer-knob resolution, mirroring ``telemetry.resolve_telemetry``:
    ``None``/``False`` = off; a string = trace dir with defaults; a
    :class:`ProfileConfig` passes through."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, str):
        return ProfileConfig(dir=spec)
    if isinstance(spec, ProfileConfig):
        return spec
    raise TypeError(
        f"profile must be None, a trace-dir string, or a ProfileConfig, got {type(spec)}"
    )


class StepTraceCapture:
    """Drives one traced window of real training steps at unit boundaries."""

    def __init__(self, config: ProfileConfig, *, log=None, events=None,
                 process_index: int | None = None, flops_source=None):
        if config.dir is None:
            raise ValueError("StepTraceCapture needs a resolved ProfileConfig.dir")
        self.config = config
        self._log = log or (lambda msg, log_type="info": print(f"{log_type.upper()}: {msg}"))
        self._events = events
        # Zero-arg callable -> flops_by_op mapping (see report.flops_index),
        # evaluated lazily at analysis time so the roofline join's one-time
        # probe compile is paid only by a capture that actually completes.
        self._flops_source = flops_source
        proc = jax.process_index() if process_index is None else process_index
        self.active = proc == 0  # rank-0 file ownership
        self.state = "waiting" if self.active else "done"
        self.start_step: int | None = None
        self.steps_traced = 0
        self.report = None  # StepProfile after an analyzed stop
        # Process-local skip accounting: steps THIS process has dispatched
        # (unit boundaries observed while waiting), and the first-step index
        # of the unit currently in flight. step_in_epoch itself cannot gate
        # the skip: a mid-epoch resume starts at a large epoch-local index
        # but its first unit still pays XLA compilation.
        self._steps_seen = 0
        self._unit_start: int | None = None

    def _note_boundary(self, step_in_epoch: int) -> None:
        if self._unit_start is not None:
            self._steps_seen += max(0, step_in_epoch - self._unit_start)
            self._unit_start = None

    def _fail(self, what: str, e: BaseException) -> None:
        # Profiling must never kill training: park the machine and warn.
        self.state = "done"
        self._log(f"profile: {what} failed ({e}) — capture disabled", "warning")
        if self._events is not None:
            self._events.emit("profile_capture", trace_dir=self.config.dir, error=repr(e))

    def maybe_start(self, step_in_epoch: int, sync=None) -> None:
        """Call BEFORE dispatching the unit whose first step is
        ``step_in_epoch``; starts tracing once this process has dispatched
        ``skip_steps`` steps (the compile-paying prefix)."""
        if self.state != "waiting":
            return
        self._note_boundary(step_in_epoch)
        if self._steps_seen < self.config.skip_steps:
            self._unit_start = step_in_epoch  # closed by the next boundary call
            return
        if sync is not None:
            # Drain in-flight dispatches so earlier (untraced) steps' device
            # work cannot bleed into the traced window.
            jax.block_until_ready(sync)
        try:
            os.makedirs(self.config.dir, exist_ok=True)
            jax.profiler.start_trace(self.config.dir)
        except (OSError, RuntimeError) as e:
            # e.g. unwritable trace dir, or another profiler session already
            # active (a user-level profiling.trace() around trainer.train()).
            self._fail("trace start", e)
            return
        self.state = "tracing"
        self.start_step = step_in_epoch

    def maybe_stop(
        self, step_in_epoch: int, sync=None, *, force: bool = False, abort: bool = False
    ) -> None:
        """Call AFTER a unit completes, with the next step index; stops once
        ``config.steps`` steps are covered (``force`` at epoch end).

        ``abort`` (exception-path teardown) stops the process-global profiler
        session but SKIPS analysis: the roofline join compiles an XLA probe
        and the parse reads the trace off disk — neither may delay an
        emergency save racing a preemption grace window. The raw trace stays
        on disk for TensorBoard."""
        if self.state == "waiting":
            self._note_boundary(step_in_epoch)  # skip-prefix unit completed
            return
        if self.state != "tracing":
            return
        covered = step_in_epoch - self.start_step
        if covered < self.config.steps and not force:
            return
        if sync is not None:
            jax.block_until_ready(sync)  # traced work must land inside the window
        try:
            jax.profiler.stop_trace()
        except (OSError, RuntimeError) as e:
            self._fail("trace stop", e)
            return
        self.state = "done"
        self.steps_traced = covered
        self._log(
            f"profile: traced steps [{self.start_step}, {step_in_epoch}) -> "
            f"{self.config.dir}"
        )
        if self.config.analyze and not abort:
            self._analyze()
        elif self._events is not None:
            self._events.emit(
                "profile_capture",
                trace_dir=self.config.dir,
                start_step=self.start_step,
                steps=self.steps_traced,
            )

    def _analyze(self) -> None:
        from distributed_training_pytorch_tpu.profiling.report import analyze_trace

        fields = {
            "trace_dir": self.config.dir,
            "start_step": self.start_step,
            "steps": self.steps_traced,
        }
        flops_by_op = None
        if self._flops_source is not None:
            try:
                flops_by_op = self._flops_source()
            except Exception as e:  # noqa: BLE001 — profiling must never kill training
                self._log(
                    f"profile: roofline join failed ({e}) — top-op table "
                    "carries no FLOPs/bytes columns",
                    "warning",
                )
        try:
            self.report = analyze_trace(
                self.config.dir,
                steps=self.steps_traced or None,
                top_k=self.config.top_k,
                flops_by_op=flops_by_op,
            )
        except (FileNotFoundError, ValueError, OSError) as e:
            # Profiling must never kill training: a trace the analyzer cannot
            # read still exists on disk for TensorBoard.
            self._log(f"profile: trace analysis failed ({e})", "warning")
            if self._events is not None:
                self._events.emit("profile_capture", **fields, error=repr(e))
            return
        summary = self.report.to_dict()
        self._log(f"profile: {self.report.summary()}")
        if self._events is not None:
            self._events.emit(
                "profile_capture",
                **fields,
                source=summary["source"],
                span_us=summary["span_us"],
                step_us=summary["step_us"],
                device_busy_frac=summary["device_busy_frac"],
                dispatch_gap_frac=summary["dispatch_gap_frac"],
                categories=summary["categories"],
            )
