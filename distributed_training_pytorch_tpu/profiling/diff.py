"""Profile A/B diff: why did step time change between two runs? (ISSUE 14)

PR 6 made ONE run exhaustively explainable (``StepProfile``: per-category
device-wall attribution + the idle dispatch gap, fractions summing to 1 by
construction). This module is the *across-runs* layer: two StepProfiles in,
one :class:`ProfileDiff` out, answering the question the ROADMAP actually
asks — *where did the step_ms delta come from?* BENCH r02→r05 sat flat at
~76.85 ms for four rounds and nothing could say which category refused to
move; ROADMAP item 2's Pallas/XLA-flag PR needs exactly this before/after
evidence to claim a win.

Conventions, inherited from StepProfile so the diff cannot invent time:

* **Per-step attribution.** Each side's per-category wall is
  ``category_fraction × step_us`` (``idle`` included). Fractions sum to 1,
  so per-category microseconds sum to the step time EXACTLY — and therefore
  the per-category *deltas* sum to the step-time delta exactly. Nothing can
  leak out of (or into) the attribution.
* **Fractions of delta sum to 1 by construction.** Each
  :class:`DeltaRow.frac_of_delta` is ``delta_cat / delta_total`` (signed:
  a category that *improved* inside a regressing step carries a negative
  fraction), so the ranked rows are a complete account of the change.
* **Ranked by |delta|** — the categories explaining the step_ms delta come
  first, the doctor-style report reads top-down.

Op level: the top-k tables of both sides are joined by instruction name —
matched ops carry before/after/delta, ops present on one side only are
called out as **new** / **removed** (a fusion-boundary change, a folded op,
a Pallas kernel replacing a conv). When both sides carry roofline columns,
an op whose arithmetic intensity crossed the chip's ridge point is a
**roofline shift** — memory-bound→compute-bound is the Pallas-win
signature (docs/profiling.md).

The small generic core — :func:`attribute_delta` over two ``{key: value}``
maps + :func:`describe_rows` — is THE one delta-attribution implementation
in the repo: ``scripts/run_compare.py`` uses it for profile categories and
goodput buckets alike, and ``scripts/perf_gate.py`` uses it to pre-diagnose
its own FAIL (test-enforced: neither script defines a private copy).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

from distributed_training_pytorch_tpu.profiling.categories import IDLE
from distributed_training_pytorch_tpu.profiling.report import StepProfile

__all__ = [
    "DeltaRow",
    "OpDelta",
    "ProfileDiff",
    "attribute_delta",
    "attribute_entry_delta",
    "describe_rows",
    "diff_profiles",
    "roofline_bound",
]


@dataclasses.dataclass
class DeltaRow:
    """One key's contribution to a total delta. ``frac_of_delta`` is signed
    and the rows of one :func:`attribute_delta` call sum to 1 by
    construction (0 everywhere when the totals are identical)."""

    key: str
    before: float
    after: float
    delta: float
    frac_of_delta: float

    def to_dict(self) -> dict:
        return {
            "key": self.key,
            "before": round(self.before, 4),
            "after": round(self.after, 4),
            "delta": round(self.delta, 4),
            "frac_of_delta": round(self.frac_of_delta, 4),
        }


def attribute_delta(
    before: Mapping[str, float], after: Mapping[str, float]
) -> list[DeltaRow]:
    """THE delta-attribution rule: per-key ``after - before`` over the union
    of keys (absent = 0), each with its signed share of the total delta,
    ranked by |delta| so the keys explaining the change come first.

    ``sum(row.delta) == sum(after.values()) - sum(before.values())`` exactly
    (same float additions), and ``sum(row.frac_of_delta) == 1`` whenever the
    total delta is nonzero — the attribution is exhaustive by construction,
    the StepProfile convention carried across runs."""
    keys = sorted(set(before) | set(after))
    total = sum(after.values()) - sum(before.values())
    rows = []
    for key in keys:
        b = float(before.get(key, 0.0))
        a = float(after.get(key, 0.0))
        delta = a - b
        rows.append(
            DeltaRow(
                key=key,
                before=b,
                after=a,
                delta=delta,
                frac_of_delta=(delta / total) if total else 0.0,
            )
        )
    rows.sort(key=lambda r: (-abs(r.delta), r.key))
    return rows


def attribute_entry_delta(
    before: Mapping, after: Mapping, *, metric: str = "step_ms"
) -> "list[DeltaRow] | None":
    """Category attribution of a ``step_ms`` delta between two measurement
    dicts (a ``PERF_BASELINE.json`` entry, a bench JSON line, a perf_gate
    measurement), each carrying ``metric`` plus ``categories`` — the
    StepProfile fraction dict (``idle`` included, summing to 1). Returns
    ranked per-category millisecond rows whose deltas sum to the step_ms
    delta exactly, or None when either side lacks the ingredients (the
    caller degrades to an unattributed verdict)."""
    try:
        b_ms = float(before[metric])
        a_ms = float(after[metric])
        b_cats = dict(before["categories"])
        a_cats = dict(after["categories"])
    except (KeyError, TypeError, ValueError):
        return None
    if not b_cats or not a_cats:
        return None
    return attribute_delta(
        {str(k): float(v) * b_ms for k, v in b_cats.items()},
        {str(k): float(v) * a_ms for k, v in a_cats.items()},
    )


def describe_rows(
    rows: list[DeltaRow], *, unit: str = "ms", top: int = 6, digits: int = 2
) -> str:
    """The doctor-style one-line attribution: ``conv +3.10 ms (74%), idle
    +0.90 ms (21%), …`` — shared by run_compare's verdict rows and
    perf_gate's FAIL diagnosis so the two can never phrase the same delta
    differently."""
    parts = []
    for row in rows[:top]:
        pct = f" ({100 * row.frac_of_delta:.0f}%)" if row.frac_of_delta else ""
        parts.append(f"{row.key} {row.delta:+.{digits}f} {unit}{pct}")
    dropped = len(rows) - top
    if dropped > 0:
        parts.append(f"… {dropped} smaller")
    return ", ".join(parts)


def roofline_bound(intensity: "float | None", ridge: "float | None") -> "str | None":
    """Classify an op's roofline position: ``compute``-bound at or above the
    ridge intensity (FLOPs/byte), ``memory``-bound below, None when either
    figure is unknown."""
    if intensity is None or ridge is None:
        return None
    return "compute" if intensity >= ridge else "memory"


@dataclasses.dataclass
class OpDelta:
    """One op's before/after line. ``status`` is ``matched`` / ``new`` /
    ``removed``; per-step microseconds on both sides (0 for the absent
    side). ``bound_shift`` names a ridge crossing (``memory->compute`` —
    the Pallas-win signature — or the reverse) when both sides carry
    roofline intensity and a ridge was given."""

    name: str
    category: str
    before_us: float
    after_us: float
    delta_us: float
    status: str
    intensity_before: "float | None" = None
    intensity_after: "float | None" = None
    bound_shift: "str | None" = None

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "category": self.category,
            "before_us": round(self.before_us, 1),
            "after_us": round(self.after_us, 1),
            "delta_us": round(self.delta_us, 1),
            "status": self.status,
        }
        if self.intensity_before is not None:
            out["intensity_before"] = round(self.intensity_before, 2)
        if self.intensity_after is not None:
            out["intensity_after"] = round(self.intensity_after, 2)
        if self.bound_shift is not None:
            out["bound_shift"] = self.bound_shift
        return out

    def describe(self) -> str:
        line = f"{self.name} [{self.category}] "
        if self.status == "new":
            line += f"NEW {self.after_us:.1f} us/step"
        elif self.status == "removed":
            line += f"REMOVED (was {self.before_us:.1f} us/step)"
        else:
            line += (
                f"{self.before_us:.1f} -> {self.after_us:.1f} us/step "
                f"({self.delta_us:+.1f})"
            )
        if self.bound_shift:
            line += (
                f"; roofline {self.bound_shift} "
                f"(intensity {self.intensity_before:.0f} -> {self.intensity_after:.0f})"
            )
        return line


@dataclasses.dataclass
class ProfileDiff:
    """The A/B report over two StepProfiles. ``categories`` are per-step
    microsecond rows (``idle`` included) whose deltas sum to
    ``step_delta_us`` exactly and whose ``frac_of_delta`` sum to 1;
    ``ops`` is the joined top-op table ranked by |delta|."""

    before_path: str
    after_path: str
    step_before_us: float
    step_after_us: float
    categories: list[DeltaRow]
    ops: list[OpDelta]

    @property
    def step_delta_us(self) -> float:
        return self.step_after_us - self.step_before_us

    @property
    def new_ops(self) -> list[OpDelta]:
        return [o for o in self.ops if o.status == "new"]

    @property
    def removed_ops(self) -> list[OpDelta]:
        return [o for o in self.ops if o.status == "removed"]

    @property
    def roofline_shifts(self) -> list[OpDelta]:
        return [o for o in self.ops if o.bound_shift is not None]

    def max_category_delta_frac(self) -> float:
        """Largest |category delta| relative to the larger step time — the
        identical-twins noise-floor figure (run_compare --self-test: no
        category of a twin pair may exceed the floor)."""
        denom = max(self.step_before_us, self.step_after_us, 1e-9)
        return max((abs(r.delta) / denom for r in self.categories), default=0.0)

    def to_dict(self) -> dict:
        return {
            "before": self.before_path,
            "after": self.after_path,
            "step_before_us": round(self.step_before_us, 1),
            "step_after_us": round(self.step_after_us, 1),
            "step_delta_us": round(self.step_delta_us, 1),
            "categories": [r.to_dict() for r in self.categories],
            "ops": [o.to_dict() for o in self.ops],
            "new_ops": [o.name for o in self.new_ops],
            "removed_ops": [o.name for o in self.removed_ops],
            "roofline_shifts": [o.to_dict() for o in self.roofline_shifts],
        }

    def describe(self, *, top: int = 6) -> str:
        ms = self.step_delta_us / 1e3
        pct = (
            f" ({100 * self.step_delta_us / self.step_before_us:+.1f}%)"
            if self.step_before_us
            else ""
        )
        lines = [
            f"step {self.step_before_us / 1e3:.2f} -> {self.step_after_us / 1e3:.2f} ms"
            f" ({ms:+.2f} ms{pct}): "
            + describe_rows(
                [
                    DeltaRow(r.key, r.before / 1e3, r.after / 1e3, r.delta / 1e3,
                             r.frac_of_delta)
                    for r in self.categories
                ],
                top=top,
            )
        ]
        for op in self.ops[:top]:
            if op.status != "matched" or abs(op.delta_us) > 0:
                lines.append("  op: " + op.describe())
        for op in self.roofline_shifts:
            if op not in self.ops[:top]:
                lines.append("  op: " + op.describe())
        lines.append(f"  evidence: before={self.before_path} after={self.after_path}")
        return "\n".join(lines)


def _as_report(profile) -> dict:
    """Accept a StepProfile or its ``to_dict()`` (the ``profile_capture``
    event payload / bench JSON fields carry the dict form). A live
    StepProfile is read at FULL precision — ``to_dict()`` rounds fractions
    to 4 digits for JSON, and the diff must not manufacture a few-ppm
    category delta out of display rounding."""
    if isinstance(profile, StepProfile):
        return {
            "trace_path": profile.trace_path,
            "source": profile.source,
            "steps": profile.steps,
            "span_us": profile.span_us,
            "step_us": profile.step_us,
            "categories": profile.categories,
            "top_ops": [row.to_dict() | {"total_us": row.total_us}
                        for row in profile.top_ops],
        }
    if isinstance(profile, dict):
        return profile
    raise TypeError(
        f"expected StepProfile or its to_dict() mapping, got {type(profile)}"
    )


def _per_step_us(report: dict) -> float:
    """One side's per-step span: ``step_us`` when the trace knew its step
    count, else the whole span as one unit (both sides then compare
    span-to-span — still exhaustive, just coarser)."""
    step = report.get("step_us")
    if step is None:
        step = report["span_us"]
    return float(step)


def _op_rows(report: dict) -> dict[str, dict]:
    steps = report.get("steps") or 1
    out = {}
    for row in report.get("top_ops", ()):  # OpRow dicts (REPORT_FIELDS schema)
        out[str(row["name"])] = {
            "category": row.get("category", "other"),
            "us": float(row["total_us"]) / steps,
            "intensity": row.get("arith_intensity"),
        }
    return out


def diff_profiles(
    before,
    after,
    *,
    ridge_intensity: "float | None" = None,
    top_k: int = 20,
) -> ProfileDiff:
    """Diff two step profiles (:class:`~.report.StepProfile` objects or
    their ``to_dict()`` forms) into a ranked :class:`ProfileDiff`.

    ``ridge_intensity`` (FLOPs/byte — peak FLOPs ÷ HBM bandwidth for the
    chip; ~200 on v5e bf16, see docs/profiling.md) arms the roofline-shift
    detector: a matched op whose arithmetic intensity crossed the ridge is
    flagged ``memory->compute`` (the Pallas-win signature) or the reverse.
    Without it, intensities are still carried on matched rows, shifts are
    simply not classified."""
    b = _as_report(before)
    a = _as_report(after)
    step_b = _per_step_us(b)
    step_a = _per_step_us(a)

    # Per-category per-step us: fraction x step — the fractions include
    # `idle` and sum to 1, so each side's rows sum to its step time and the
    # deltas sum to the step delta, exactly.
    cat_rows = attribute_delta(
        {str(k): float(v) * step_b for k, v in b.get("categories", {}).items()},
        {str(k): float(v) * step_a for k, v in a.get("categories", {}).items()},
    )

    ops_b = _op_rows(b)
    ops_a = _op_rows(a)
    op_deltas = []
    for name in sorted(set(ops_b) | set(ops_a)):
        rb, ra = ops_b.get(name), ops_a.get(name)
        status = "matched" if rb and ra else ("removed" if rb else "new")
        ib = rb.get("intensity") if rb else None
        ia = ra.get("intensity") if ra else None
        shift = None
        if status == "matched":
            bound_b = roofline_bound(ib, ridge_intensity)
            bound_a = roofline_bound(ia, ridge_intensity)
            if bound_b and bound_a and bound_b != bound_a:
                shift = f"{bound_b}->{bound_a}"
        op_deltas.append(
            OpDelta(
                name=name,
                category=(ra or rb)["category"],
                before_us=rb["us"] if rb else 0.0,
                after_us=ra["us"] if ra else 0.0,
                delta_us=(ra["us"] if ra else 0.0) - (rb["us"] if rb else 0.0),
                status=status,
                intensity_before=ib,
                intensity_after=ia,
                bound_shift=shift,
            )
        )
    op_deltas.sort(key=lambda o: (-abs(o.delta_us), o.name))

    return ProfileDiff(
        before_path=str(b.get("trace_path", "")),
        after_path=str(a.get("trace_path", "")),
        step_before_us=step_b,
        step_after_us=step_a,
        categories=cat_rows,
        ops=op_deltas[:top_k],
    )


# Re-exported for consumers that reason about the idle bucket by name
# (run_compare's verdict phrasing) without importing categories directly.
IDLE_CATEGORY = IDLE
