"""The ONE HLO-op categorizer (ISSUE 6 satellite: dedupe).

``scripts/profile_step.py`` used to carry a private ``categorize()``; this is
that implementation promoted to the shared source of truth, used by the CLI,
``report.analyze_trace``'s category attribution, and the bench's
``BENCH_PROFILE`` fields — one bucketing everywhere, so a category line in a
profile report, a bench JSON, and a ``profile_capture`` event always mean the
same thing.

Buckets follow where TPU step time actually goes: MXU work (``matmul``,
``convolution``), VPU elementwise (``fusion(elementwise)``), layout/data
movement (``copy/transpose``), cross-chip ops (``collective``), host->device
feed (``infeed``), pooling forward/backward, batch-stat reductions, and
``other``. ``IDLE`` is not an op category — it is the *absence* of device
work (gap between programs), attributed by ``report.analyze_trace`` from
event intervals.
"""

from __future__ import annotations

__all__ = ["CATEGORIES", "IDLE", "categorize"]

# Every value categorize() can return, in rough "hot on a TPU profile" order.
CATEGORIES = (
    "matmul",
    "convolution",
    "fusion(elementwise)",
    "copy/transpose",
    "collective",
    "infeed",
    "pool-forward",
    "pool-backward",
    "reduce(stats)",
    "other",
)

# The non-op attribution bucket: device wall with no program running
# (dispatch gaps between consecutive executables). See report.analyze_trace.
IDLE = "idle"


def categorize(name: str) -> str:
    """Bucket an HLO op name (a trace event name or an HLO text line).

    Every pattern matches the instruction HEAD (the text before `` = ``),
    never the operand list: a full HLO line like
    ``%copy.3 = f32[...] copy(%convolution.2)`` is a copy — matching the
    whole line would let the operand reference inflate the convolution
    bucket and shrink exactly the copy/transpose bucket the audit exists
    to expose."""
    head = name.split(" = ")[0]
    if "convolution" in head:
        return "convolution"
    if "select_and_scatter" in head or "select-and-scatter" in head:
        return "pool-backward"
    if "reduce_window" in head or "reduce-window" in head:
        return "pool-forward"
    if (
        "all-reduce" in head
        or "all-gather" in head
        or "reduce-scatter" in head
        or "collective-permute" in head
        or "all-to-all" in head
    ):
        # The full cross-chip family: permutes and all-to-alls are how SPMD
        # lowers resharding moves (measured on the fsdp audit programs) —
        # before ISSUE 11 they leaked into `other`, hiding comm time from
        # profile reports and comm bytes from the audit's category join.
        return "collective"
    if "infeed" in head or "outfeed" in head:
        return "infeed"
    if "copy" in head or "transpose" in head or "bitcast" in head:
        return "copy/transpose"
    if "reduce" in head:  # BN batch statistics, loss reductions
        return "reduce(stats)"
    if "fusion" in head:
        return "fusion(elementwise)"
    if "dot" in head or "custom-call" in head:
        return "matmul"
    return "other"
