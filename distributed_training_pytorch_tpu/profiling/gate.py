"""Perf-regression gate logic (ISSUE 6): measurement vs committed baseline.

Four flat bench rounds (BENCH_r02 -> r05, ~54k img/s/chip) happened silently
because nothing *failed* when step time stood still or slipped. The gate
makes perf a CI contract: ``scripts/perf_gate.py`` measures a step time,
this module compares it against the committed ``PERF_BASELINE.json`` with a
relative tolerance, and a regression past the tolerance is a nonzero exit in
``scripts/verify.sh`` — the same teeth the retrace/precision/telemetry
gates have.

Two comparison modes, one rule (``measured <= baseline * (1 + tolerance)``):

* **absolute** (``step_ms``) — for a pinned machine (the TPU bench host),
  where milliseconds are comparable across runs;
* **calibrated ratio** (``step_per_calib`` = workload step time / a fixed
  calibration kernel's time on the same machine) — for the CPU verify gate,
  where absolute milliseconds vary across dev machines but the *ratio* of
  two programs on the same machine is stable. Machine speed cancels to first
  order, so one committed baseline serves every contributor;
* **goodput-fraction ceiling** (``data_wait_frac`` — ISSUE 13 /
  ROADMAP item 5): the committed entry is a ceiling on the steady-state
  ``data_wait`` goodput fraction of a small real-Trainer run
  (``scripts/perf_gate.py --data-wait``), so the input pipeline cannot
  quietly become the bottleneck. Same rule — a fraction is already
  machine-portable.

The module is pure logic (no timing, no I/O beyond the baseline file) so the
pass/fail semantics are unit-testable on synthetic baselines — including the
injected-regression case verify.sh exercises end to end.
"""

from __future__ import annotations

import dataclasses
import json
import os

__all__ = [
    "DEFAULT_BASELINE_PATH",
    "GateResult",
    "check",
    "evaluate",
    "load_baseline",
    "update_baseline",
]

# Repo-root PERF_BASELINE.json (this module lives two levels down).
DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "PERF_BASELINE.json",
)

@dataclasses.dataclass
class GateResult:
    """One metric's verdict. ``ratio`` is measured/baseline: 1.0 = parity,
    above ``1 + tolerance`` = fail. ``stale`` flags a measurement so much
    *faster* than baseline (beyond the tolerance on the good side) that the
    committed baseline undersells the current code — a pass, with a nudge to
    re-record so the gate keeps protecting the new level."""

    key: str
    metric: str
    measured: float
    baseline: float
    tolerance: float
    ratio: float
    passed: bool
    stale: bool = False

    def describe(self) -> str:
        verdict = "PASS" if self.passed else "FAIL"
        line = (
            f"[{verdict}] {self.key}/{self.metric}: measured {self.measured:.4g} "
            f"vs baseline {self.baseline:.4g} (x{self.ratio:.3f}, "
            f"tolerance +{100 * self.tolerance:.0f}%)"
        )
        if not self.passed:
            line += f" — {self.metric} REGRESSION past tolerance"
        elif self.stale:
            line += (
                " — faster than baseline beyond tolerance; re-record it "
                "(scripts/perf_gate.py --update) so the gate protects the new level"
            )
        return line


def check(
    measured: float, baseline: float, tolerance: float, *, key: str, metric: str
) -> GateResult:
    """The one comparison rule: fail iff measured > baseline*(1+tolerance)."""
    if baseline <= 0:
        raise ValueError(f"{key}: baseline {metric} must be > 0, got {baseline}")
    if measured <= 0:
        raise ValueError(f"{key}: measured {metric} must be > 0, got {measured}")
    if tolerance <= 0:
        raise ValueError(f"{key}: tolerance must be > 0, got {tolerance}")
    ratio = measured / baseline
    return GateResult(
        key=key,
        metric=metric,
        measured=measured,
        baseline=baseline,
        tolerance=tolerance,
        ratio=ratio,
        passed=ratio <= 1.0 + tolerance,
        stale=ratio < 1.0 - tolerance,
    )


def load_baseline(path: str = DEFAULT_BASELINE_PATH) -> dict:
    with open(path, encoding="utf-8") as f:
        baseline = json.load(f)
    if "entries" not in baseline:
        raise ValueError(f"{path}: not a perf baseline (no 'entries' key)")
    return baseline


def evaluate(
    baseline: dict,
    key: str,
    measurement: dict,
    *,
    tolerance: float | None = None,
    default_tolerance: float | None = None,
) -> GateResult:
    """Gate ``measurement`` against ``baseline['entries'][key]``.

    Prefers the machine-portable ``step_per_calib`` ratio when both sides
    carry it, else absolute ``step_ms``. ``tolerance`` resolution order:
    explicit arg > ``baseline['tolerance'][key]`` > ``default_tolerance``
    (the CALLER's mode default — quick and full mode gate at very different
    tightness, so a constant here could only match one of them and would
    silently loosen or tighten the other). All three absent is an error, not
    a guess: a tolerance table lost in a merge must not soften the gate."""
    entries = baseline.get("entries", {})
    if key not in entries:
        raise KeyError(
            f"no baseline entry {key!r} (have {sorted(entries)}); record one "
            "with scripts/perf_gate.py --update"
        )
    entry = entries[key]
    if tolerance is None:
        tolerance = baseline.get("tolerance", {}).get(key, default_tolerance)
    if tolerance is None:
        raise ValueError(
            f"no tolerance for baseline entry {key!r} (no --tolerance arg, no "
            f"tolerance[{key!r}] record in the file, no caller default); "
            "re-record with scripts/perf_gate.py --update"
        )
    # Metric preference: the machine-portable calibrated ratio, then the
    # goodput-fraction ceiling (the --data-wait mode, ISSUE 13 — the entry
    # records a CEILING, same fail-iff-measured-exceeds rule), then
    # absolute milliseconds.
    for candidate in ("step_per_calib", "data_wait_frac"):
        if candidate in entry and candidate in measurement:
            metric = candidate
            break
    else:
        metric = "step_ms"
    if metric not in entry:
        # Not a missing baseline — the entry EXISTS but cannot gate this
        # measurement (e.g. a ratio-only entry against a full-mode step_ms
        # measurement). A KeyError here would be misreported as NO BASELINE.
        raise ValueError(
            f"baseline entry {key!r} has no {metric!r} (keys: {sorted(entry)}) "
            f"— it cannot gate this measurement; re-record it with "
            "scripts/perf_gate.py --update"
        )
    result = check(
        float(measurement[metric]), float(entry[metric]), float(tolerance),
        key=key, metric=metric,
    )
    if metric == "data_wait_frac":
        # The entry is a CEILING recorded with deliberate headroom
        # (perf_gate --data-wait --update): sitting well under it is the
        # healthy state, not a stale baseline to re-record.
        result.stale = False
    return result


def update_baseline(
    path: str, key: str, measurement: dict, *, tolerance: float | None = None
) -> dict:
    """Record/overwrite one entry, preserving every other entry and the
    file's tolerance table. Returns the written baseline dict."""
    try:
        baseline = load_baseline(path)
    except (FileNotFoundError, ValueError):
        # ValueError covers a malformed file (torn write, merge-conflict
        # markers, missing "entries"): --update is the documented recovery
        # for exactly that state, so it must rewrite, not crash. Other
        # entries in a malformed file are unrecoverable either way.
        baseline = {"schema": 1, "entries": {}, "tolerance": {}}
    baseline["entries"][key] = dict(measurement)
    if tolerance is not None:
        baseline.setdefault("tolerance", {})[key] = float(tolerance)
    tmp = path + ".staging"
    with open(tmp, "w", encoding="utf-8") as f:  # jaxlint: disable=file-write-without-rank-gate -- the --update baseline ritual: an operator CLI writing a repo file on one machine, not a training-job artifact
        json.dump(baseline, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return baseline
