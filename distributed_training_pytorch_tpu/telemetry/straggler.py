"""Per-chip straggler attribution at existing host sync points (ISSUE 13).

Multi-device meshes (ISSUE 10) and elastic N->M resumes (ISSUE 12) made
"which chip is slow?" a real operational question, but every timing figure
the trainer reports is a *global* host observation: the ``log_every`` sync
blocks until the slowest chip's work lands, so one degraded chip (thermal
throttling, a noisy PCIe neighbor, a failing HBM stack) shows up only as
"steps got slower" with no attribution.

This module is the timing twin of the PR 8 ``live_bytes_min/max/skew``
pattern — a per-local-device sample taken at a host sync the trainer
already pays, adding **zero extra device syncs**:

* :func:`sample_arrivals` walks one device-resident metrics array's
  addressable shards in device order, timing ``block_until_ready`` per
  shard. The sync point was about to block on ALL of them anyway (the
  ``float()`` metric fetch right after); sampling merely observes *which
  shard the host actually blocked on*. Each chip is charged its
  **incremental** blocking time (the delta over the previous shard's
  return — cumulative elapsed would bill every later chip for an earlier
  chip's tail and always crown the last-sampled device the straggler): a
  healthy SPMD window finishes near-simultaneously (all deltas ~0), while
  a straggler chip's shard absorbs the whole tail wherever it sits in the
  sampling order, so ``max - min`` of the per-chip deltas is the
  host-observed **dispatch skew** of the window's slowest chip.
* :func:`ratio` normalizes that skew by the window's per-step wall —
  "the slowest chip effectively ran each step ``ratio``× slower than the
  window average". Healthy ≈ 1.0 regardless of absolute step time, which
  is what makes it a baseline-able anomaly signal: the ``straggler``
  anomaly kind (``telemetry/anomaly.py``) fires when the ratio exceeds
  ``factor ×`` the post-warmup **floor** (the memory-growth floor rule:
  a floor cannot be dragged up by a slowly worsening chip).

Degradation contract (the ``memory.live`` convention): fewer than two
addressable shards (single-chip hosts, plain-CPU smoke runs) or a
non-Array metric return ``{}`` — the window records simply omit the
fields, and the detector never fires on an absent value.

Identity: every event record already carries ``host``/``process``/``pid``
plus the ``chips`` string (``telemetry/events.py``), and the sample names
``slowest_chip`` by global device id — so attribution stays coherent when
an elastic resume re-plans the topology mid-job (the resumed attempt's
records carry the NEW chip set; the flight log's append-across-restarts
property keeps both attempts' attributions side by side).
"""

from __future__ import annotations

import time

__all__ = ["FIELDS", "ratio", "sample_arrivals"]

# The per-window fields a successful sample contributes to the `window`
# event (docs/observability.md vocabulary).
FIELDS = (
    "chip_wall_ms_min",
    "chip_wall_ms_max",
    "chip_skew_ms",
    "slowest_chip",
    "chips_sampled",
)


def sample_arrivals(metric_tree, *, slow_chip: tuple[int, float] | None = None) -> dict:
    """Per-chip arrival sample off one window's device-resident metrics.

    ``metric_tree`` is the last executed unit's metrics pytree (device
    scalars, replicated over the mesh — every local device holds an
    addressable shard). Blocks on each shard in device-id order, charging
    each device the INCREMENTAL wall its shard kept the host blocked
    beyond the previous shard's return (see module doc: cumulative
    elapsed misattributes the tail). The TOTAL blocking time is what the
    sync's metric fetch would have paid anyway; only the per-device split
    is new information.

    ``slow_chip=(device_id, delay_s)`` is the deterministic degraded-chip
    seam (``FaultPlan`` kind ``slow_chip``): the named device's shard
    arrival is delayed by ``delay_s`` before blocking, so its incremental
    wait — and only its — absorbs the injected tail, exactly as a
    thermally-throttled chip's would. The delay is host-side ``sleep``, so
    the fault perturbs *observed timing only*, never the computed numbers.

    Returns the :data:`FIELDS` dict, or ``{}`` when there are fewer than
    two addressable shards to compare (nothing to attribute)."""
    import jax

    leaves = jax.tree.leaves(metric_tree)
    arr = leaves[0] if leaves else None
    shards = getattr(arr, "addressable_shards", None)
    if not shards or len(shards) < 2:
        return {}
    shards = sorted(shards, key=lambda s: s.device.id)
    prev = time.perf_counter()
    waits = []
    for shard in shards:
        if slow_chip is not None and int(shard.device.id) == int(slow_chip[0]):
            time.sleep(max(float(slow_chip[1]), 0.0))
        try:
            shard.data.block_until_ready()
        except (AttributeError, RuntimeError):
            return {}  # a backend without per-shard blocking: degrade, never guess
        now = time.perf_counter()
        waits.append((now - prev, shard.device.id))
        prev = now
    lo_ms = min(w for w, _ in waits) * 1e3
    hi_ms, slowest = max(waits)
    hi_ms *= 1e3
    return {
        "chip_wall_ms_min": lo_ms,
        "chip_wall_ms_max": hi_ms,
        "chip_skew_ms": hi_ms - lo_ms,
        "slowest_chip": int(slowest),
        "chips_sampled": len(waits),
    }


def ratio(skew_ms: float, step_ms: float) -> float:
    """Slowest-chip ratio: ``1 + skew / step`` — how much slower the
    slowest chip effectively ran each of the window's steps than the
    window-average step wall. 1.0 = perfectly synchronous; 2.0 = one chip
    cost the window a full extra step-time. Normalizing by step wall makes
    the figure comparable across models/batch sizes (absolute skew is
    not), which is what the floor-baselined ``straggler`` anomaly needs."""
    step_ms = max(float(step_ms), 1e-9)
    return 1.0 + max(float(skew_ms), 0.0) / step_ms
