"""The run doctor: a ranked, machine-checkable diagnosis of a training run.

The instruments are installed — goodput buckets (PR 4), profile captures
(PR 6), live memory (PR 8), straggler skew (this PR) — but reading them
still took a human. This module turns the signals into one of six
verdicts, each carrying the evidence rows (goodput fractions, event-log
line numbers, timeline track refs) that justify it:

=====================  ====================================================
verdict                signature
=====================  ====================================================
``compile_bound``      non-probe ``compile`` events past the attempt's
                       starting epoch — the steady state is retracing
                       (warmup compiles in the epoch an attempt began at —
                       epoch 0 cold, the resume epoch after a restart —
                       are normal and never fire this)
``data_bound``         steady-state ``data_wait`` fraction over the ceiling
                       (default 20%) — the input pipeline starves the chips
``checkpoint_stall``   steady-state ``checkpoint`` fraction over the
                       ceiling (default 20%) — hot-loop save stalls /
                       commit backpressure dominate
``straggler``          a ``straggler``/``step_time_regression`` anomaly or
                       ``hung_step`` fired, or the worst window's
                       slowest-chip ratio exceeds the threshold — one chip
                       (or a host hang) is pacing the job
``comm_heavy``         the profile capture attributes more than the
                       threshold of device wall to ``collective`` ops —
                       the sharding plan spends the chips on the wire
``healthy``            none of the above
=====================  ====================================================

Two further kinds — ``stale_heartbeat`` (the run emits but no execution
unit completes) and ``dead`` (the log itself went silent) — belong to the
same vocabulary but are produced only by the streaming monitor
(``telemetry/monitor.py``, ISSUE 15), which alone holds a wall clock to
compare the log's last pulse against; a complete log read post-hoc is
finished, not dead.

**Steady-state fractions.** Verdicts divide by the wall the run could
actually control: ``total - compile - restart_rollback -
checkpoint_async`` (one-time warmup, resume overhead, and overlapped
background commits excluded). A two-epoch CPU smoke run spends half its
wall in XLA compile; dividing data_wait by *total* would let a genuinely
data-bound run hide behind warmup, and a clean short run misread as
healthy-by-dilution. The perf gate's ``data_wait`` ceiling
(``scripts/perf_gate.py --data-wait``) gates the SAME
:func:`steady_fractions` figure, so the gate and the doctor cannot
disagree about what "data-bound" means.

Scores are severities normalized to the threshold: ``score >= 1.0`` means
"over the line", and verdicts rank by score. The same rules run in two
places: offline over a run directory's event log
(:func:`extract_signals` + :func:`diagnose` — ``scripts/run_doctor.py``),
and live at epoch end from the trainer's in-memory counters
(:func:`scalar_fields` — the ``doctor/*`` TensorBoard scalars), so the
dashboard sees what the offline doctor would say.
"""

from __future__ import annotations

import dataclasses

from distributed_training_pytorch_tpu.telemetry.goodput import BUCKETS

__all__ = [
    "Diagnosis",
    "Signals",
    "THRESHOLDS",
    "VERDICTS",
    "Verdict",
    "diagnose",
    "extract_signals",
    "scalar_fields",
    "steady_fractions",
    "update_signals",
]

VERDICTS = (
    "compile_bound",
    "data_bound",
    "checkpoint_stall",
    "straggler",
    "comm_heavy",
    # Liveness verdicts (ISSUE 15): produced by the streaming monitor
    # (``telemetry/monitor.py``), which alone can compare the log's last
    # pulse against a wall clock — a finished log read post-hoc is neither
    # stale nor dead. Named here so the vocabulary has ONE home.
    "stale_heartbeat",
    "dead",
    "healthy",
)

# Firing ceilings. A verdict's score is measured/threshold (>= 1.0 fires);
# the thresholds are deliberately generous — the doctor names what
# DOMINATES a run, not every inefficiency.
THRESHOLDS = {
    "data_wait_frac": 0.20,
    "checkpoint_frac": 0.20,
    "straggler_ratio": 1.5,
    "comm_frac": 0.25,
}

# Buckets excluded from the steady-state denominator (see module doc).
_EXCLUDED = ("compile", "restart_rollback", "checkpoint_async")


def steady_fractions(seconds: dict) -> dict:
    """Bucket fractions of the steady-state wall (warmup/resume/overlapped
    buckets excluded from the denominator; their own fractions report 0).
    All zeros when nothing steady-state was accounted."""
    steady = {b: float(seconds.get(b, 0.0)) for b in BUCKETS}
    denom = sum(v for b, v in steady.items() if b not in _EXCLUDED)
    if denom <= 0.0:
        return {b: 0.0 for b in BUCKETS}
    return {b: (0.0 if b in _EXCLUDED else v / denom) for b, v in steady.items()}


@dataclasses.dataclass
class Signals:
    """The doctor's inputs, source-agnostic: :func:`extract_signals` fills
    them from an event log; the trainer fills them from live counters."""

    goodput_seconds: dict | None = None
    anomaly_counts: dict = dataclasses.field(default_factory=dict)
    hung_steps: int = 0
    max_straggler_ratio: float | None = None
    # Global device id of the chip the worst window blocked on (rides the
    # same `window` record as the ratio) — the fleet controller's
    # exclude-and-replan leg needs a NAMED chip, not just a ratio.
    slowest_chip: int | None = None
    # Epoch the newest attempt started at (run_start's `epoch` field): a
    # resumed attempt's first-epoch compiles are warmup exactly like a cold
    # start's epoch-0 compiles — without this, every controller-restarted
    # run mid-training would read as compile_bound.
    start_epoch: int = 0
    late_compiles: int = 0
    comm_frac: float | None = None
    # Evidence rows keyed by verdict kind: lists of {"metric"/"value"/
    # "line"/"timeline"} dicts accumulated during extraction.
    evidence: dict = dataclasses.field(default_factory=dict)

    def note(self, kind: str, **row) -> None:
        self.evidence.setdefault(kind, []).append(row)


@dataclasses.dataclass
class Verdict:
    kind: str
    score: float
    summary: str
    evidence: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Diagnosis:
    verdicts: list  # ranked, most severe first; never empty
    signals: Signals

    @property
    def verdict(self) -> str:
        return self.verdicts[0].kind

    @property
    def healthy(self) -> bool:
        return self.verdict == "healthy"

    def to_dict(self) -> dict:
        return {
            "verdict": self.verdict,
            "healthy": self.healthy,
            "verdicts": [v.to_dict() for v in self.verdicts],
            "steady_fractions": steady_fractions(self.signals.goodput_seconds or {}),
        }

    def describe(self) -> str:
        lines = []
        for i, v in enumerate(self.verdicts, 1):
            lines.append(f"  {i}. [{v.kind}] score {v.score:.2f} — {v.summary}")
            for row in v.evidence:
                cite = ", ".join(
                    f"{k}={row[k]}"
                    for k in ("metric", "value", "threshold", "chip", "line", "timeline")
                    if row.get(k) is not None
                )
                lines.append(f"       evidence: {cite}")
        return "\n".join(lines)


def update_signals(sig: Signals, rec: dict) -> None:
    """Fold ONE event record into :class:`Signals` — the incremental unit
    behind both read paths (ISSUE 15): :func:`extract_signals` loops it
    over a complete log (``scripts/run_doctor.py``), and the streaming
    monitor (``telemetry/monitor.py``) calls it per record as its tail
    follower yields them — so the post-hoc doctor and the live monitor
    derive their verdicts from literally the same accumulation (the
    same-log => byte-identical-verdicts regression test pins it)."""
    kind = rec.get("event")
    line = rec.get("_line")
    if isinstance(rec.get("goodput_seconds"), dict):
        # Cumulative counters: the LAST snapshot wins (append-across-
        # restarts keeps them cumulative over the whole job). ONE evidence
        # row, REPLACED rather than appended: heartbeats carry a snapshot
        # every pulse (ISSUE 15), and an append here would grow every
        # fraction verdict's evidence — and a long-lived monitor's memory
        # — by one identical row per heartbeat.
        sig.goodput_seconds = dict(rec["goodput_seconds"])
        sig.evidence["goodput"] = [
            dict(metric="goodput_seconds", line=line, timeline="goodput")
        ]
    if kind == "anomaly":
        akind = str(rec.get("kind"))
        sig.anomaly_counts[akind] = sig.anomaly_counts.get(akind, 0) + 1
        if akind in ("straggler", "step_time_regression"):
            sig.note("straggler", metric=f"anomaly:{akind}",
                     value=rec.get("value"), line=line, timeline="markers")
    elif kind == "hung_step":
        sig.hung_steps += 1
        sig.note("straggler", metric="hung_step",
                 value=rec.get("timeout_s"), line=line, timeline="markers")
    elif kind == "window" and rec.get("straggler_ratio") is not None:
        r = float(rec["straggler_ratio"])
        if sig.max_straggler_ratio is None or r > sig.max_straggler_ratio:
            sig.max_straggler_ratio = r
            chip = rec.get("slowest_chip")
            sig.slowest_chip = int(chip) if chip is not None else None
            sig.note("straggler_ratio", metric="straggler_ratio", value=round(r, 4),
                     chip=sig.slowest_chip, line=line, timeline="steps")
    elif kind == "run_start":
        # Where THIS attempt began: compiles in its starting epoch are
        # warmup (a resume recompiles its executables mid-run), not the
        # retrace signature. Fresh runs start at 0 — identical behavior.
        sig.start_epoch = int(rec.get("epoch") or 0)
    elif kind == "compile" and rec.get("kind") != "mfu_probe":
        if int(rec.get("epoch", 0) or 0) > sig.start_epoch:
            sig.late_compiles += 1
            sig.note("compile_bound", metric="late_compile",
                     value=rec.get("executables"), line=line, timeline="markers")
    elif kind == "profile_capture" and isinstance(rec.get("categories"), dict):
        sig.comm_frac = float(rec["categories"].get("collective", 0.0))
        sig.note("comm_heavy", metric="collective_frac",
                 value=round(sig.comm_frac, 4), line=line, timeline="profile")


def extract_signals(events: list[dict]) -> Signals:
    """Distill an event log (``events.load_run_events`` output — records
    carry ``_line``) into :class:`Signals`, citing line numbers and the
    timeline track each piece of evidence lands on. A loop over
    :func:`update_signals` and nothing more — the streaming monitor's
    incremental path IS this path."""
    sig = Signals()
    for rec in events:
        update_signals(sig, rec)
    return sig


def _verdicts(sig: Signals) -> list[Verdict]:
    found = []
    fr = steady_fractions(sig.goodput_seconds or {})

    def frac_verdict(kind, bucket, threshold_key, what):
        f = fr.get(bucket, 0.0)
        threshold = THRESHOLDS[threshold_key]
        score = f / threshold
        if score >= 1.0:
            ev = [dict(metric=f"{bucket}_frac_steady", value=round(f, 4),
                       threshold=threshold, timeline="goodput")]
            ev += sig.evidence.get("goodput", [])
            found.append(Verdict(
                kind, score,
                f"{what}: {bucket} is {100 * f:.0f}% of steady-state wall "
                f"(ceiling {100 * threshold:.0f}%)", ev))
        return score

    frac_verdict("data_bound", "data_wait", "data_wait_frac",
                 "the input pipeline starves the chips")
    frac_verdict("checkpoint_stall", "checkpoint", "checkpoint_frac",
                 "checkpoint saves stall the hot loop")

    if sig.late_compiles > 0:
        found.append(Verdict(
            "compile_bound", 1.0 + float(sig.late_compiles),
            f"{sig.late_compiles} executable(s) compiled past the attempt's "
            "warmup epoch — the "
            "steady state is retracing (a shape leak or a lost executable "
            "cache), not warmup",
            sig.evidence.get("compile_bound", [])))

    strag_score = 0.0
    if sig.max_straggler_ratio is not None:
        strag_score = sig.max_straggler_ratio / THRESHOLDS["straggler_ratio"]
    n_anom = sig.anomaly_counts.get("straggler", 0)
    n_regress = sig.anomaly_counts.get("step_time_regression", 0)
    if n_anom:
        strag_score = max(strag_score, 1.0 + float(n_anom))
    if n_regress:
        strag_score = max(strag_score, 1.0 + 0.5 * n_regress)
    if sig.hung_steps:
        strag_score = max(strag_score, 2.0 + float(sig.hung_steps))
    if strag_score >= 1.0:
        parts = []
        if n_anom:
            parts.append(f"{n_anom} straggler anomaly(ies)")
        if n_regress:
            parts.append(f"{n_regress} step-time regression(s)")
        if sig.hung_steps:
            parts.append(f"{sig.hung_steps} hung step(s)")
        if sig.max_straggler_ratio is not None and (
            sig.max_straggler_ratio >= THRESHOLDS["straggler_ratio"]
        ):
            chip = "" if sig.slowest_chip is None else f" (chip {sig.slowest_chip})"
            parts.append(
                f"worst slowest-chip ratio {sig.max_straggler_ratio:.2f}{chip}"
            )
        found.append(Verdict(
            "straggler", strag_score,
            "one chip (or a host-side hang) is pacing the job: " + ", ".join(parts),
            sig.evidence.get("straggler", []) + sig.evidence.get("straggler_ratio", [])))

    if sig.comm_frac is not None:
        score = sig.comm_frac / THRESHOLDS["comm_frac"]
        if score >= 1.0:
            found.append(Verdict(
                "comm_heavy", score,
                f"collectives take {100 * sig.comm_frac:.0f}% of traced device "
                f"wall (ceiling {100 * THRESHOLDS['comm_frac']:.0f}%) — the "
                "sharding plan spends the chips on the wire",
                sig.evidence.get("comm_heavy", [])))
    return found


def diagnose(signals_or_events) -> Diagnosis:
    """Rank the verdicts for a run. Accepts :class:`Signals` (the trainer's
    live path) or a parsed event list (the offline path). Always returns
    at least one verdict — ``healthy`` with the goodput headline as its
    evidence when nothing fires."""
    sig = (signals_or_events if isinstance(signals_or_events, Signals)
           else extract_signals(list(signals_or_events)))
    found = sorted(_verdicts(sig), key=lambda v: -v.score)
    if not found:
        fr = steady_fractions(sig.goodput_seconds or {})
        found = [Verdict(
            "healthy", 0.0,
            f"no bottleneck over threshold (steady-state productive fraction "
            f"{100 * fr.get('productive_step', 0.0):.0f}%)",
            [dict(metric="productive_frac_steady",
                  value=round(fr.get("productive_step", 0.0), 4), timeline="goodput")])]
    return Diagnosis(found, sig)


def scalar_fields(sig: Signals) -> dict:
    """The live-dashboard projection: per-verdict severity scores (0.0 when
    the rule is quiet) + ``healthy`` as 1.0/0.0 — written at epoch end
    under the ``doctor/`` TensorBoard prefix so dashboards see what the
    offline doctor would say. Floats only (the MetricsWriter contract)."""
    scores = {k: 0.0 for k in VERDICTS if k != "healthy"}
    for v in _verdicts(sig):
        scores[v.kind] = max(scores[v.kind], float(v.score))
    scores["healthy"] = 0.0 if any(s >= 1.0 for s in scores.values()) else 1.0
    return scores
