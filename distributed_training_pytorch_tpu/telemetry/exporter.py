"""In-process status exporter: ``/status`` JSON + ``/metrics`` Prometheus
text straight from the live trainer counters (ISSUE 15 tentpole c).

``Trainer(telemetry=Telemetry(export_port=...))`` starts one
:class:`StatusExporter` on process 0: a stdlib ``ThreadingHTTPServer`` on
a daemon thread serving two endpoints —

* ``GET /status``  — one JSON object: the trainer's latest status
  snapshot (goodput fractions, step_ms, MFU, live/peak bytes, loss scale,
  anomaly counts, the live doctor scores + top verdict);
* ``GET /metrics`` — the same snapshot rendered as Prometheus exposition
  text (gauges under the ``tpu_trainer_`` prefix), so a standard scrape
  config points at a training job with zero glue.

Design rules (the EventLog never-kills-training policy, applied to HTTP):

* **The hot loop is never blocked.** The trainer *builds* a fresh
  snapshot dict at its existing ``log_every`` sync points and swaps it in
  with one (GIL-atomic) reference assignment; the HTTP threads only ever
  read whichever complete dict the reference points at. No lock spans the
  step loop, no handler touches live mutable trainer state, and a scrape
  between syncs simply serves the previous snapshot.
* **A taken port degrades to a warning.** Binding failure (another run on
  the port, a permission error) logs one warning and disables the
  exporter — a observability knob must never be why training died.
* **Bit-exact with the exporter off.** The exporter reads host-side
  floats the telemetry layer already fetched: params and
  ``trace_counts`` are identical with ``export_port=None``
  (test-enforced — the historical-program pillar).

``port=0`` binds an ephemeral port (tests); read it back from
:attr:`StatusExporter.port`.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

# The ONE JSON-safety rule (events.py): non-finite floats become their repr
# strings instead of bare NaN/Infinity tokens — a diverged run's /status
# (loss=NaN is exactly when an operator scrapes it) must stay parseable by
# strict JSON consumers, the same contract the event log keeps.
from distributed_training_pytorch_tpu.telemetry.events import _jsonable

__all__ = ["StatusExporter", "prometheus_text"]

# Prometheus metric-name charset ([a-zA-Z_:][a-zA-Z0-9_:]*); label names
# drop the colon. Everything else maps to "_".
_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")

# Label name per known dict-valued snapshot field (unknown dicts fall back
# to the generic "key" label rather than being dropped).
_DICT_LABELS = {
    "goodput_seconds": "bucket",
    "goodput_fractions": "bucket",
    "steady_fractions": "bucket",
    "anomaly_counts": "kind",
    "doctor_scores": "verdict",
}


def _metric_name(prefix: str, key: str) -> str:
    return f"{prefix}_{_NAME_OK.sub('_', str(key))}"


def _fmt(value) -> str:
    # Prometheus floats: repr round-trips exactly; bools become 0/1.
    if isinstance(value, bool):
        return "1" if value else "0"
    return repr(float(value))


def _escape(value: str) -> str:
    # Prometheus label-value escaping: backslash first, then quotes.
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def prometheus_text(snapshot: dict, *, prefix: str = "tpu_trainer") -> str:
    """Render a status snapshot as Prometheus exposition text (v0.0.4).

    Numeric scalars become ``<prefix>_<key>`` gauges; dicts of numerics
    become one labeled gauge per entry (label name from
    ``_DICT_LABELS``); string fields collapse into ONE ``<prefix>_info``
    gauge carrying them as labels (the node-exporter convention — a
    verdict is a label, not a float). Non-numeric leaves are skipped:
    the exporter must serve whatever the snapshot holds, never 500 on a
    field it does not know."""
    lines: list[str] = []
    info_labels: list[tuple[str, str]] = []
    for key in sorted(snapshot):
        value = snapshot[key]
        if isinstance(value, str):
            info_labels.append((_LABEL_OK.sub("_", key), value))
            continue
        if isinstance(value, bool) or isinstance(value, (int, float)):
            name = _metric_name(prefix, key)
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_fmt(value)}")
            continue
        if isinstance(value, dict):
            label = _DICT_LABELS.get(key, "key")
            name = _metric_name(prefix, key)
            samples = []
            for k in sorted(value, key=str):
                v = value[k]
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    samples.append(f'{name}{{{label}="{_escape(k)}"}} {_fmt(v)}')
            if samples:
                lines.append(f"# TYPE {name} gauge")
                lines.extend(samples)
    if info_labels:
        name = f"{prefix}_info"
        rendered = ",".join(f'{k}="{_escape(v)}"' for k, v in info_labels)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{{{rendered}}} 1")
    up = f"{prefix}_up"
    lines.append(f"# TYPE {up} gauge")
    lines.append(f"{up} 1")
    return "\n".join(lines) + "\n"


class StatusExporter:
    """Serve ``snapshot_fn()`` over HTTP from a daemon thread.

    ``snapshot_fn`` is called on the HTTP thread per request and must be
    cheap and read-only (the trainer passes a closure returning its
    latest atomically-swapped snapshot dict). Any exception it raises is
    answered as a 500 — never propagated into the server loop.
    """

    def __init__(
        self,
        snapshot_fn: Callable[[], dict],
        port: int,
        *,
        host: str = "0.0.0.0",
        prefix: str = "tpu_trainer",
        log=None,
    ):
        self.enabled = False
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.port = None

        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            # One training job must never die (or spam its console) for a
            # scraper's sake.
            def log_message(self, *args):  # noqa: D102 — silence stdlib logging
                pass

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
                route = self.path.split("?", 1)[0].rstrip("/") or "/status"
                try:
                    snapshot = snapshot_fn() or {}
                except Exception as e:  # noqa: BLE001 — a snapshot bug is a 500, not a crash
                    self._respond(500, "text/plain", f"snapshot failed: {e}\n")
                    return
                if route in ("/status", "/"):
                    self._respond(
                        200, "application/json",
                        json.dumps(_jsonable(snapshot)) + "\n",
                    )
                elif route == "/metrics":
                    self._respond(
                        200,
                        "text/plain; version=0.0.4; charset=utf-8",
                        prometheus_text(snapshot, prefix=exporter._prefix),
                    )
                else:
                    self._respond(404, "text/plain", "try /status or /metrics\n")

            def _respond(self, code: int, ctype: str, body: str):
                try:
                    payload = body.encode("utf-8")
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                except OSError:
                    pass  # client went away mid-response: its problem

        self._prefix = prefix
        warn = log if log is not None else _default_warn
        try:
            self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        except OSError as e:
            # The EventLog policy: a taken port (another run already
            # exporting there, a privileged port) is a warning, not a
            # reason training dies.
            warn(
                f"status exporter disabled — could not bind {host}:{port} ({e}); "
                "training continues without /status"
            )
            return
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="status-exporter",
            daemon=True,
        )
        self._thread.start()
        self.enabled = True

    def close(self) -> None:
        """Stop serving and release the port (idempotent)."""
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except OSError:
                pass
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.enabled = False

    def __enter__(self) -> "StatusExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _default_warn(msg: str) -> None:
    import warnings

    warnings.warn(msg)
