"""Structured JSONL event log — the run's flight recorder.

The framework's narrative observability so far lived in free-text log lines
(``utils/logger.py``); answering "why did the loss spike at step 12k?" or
"how many preemptions did this run survive?" meant regexing a logfile. The
event log records the run's *discrete* happenings — run start/end,
compilation, checkpoint save/restore, preemption, fault injection,
loss-scale backoff, anomaly (including ``kind="memory_growth"``, the
live-memory leak detector — the "memory anomaly"), profiling captures
(``profile_capture``: trace path, traced window, category fractions +
dispatch-gap audit, emitted by ``profiling.StepTraceCapture``), perf-gate
verdicts (``perf_gate``: measured vs baseline, tolerance, verdict, emitted
by ``scripts/perf_gate.py``), static-audit verdicts (``static_audit``:
per-rule lint counts, waiver counts, undonated param/opt-state bytes of
the single-step and chained programs, precision leaks, host callbacks,
per-mesh comm bytes + comm-audit findings and gate verdicts,
emitted by ``scripts/static_audit.py --events``), memory-preflight
verdicts (``memory_preflight``: predicted peak vs capacity, per-class
attribution, batch/microbatch/fsdp recommendations, emitted by
``memory.preflight.run_preflight`` before the first dispatch), and
resharding restores (``checkpoint_reshard``: a checkpoint whose recorded
sharding layout differs from the restore target's — mesh axes and sharded
leaf counts on both sides, emitted by ``CheckpointManager.restore``; the
DP<->FSDP elasticity path of docs/parallelism.md), and elastic restores
(``elastic_restore``: a resume that crossed a device-count change — old/new
mesh axes and device counts, old/new grad-accumulation factors, the re-plan
reason, and whether the mesh was re-planned or explicitly overridden,
emitted by the Trainer after a topology-changed restore; the N!=M elastic
path of docs/fault_tolerance.md), and run-doctor verdicts (``run_doctor``:
the ranked bottleneck diagnosis — top verdict, per-verdict severity
scores, steady-state goodput fractions — emitted by
``scripts/run_doctor.py --events``; the ``anomaly`` kind vocabulary also
includes ``straggler``, the slowest-chip-ratio detector of
``telemetry/straggler.py``), and the A/B layer's records (ISSUE 14:
``run_compare`` — an across-runs comparison's kind, clean verdict, step_ms
delta, ranked attribution rows and provenance-mismatch keys, emitted by
``scripts/run_compare.py --events``; ``bench_history`` — the
committed-rounds ledger's flat streaks and regressions, emitted by
``scripts/bench_history.py --events``), and the live-operations layer's
records (ISSUE 15: ``heartbeat`` — the liveness pulse, emitted by the
trainer at the existing ``log_every`` syncs (``source="loop"``: epoch,
``step_in_epoch``, ``units`` executed this attempt, ``step_ms``,
``live_bytes`` where sampled, and the cumulative ``goodput_seconds``
snapshot) and from the step watchdog's patrol thread between syncs
(``source="watchdog"``, plus ``since_progress_s`` — seconds since the
last completed execution unit), debounced to
``Telemetry(heartbeat_every_s=...)``; ``monitor_alert`` — a debounced
alert-rule firing from the streaming monitor (``telemetry/monitor.py``:
``rule``, ``run_dir``, ``status``, measured ``value`` vs ``threshold``,
``message``, emitted by ``scripts/run_monitor.py --events``), and the
closed-loop layer's record (ISSUE 16: ``controller_action`` — one
remediation decision by the fleet controller (``telemetry/controller.py``
via ``scripts/fleet_controller.py``): the ``action`` taken (``restart`` |
``restart_excluding`` | ``tune`` | ``keep`` | ``revert`` | ``give_up`` |
``refuse``),
the ``run_dir`` and ``attempt`` acted on, the triggering ``reason``
verdict/rule, the justifying ``evidence`` rows copied from the doctor
verdict or alert that fired, and budget state (``restarts_used`` /
``max_restarts``, ``backoff_s``); the ``fault_injection`` kind vocabulary
also gains ``slow_chip``, the deterministic degraded-chip seam of
``fault/inject.py``), and the kernel-policy layer's record (ISSUE 17:
``kernel_dispatch`` — one Pallas-vs-plain path resolution by
``ops/dispatch.py`` (``model``, ``op``, resolved ``path``
``pallas``|``plain``|``ring``, the ``reason`` including the
formerly-silent below-``FLASH_MIN_SEQ_LEN`` fall-through, and ``seq_len``
where shape-dependent), deduplicated to one record per distinct decision
per process and forwarded through the sink the Trainer installs for the
run — so a "tuned" run that quietly lost its kernels is visible to the
doctor), and the serving layer's records (ISSUE 18, emitted by
``serving/server.py`` into the SAME per-run-dir flight recorder the
monitor/controller already read: ``serve_start`` — one per server
attempt (``port``, ``buckets``, admission bounds, ``slo_p99_ms``,
``params_version``, ``mesh_axes``); ``request_batch`` — the ~1 Hz
serving summary pulse doubling as the server's liveness heartbeat
(``requests``/``batches`` since the last pulse, trailing-window ``qps``,
``p50_ms``/``p99_ms``, ``slo_ok``, ``params_version``); ``hot_swap`` —
one checkpoint hot-swap under load (``checkpoint`` name,
``from_version``/``to_version``, ``swap_ms``, ``pending_requests``);
``admission_reject`` — a typed overload rejection, debounced to one
record per tenant per second (``tenant``, ``depth`` vs ``bound``,
``rejects`` since the last record; since schema 8 also ``reason``
``overload``|``draining``|``replanning`` and the ``retry_after_s`` the
refused caller was told — the backpressure signal, derived from queue
depth and the drain deadline)), and the actuated-handshake records
(ISSUE 20, emitted by ``serving/server.py``: ``offer_accept`` /
``offer_decline`` — a replica's decision on an offered chip
(``chip``, ``reason``, its ``state``/``slo_ok``/``p99_ms``/``pending``
at decision time — a replica under SLO pressure declines);
``drain_start`` — admission stops for a drain (``deadline_s``,
``pending``, ``params_version``); ``replan_done`` — the replica is
serving again on the re-planned device set (``from_mesh``/``to_mesh``
axes, ``device_ids``, requests ``shed`` past the drain deadline,
``replan_ms``, the unchanged ``params_version``, cumulative
``replans``, the elastic solver's ``plan_reason``)), and the
streaming-data layer's records
(ISSUE 19, emitted by the Trainer for any loader speaking the
reader-state surface (``data/streaming``): ``shard_assignment`` — one per
attempt, on start and on every elastic resume (the assignment ``version``
fingerprint, ``record_count``/``shard_count``, ``global_batch_size``, this
host's ``row_lo``/``row_hi`` slice, the ``batch_extent`` it feeds, the
``resume_batch`` the cursor positions at, and ``elastic`` — whether this
attempt crossed a topology change); ``data_reader_state`` — one per
checkpoint save, the reader position a resume from that checkpoint will
consume from (``name``, resume ``epoch``, global record ``cursor``,
shuffle ``seed``, ``record_count``, ``assignment_version``)) — as one JSON
object per line,
machine-readable and append-only. Since schema 2 every record also carries ``chips`` (this
process's local device ids) and ``schema`` (:data:`SCHEMA_VERSION`), so
per-chip attribution survives elastic topology changes and consumers can
detect vocabularies they predate. Since schema 4, ``run_start`` and
``heartbeat`` records (and every ``controller_action``) also carry
``attempt`` — the monotonic per-run-dir attempt id claimed via
:func:`claim_attempt`, so one appended events.jsonl attributes each
record to the restart generation that wrote it.

Conventions:

* **Rank-0 file ownership** (the logger's multi-host convention,
  ``utils/logger.py``): only process 0 writes the file; other processes get
  a disabled no-op writer. Events are global run facts (the trainer emits
  them at points every host reaches), so one writer sees everything — and a
  shared filesystem never sees interleaved half-lines from N writers.
* **Monotonic timestamps**: every record carries ``t_mono``
  (``time.monotonic()`` — ordering-safe across NTP slews) next to ``t_wall``
  (``time.time()`` — human-correlatable). Within one process the ``t_mono``
  stream is nondecreasing by construction.
* **Append mode**: a resumed run appends to the same file, so the log shows
  the full preempt/restart history (each attempt opens with its own
  ``run_start``). Crash-safe: every record is flushed line-atomically, and
  a torn last line from a hard kill is newline-terminated on reopen so
  records never merge (``read_events(strict=False)`` audits past it).
* **Never the reason a run dies**: emit failures (disk full, permission)
  disable the log with one warning instead of raising into the step loop.
"""

from __future__ import annotations

import json
import math
import os
import socket
import threading
import time
from typing import Any, Iterator

import jax

__all__ = [
    "EventFollower",
    "EventLog",
    "SCHEMA_VERSION",
    "claim_attempt",
    "load_run_events",
    "peek_attempt",
    "read_events",
    "resolve_events_path",
]

# Record-schema version, stamped on every record as ``schema`` so offline
# consumers (the timeline exporter, the run doctor, dashboards) can detect
# a vocabulary they predate instead of misparsing it. History:
#   1 — implicit (PR 4-12 records carry no ``schema`` field);
#   2 — this field + ``chips`` identity + straggler/goodput-snapshot
#       window/epoch fields (ISSUE 13);
#   3 — the live-operations vocabulary (ISSUE 15): ``heartbeat``
#       (``source`` loop|watchdog, ``units``, ``since_progress_s``,
#       ``goodput_seconds`` snapshot — the liveness pulse) and
#       ``monitor_alert`` (``rule``, ``status``, ``value``/``threshold``
#       — a debounced monitor rule firing);
#   4 — the closed-loop vocabulary (ISSUE 16): ``attempt`` on
#       ``run_start``/``heartbeat`` (monotonic per-run-dir restart
#       generation, claimed via :func:`claim_attempt`),
#       ``controller_action`` (the fleet controller's evidenced
#       remediation decisions), and ``fault_injection``
#       ``kind="slow_chip"`` (the degraded-chip seam);
#   5 — the kernel-policy vocabulary (ISSUE 17): ``kernel_dispatch``
#       (one ops/dispatch.py Pallas-vs-plain resolution: ``model``,
#       ``op``, ``path``, ``reason``, optional ``seq_len`` — deduplicated
#       per distinct decision per process);
#   6 — the serving vocabulary (ISSUE 18): ``serve_start``,
#       ``request_batch`` (the server's liveness pulse), ``hot_swap``,
#       ``admission_reject`` (serving/server.py), and ``offer_chip``
#       joins the ``controller_action`` action vocabulary (a mixed-fleet
#       controller offering a freed chip to a serving replica);
#   7 — the streaming-data vocabulary (ISSUE 19): ``shard_assignment``
#       (one per attempt: the per-host split of the deterministic global
#       record sequence — version fingerprint, row range, batch extent,
#       resume batch) and ``data_reader_state`` (one per checkpoint save:
#       the epoch/cursor/seed a resume will consume from);
#   8 — the actuated-handshake vocabulary (ISSUE 20): ``offer_accept`` /
#       ``offer_decline`` (a serving replica's decision on an offered
#       chip), ``drain_start`` / ``replan_done`` (the graceful-drain +
#       live-re-plan cycle), ``reason``/``retry_after_s`` on
#       ``admission_reject``, and ``state``/``qps_per_chip``/
#       ``mesh_chips``/``shed_total`` on the ``request_batch`` pulse.
SCHEMA_VERSION = 8


def _jsonable(value: Any) -> Any:
    """Best-effort scalar coercion: numpy/jax scalars -> python, everything
    non-serializable -> repr (an event must never fail to serialize).

    Non-finite floats become their repr strings ("nan"/"inf"/"-inf"):
    json.dumps would otherwise emit bare ``NaN``/``Infinity`` literals —
    Python-parseable but invalid strict JSON, which jq / JSON.parse reject.
    The value (e.g. an anomaly's NaN loss) is payload, so it is preserved
    as a string rather than dropped."""
    if isinstance(value, float):
        return value if math.isfinite(value) else repr(value)
    if value is None or isinstance(value, (bool, int, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    try:
        value = float(value)  # numpy / jax 0-d scalars
    except (TypeError, ValueError):
        return repr(value)
    return value if math.isfinite(value) else repr(value)


class EventLog:
    """``EventLog(path).emit("checkpoint_save", name="last", epoch=3)``.

    ``path=None`` (or a non-zero process index) constructs a disabled no-op
    writer — the universal telemetry-off contract, mirroring
    ``utils.tensorboard.MetricsWriter``.
    """

    def __init__(self, path: str | None, *, process_index: int | None = None):
        self._path = path
        self._file = None
        self._dead = False  # a failed write disables the log permanently
        proc = jax.process_index() if process_index is None else process_index
        self.process = proc
        self.enabled = path is not None and proc == 0
        self._host = socket.gethostname()
        # Chip identity (ISSUE 13): the local device ids this process owns,
        # as one compact string stamped on every record — so per-chip
        # attribution (straggler skew, memory skew) stays coherent across
        # an elastic N->M resume, where the SAME appended log suddenly
        # describes a different topology. Resolved lazily at the first
        # enabled emit: a disabled log (telemetry off / non-zero rank) must
        # not force jax backend initialization beyond what the
        # process_index read above already did.
        self._chips: str | None = None
        # Emits may come from the async-checkpoint commit worker as well as
        # the main thread; timestamping AND writing under one lock keeps the
        # file's t_mono stream nondecreasing (two threads reading the clock
        # then writing in the other order would interleave otherwise).
        self._emit_lock = threading.Lock()

    def _open(self):
        if self._file is None:
            os.makedirs(os.path.dirname(os.path.abspath(self._path)), exist_ok=True)
            # Torn-last-line repair: a hard kill (SIGKILL, power loss) can
            # leave a partial record with no trailing newline; appending the
            # resumed run's first event onto it would merge two records into
            # one unparseable line. Terminate the fragment first — it stays
            # in the log as its own (malformed) line marking the crash.
            try:
                with open(self._path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    torn = f.read(1) != b"\n"
            except (OSError, ValueError):  # missing or empty file
                torn = False
            self._file = open(self._path, "a", encoding="utf-8")
            if torn:
                self._file.write("\n")
        return self._file

    def emit(self, event: str, **fields) -> dict | None:
        """Append one event record; returns the record dict (or None when
        disabled). Field values are coerced to JSON-safe scalars."""
        if not self.enabled or self._dead:
            return None
        if self._chips is None:
            try:
                self._chips = ",".join(str(d.id) for d in jax.local_devices())
            except RuntimeError:
                self._chips = ""  # backend unavailable: identity degrades, log lives
        with self._emit_lock:
            record = {
                "event": str(event),
                "t_wall": time.time(),
                "t_mono": time.monotonic(),
                "process": self.process,
                "host": self._host,
                "pid": os.getpid(),
                "chips": self._chips,
                "schema": SCHEMA_VERSION,
            }
            for key, value in fields.items():
                record[str(key)] = _jsonable(value)
            try:
                f = self._open()
                f.write(json.dumps(record) + "\n")
                f.flush()
            except OSError as e:
                # Telemetry must never kill training: disable and move on.
                self._dead = True
                import warnings

                warnings.warn(f"EventLog disabled — write to {self._path!r} failed: {e}")
                return None
        return record

    def close(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None  # a later emit() lazily reopens (append mode)

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _parse_tolerant(raw: bytes | str, lineno: int, path: str) -> dict | None:
    """Parse ONE event-log line the tolerant way (the post-crash-audit
    contract of ``read_events(strict=False)``): blank lines skip silently,
    malformed JSON (a torn fragment from a hard kill, a corrupted write)
    skips with a warning naming the file line, and only dict records
    survive (a bare JSON scalar cannot carry an ``event`` field and would
    crash every consumer downstream)."""
    if isinstance(raw, bytes):
        try:
            raw = raw.decode("utf-8")
        except UnicodeDecodeError as e:
            import warnings

            warnings.warn(f"{path}:{lineno}: skipping undecodable event line: {e}")
            return None
    line = raw.strip()
    if not line:
        return None
    try:
        record = json.loads(line)
    except json.JSONDecodeError as e:
        import warnings

        warnings.warn(f"{path}:{lineno}: skipping malformed event line: {e}")
        return None
    if not isinstance(record, dict):
        import warnings

        warnings.warn(
            f"{path}:{lineno}: skipping non-object event line ({type(record).__name__})"
        )
        return None
    return record


def resolve_events_path(run_dir: str) -> str:
    """Map a run directory (the Trainer ``save_folder``) to its event-log
    path — or pass a direct ``.jsonl``/existing-file path through. The
    ONE layout rule (``<save_folder>/telemetry/events.jsonl``) shared by
    the timeline exporter, the run doctor, and the live monitor.

    Resolution is by suffix/file-ness rather than ``isdir``: a monitor is
    deliberately allowed to attach BEFORE the run creates its directory
    (the EventFollower yields ``[]`` until the first emit), and an
    isdir-based rule would freeze a not-yet-existing run dir into a
    direct-file path that never resolves."""
    if run_dir.endswith(".jsonl") or os.path.isfile(run_dir):
        return run_dir
    return os.path.join(run_dir, "telemetry", "events.jsonl")


def _attempt_path(run_dir: str) -> str:
    """Sidecar path of the attempt counter: next to events.jsonl, NOT inside
    it — the counter must survive (and be readable before) any event emit,
    and a controller process must read it without tailing the log."""
    return os.path.join(run_dir, "telemetry", "attempt")


def peek_attempt(run_dir: str) -> int:
    """The last attempt id claimed for ``run_dir`` (0 when none yet).
    Stdlib-only and side-effect-free — safe from a supervising controller."""
    try:
        with open(_attempt_path(run_dir), encoding="utf-8") as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def claim_attempt(run_dir: str) -> int:
    """Claim the next monotonic attempt id for ``run_dir`` (1, 2, 3, ...).

    Called once per trainer process at run start (rank 0, telemetry on);
    the id is stamped on that attempt's ``run_start``/``heartbeat`` records
    and into checkpoint meta, so one appended events.jsonl — and the
    checkpoints it describes — attribute every record to the restart
    generation that wrote it (ISSUE 16). The write is tmp + ``os.replace``
    so a crash mid-claim never leaves a torn counter; restarts are
    serialized by the supervisor (a run dir has at most one live trainer),
    so no cross-process lock is needed."""
    sidecar = _attempt_path(run_dir)
    os.makedirs(os.path.dirname(sidecar), exist_ok=True)
    attempt = peek_attempt(run_dir) + 1
    tmp = sidecar + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:  # jaxlint: disable=file-write-without-rank-gate -- call site is process_index()==0-gated in train(); the gate lives with the Telemetry rank check, not in this stdlib helper
        f.write(f"{attempt}\n")
    os.replace(tmp, sidecar)
    return attempt


class EventFollower:
    """Incremental, torn-line-tolerant reader over one events.jsonl file —
    THE shared parser behind :func:`load_run_events` (the one-shot
    consumers: timeline exporter, run doctor) and the live monitor's tail
    (``telemetry/monitor.py``), so the two cannot drift (ISSUE 15).

    Each :meth:`poll` returns the records whose lines became COMPLETE
    (newline-terminated) since the last poll, each stamped with ``_line``
    (the 1-based FILE line — blank and malformed lines still advance it,
    so citations stay stable past the lines the tolerant parse skipped).
    A trailing fragment with no newline is *withheld*, not rejected: a
    live writer may still be mid-``write`` on it, and the next poll picks
    it up once the newline lands. ``poll(final=True)`` — for post-mortem
    reads, where no more bytes are coming — additionally parses the
    unterminated tail (a complete record whose writer died before the
    newline is data; a torn fragment warns and skips, exactly like
    ``read_events(strict=False)``).

    A file that does not exist yet yields ``[]`` (the monitor may attach
    before the run's first emit); a file that SHRANK (a fresh attempt
    truncating, a rotation) resets the cursor and re-reads from the top —
    stale offsets must never silently hide a restarted run's records.
    """

    def __init__(self, path: str):
        self.path = path
        self._offset = 0  # bytes consumed through the last complete line
        self._lineno = 0  # 1-based count of completed lines seen
        self._partial = b""  # unterminated tail carried between polls
        self._tail_emitted: bytes | None = None  # tail a final poll yielded
        # Bumped on every truncation reset, so a stateful consumer (the
        # monitor's Signals fold) knows its accumulated state describes a
        # file that no longer exists and must be rebuilt.
        self.generation = 0

    def poll(self, *, final: bool = False) -> list[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []  # not written yet (or vanished): nothing to report
        if size < self._offset:
            # Truncated/rotated underneath us: start over from the top.
            self._offset = 0
            self._lineno = 0
            self._partial = b""
            self._tail_emitted = None
            self.generation += 1
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                data = f.read()
        except OSError:
            return []
        self._offset += len(data)
        chunks = (self._partial + data).split(b"\n")
        self._partial = chunks.pop()  # b"" when the data ended on a newline
        records = []
        for raw in chunks:
            self._lineno += 1
            if self._tail_emitted is not None:
                # A prior final poll already yielded this exact tail; its
                # newline landing now must not re-yield it (a monitor that
                # declared a stalled writer dead, then saw it resurrect).
                already, self._tail_emitted = raw == self._tail_emitted, None
                if already:
                    continue
            rec = _parse_tolerant(raw, self._lineno, self.path)
            if rec is not None:
                rec["_line"] = self._lineno
                records.append(rec)
        if final and self._partial.strip() and self._partial != self._tail_emitted:
            # Parse the unterminated tail WITHOUT consuming it: offset,
            # line counter, and buffer stay put, so a writer that was only
            # stalled (not dead) and later completes the line is read
            # normally — no lost record, no drifted _line citations. A
            # complete record missing only its newline is remembered in
            # _tail_emitted so the newline's eventual arrival dedupes.
            rec = _parse_tolerant(self._partial, self._lineno + 1, self.path)
            if rec is not None:
                rec["_line"] = self._lineno + 1
                records.append(rec)
                self._tail_emitted = self._partial
        return records


def load_run_events(run_dir: str) -> list[dict]:
    """Read a run directory's (or a direct ``.jsonl`` path's) event log,
    tolerant of a torn last line (post-crash audits are a primary
    consumer). Each record gains a ``_line`` field — the 1-based position
    in the file — so doctor evidence and timeline args can cite it.

    One shot through the SAME :class:`EventFollower` the live monitor
    tails with (``final=True``: the unterminated tail of a killed writer
    is parsed rather than withheld) — the batch load IS the follower run
    to completion, so the two read paths cannot drift."""
    path = resolve_events_path(run_dir)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"no event log at {path} — was the run telemetry-off? "
            "(Trainer(telemetry='on') writes <save_folder>/telemetry/events.jsonl)"
        )
    return EventFollower(path).poll(final=True)


def read_events(
    path: str, *, strict: bool = True, with_lineno: bool = False
) -> Iterator[dict]:
    """Parse an event log back into dicts — the test/smoke-side consumer.

    ``strict=True`` (default) raises ``ValueError`` naming the offending
    line on malformed JSONL — the CI-gate behavior, where a bad line means
    the writer regressed. ``strict=False`` skips malformed lines with a
    warning — for post-crash audits, where a torn fragment from a hard kill
    (see ``EventLog._open``'s repair) is expected and the surviving record
    stream is the point. ``with_lineno=True`` yields ``(lineno, record)``
    pairs instead — the 1-based FILE line, which a consumer citing lines
    (the run doctor's evidence rows) needs: a yielded-record index drifts
    past every blank/torn line the tolerant mode just skipped."""
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            if not strict:
                # The ONE tolerant parse (shared with EventFollower).
                record = _parse_tolerant(line, lineno, path)
                if record is not None:
                    yield (lineno, record) if with_lineno else record
                continue
            try:
                record = json.loads(line)
                yield (lineno, record) if with_lineno else record
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: malformed event line: {e}"
                ) from e
