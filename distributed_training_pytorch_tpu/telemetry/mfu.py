"""MFU and roofline fields from compiled-program cost analysis + step time.

Model-FLOPs utilization (the PaLM-paper run metric) is FLOPs-per-second
achieved over the chip's peak: ``flops_per_step / step_time / peak``. The
FLOP numerator can come from three conventions (see ``bench.py``'s module
doc): the analytic layer-formula count, the HLO conv/dot recount
(``utils.hlo_flops.executed_matmul_flops``), or XLA's own
``cost_analysis()``. This module owns the shared pieces — the per-chip peak
table and the ratio — used by both ``bench.py`` (which assembles its three
conventions with measurement-specific rescale guards) and the ``Trainer``'s
telemetry (the ``TrainEngine.step_cost_analysis`` probe, reported per
chained window via :func:`window_report`).
"""

from __future__ import annotations

__all__ = [
    "PEAK_FLOPS",
    "device_peak_flops",
    "mfu_value",
    "throughput_fields",
    "window_report",
]

# bf16 peak FLOP/s per chip, by PJRT device_kind substring (the table
# bench.py's MFU headline has always used; "cpu" is a nominal stand-in so
# smoke runs produce finite — clearly synthetic — utilization numbers).
PEAK_FLOPS = {
    "v5 lite": 197e12,  # v5e litepod chip (197 bf16 TFLOP/s)
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,
    "cpu": 1e12,  # nominal, for smoke runs
}


def device_peak_flops(device) -> float:
    """Peak bf16 FLOP/s of one device, by ``device_kind`` substring match
    (1e12 nominal fallback for unknown kinds)."""
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return 1e12


def mfu_value(flops_per_step: float, step_time_s: float, peak_flops: float) -> float | None:
    """``flops / dt / peak`` with the degenerate cases mapped to None (no
    FLOPs known / zero time / zero peak -> no utilization claim)."""
    if not flops_per_step or not step_time_s or not peak_flops:
        return None
    return float(flops_per_step) / float(step_time_s) / float(peak_flops)


def throughput_fields(items_per_sec: float, mesh) -> dict:
    """Per-chip AND per-replica throughput for a mesh run (ISSUE 10).

    On a pure-DP mesh the two divisors agree and per-chip is the whole
    story. On a sharded mesh they do not: ``data=2, tensor=4`` runs TWO
    batch replicas on 8 chips, so dividing by ``mesh.devices.size`` alone
    makes a healthy TP config look 4x slower than DP at identical
    hardware efficiency. The scale-out figure is per batch REPLICA — the
    batch-sharded axes product (``parallel.mesh.batch_shard_extent``),
    data x fsdp, never the raw device count."""
    from distributed_training_pytorch_tpu.parallel.mesh import batch_shard_extent

    n_devices = int(mesh.devices.size)
    replicas = batch_shard_extent(mesh)
    return {
        "items_per_sec_chip": float(items_per_sec) / max(n_devices, 1),
        "items_per_sec_replica": float(items_per_sec) / max(replicas, 1),
        "batch_replicas": replicas,
    }


def window_report(
    steps: int,
    window_time_s: float,
    *,
    flops_per_step: float | None,
    peak_flops: float,
) -> dict:
    """Per-window telemetry fields from measured wall time: ``steps``,
    ``step_ms``, and ``mfu`` when a FLOP count is known (the trainer's
    ``step_cost_analysis`` probe or an explicit ``Telemetry(flops_per_step=
    ...)``). A "window" is whatever interval the caller timed — under
    chained execution the trainer's sync points land on window boundaries,
    so the report covers whole windows."""
    steps = max(int(steps), 1)
    step_s = window_time_s / steps
    out = {"steps": steps, "step_ms": step_s * 1e3}
    mfu = mfu_value(flops_per_step or 0.0, step_s, peak_flops)
    if mfu is not None:
        out["mfu"] = mfu
    return out
