"""Telemetry subsystem (ISSUE 4): unified run observability.

Production training treats goodput and MFU as first-class run metrics; this
package assembles the raw ingredients the other subsystems already produce
(``utils.hlo_flops`` cost analysis, ``TrainEngine.trace_counts``, fault /
preemption events, loss-scale state) into one surface:

* :mod:`~.events`  — structured JSONL event log (run start/end, compile,
  checkpoint save/restore, preemption, fault injection, loss-scale backoff,
  anomaly) with monotonic timestamps and rank-0 file ownership;
* :mod:`~.goodput` — wall time partitioned into productive-step / compile /
  data-wait / checkpoint / restart-rollback buckets, cumulative across
  kill/resume (counters ride checkpoint meta);
* :mod:`~.stats`   — on-device train-health statistics (grad/param norm,
  update ratio, nonfinite flag) computed inside the compiled step: zero
  extra host syncs, zero retraces, bit-exact chained windows;
* :mod:`~.mfu`     — MFU + roofline fields from cost analysis and measured
  step time, shared by ``bench.py`` and the trainer's per-window reports;
* :mod:`~.anomaly` — host-side detectors (loss spike / grad explosion /
  step-time regression / memory growth / straggler) that run only at
  existing sync points;
* :mod:`~.straggler` — per-chip arrival-skew sampling at the ``log_every``
  syncs (the PR 8 live-memory-skew pattern applied to time), feeding the
  ``straggler`` anomaly kind and the doctor's attribution (ISSUE 13);
* :mod:`~.timeline` — merges a run directory's event log into one
  Chrome/Perfetto trace (windows, epochs, the goodput partition as spans,
  checkpoint snapshot/commit lifecycles with the async committer as its
  own track, profile captures, narrative markers);
* :mod:`~.doctor`   — the ranked bottleneck diagnosis (compile-bound /
  data-bound / checkpoint-stall / straggler / comm-heavy / healthy) shared
  by ``scripts/run_doctor.py`` and the epoch-end ``doctor/*`` scalars;
* :mod:`~.provenance` — the ONE provenance record (git SHA, jax/jaxlib,
  ``XLA_FLAGS``, mesh/dtype/chain_steps) stamped on bench lines, dryrun
  entries, and ``run_start`` events so comparisons are attributable
  (ISSUE 14);
* :mod:`~.history`  — the committed ``BENCH_r*``/``MULTICHIP_r*`` rounds as
  per-metric trajectories with flat-streak + regression detection
  (``scripts/bench_history.py``; the r02→r05 plateau is the self-test);
* :mod:`~.monitor`  — the live-operations layer (ISSUE 15): a streaming
  doctor tailing events.jsonl through the shared
  :class:`~.events.EventFollower`, re-deriving the doctor's verdicts
  online plus the liveness kinds (``stale_heartbeat``/``dead`` from the
  heartbeat contract), with debounced :class:`~.monitor.AlertConfig`
  rules (``scripts/run_monitor.py``: live view, fleet table, CI exit
  codes);
* :mod:`~.exporter` — the in-process rank-0 HTTP status endpoint
  (``Telemetry(export_port=...)``): ``/status`` JSON + ``/metrics``
  Prometheus text served from atomically-swapped snapshots of the live
  trainer counters — never blocks the hot loop, degrades to a warning
  when the port is taken;
* :mod:`~.controller` — the closed-loop policy engine (ISSUE 16): per-run
  state machines turning :class:`~.monitor.MonitorStatus` streams into a
  bounded, debounced, budgeted remediation-action catalog (restart /
  exclude-and-replan / knob tune with an A/B-judged keep-or-revert),
  executed and audited by ``scripts/fleet_controller.py``.

Wire-up: ``Trainer(telemetry="on")`` (or a :class:`Telemetry` instance for
knobs); entries honor ``TELEMETRY=1``; see ``docs/observability.md``.
"""

from __future__ import annotations

import dataclasses

from distributed_training_pytorch_tpu.telemetry.anomaly import (  # noqa: F401
    Anomaly,
    AnomalyDetector,
    AnomalyError,
)
from distributed_training_pytorch_tpu.telemetry.events import (  # noqa: F401
    SCHEMA_VERSION,
    EventFollower,
    EventLog,
    load_run_events,
    read_events,
)
from distributed_training_pytorch_tpu.telemetry.goodput import (  # noqa: F401
    BUCKETS,
    GoodputMeter,
)
from distributed_training_pytorch_tpu.telemetry.mfu import (  # noqa: F401
    PEAK_FLOPS,
    device_peak_flops,
    mfu_value,
    window_report,
)
from distributed_training_pytorch_tpu.telemetry.stats import (  # noqa: F401
    STAT_KEYS,
    train_health_stats,
)

__all__ = [
    "Anomaly",
    "AnomalyDetector",
    "AnomalyError",
    "BUCKETS",
    "EventFollower",
    "EventLog",
    "GoodputMeter",
    "PEAK_FLOPS",
    "SCHEMA_VERSION",
    "STAT_KEYS",
    "Telemetry",
    "device_peak_flops",
    "load_run_events",
    "mfu_value",
    "read_events",
    "resolve_telemetry",
    "train_health_stats",
    "window_report",
]

# timeline/doctor/straggler/history/provenance are imported as submodules on demand
# (``from distributed_training_pytorch_tpu.telemetry import timeline``) —
# the trainer hot path must not pay their import, and the package root
# stays import-light for the historical program.


@dataclasses.dataclass
class Telemetry:
    """The ``Trainer(telemetry=...)`` configuration bundle.

    * ``events_path``    — JSONL event-log path (None = the trainer default,
      ``<save_folder>/telemetry/events.jsonl``);
    * ``stats``          — on-device train-health stats in every step's
      metrics (``telemetry.stats``);
    * ``goodput``        — wall-time bucket accounting + checkpoint carry;
    * ``mfu``            — per-window MFU. When ``flops_per_step`` is None
      the trainer probes XLA's per-step FLOP estimate once via
      ``TrainEngine.step_cost_analysis`` at the end of the first trained
      epoch — one extra (off-hot-path) XLA compile that never touches the
      dispatch executables or their trace counts;
    * ``flops_per_step`` — analytic per-step FLOP override (skips the probe;
      e.g. ``bench.vgg16_train_flops_per_image(model, size) * batch``);
    * ``anomaly``        — ``"warn"`` (default) | ``"raise"`` | ``None`` |
      an :class:`AnomalyDetector` instance with custom thresholds;
    * ``memory``         — live device-memory fields (``live_bytes`` /
      ``peak_bytes`` from ``memory.live``, plus per-chip skew on multi-chip
      hosts) on the per-window records, read at the existing ``log_every``
      host syncs (a PJRT allocator query — zero extra device syncs), and
      fed to the anomaly detector's ``memory_growth`` leak check. Degrades
      to absent fields on backends without ``memory_stats`` (CPU);
    * ``straggler``      — per-chip arrival-skew fields
      (``chip_wall_ms_min/max``, ``chip_skew_ms``, ``slowest_chip``,
      ``straggler_ratio`` from ``telemetry.straggler``) on the per-window
      records, sampled at the same ``log_every`` host syncs (the sync was
      about to block on every chip anyway — zero extra device syncs), and
      fed to the anomaly detector's floor-baselined ``straggler`` check.
      Degrades to absent fields on single-chip hosts.
    * ``heartbeat_every_s`` — the liveness pulse (ISSUE 15,
      docs/observability.md "Live monitoring"): a cheap ``heartbeat``
      record at the existing ``log_every`` syncs and — when the
      ``step_timeout`` watchdog is armed — from its patrol thread between
      syncs, debounced to this cadence so an external monitor can tell
      *training / hung / dead* apart from file mtime + record content
      alone. ``0`` disables heartbeats (the pre-ISSUE-15 record stream).
    * ``export_port``    — rank-0 in-process HTTP status endpoint
      (``telemetry.exporter``): ``/status`` JSON and ``/metrics``
      Prometheus text from the live trainer counters. ``None`` (default)
      serves nothing; a taken port degrades to a warning, and the run
      stays bit-exact (params + trace_counts) with the exporter off
      (test-enforced). ``0`` binds an ephemeral port (tests) —
      ``trainer.exporter.port`` reads it back.
    """

    events_path: str | None = None
    stats: bool = True
    goodput: bool = True
    mfu: bool = True
    flops_per_step: float | None = None
    anomaly: AnomalyDetector | str | None = "warn"
    memory: bool = True
    straggler: bool = True
    heartbeat_every_s: float = 30.0
    export_port: int | None = None

    def resolve_anomaly(self) -> AnomalyDetector | None:
        if self.anomaly is None:
            return None
        if isinstance(self.anomaly, AnomalyDetector):
            return self.anomaly
        return AnomalyDetector(action=str(self.anomaly))


def resolve_telemetry(spec) -> Telemetry | None:
    """Trainer-knob resolution: ``None``/``False`` = off (the historical
    program, byte-for-byte); ``True``/``"on"``/``"1"`` = defaults; a
    :class:`Telemetry` instance passes through."""
    if spec is None or spec is False:
        return None
    if spec is True:
        return Telemetry()
    if isinstance(spec, str):
        key = spec.lower()
        if key in ("on", "1", "true", "default"):
            return Telemetry()
        if key in ("off", "0", "false", "none"):
            return None
        raise ValueError(f"unknown telemetry spec {spec!r} (use 'on', 'off', or a Telemetry)")
    if isinstance(spec, Telemetry):
        return spec
    raise TypeError(f"telemetry must be None, bool, str, or Telemetry, got {type(spec)}")
