"""Bench-history ledger (ISSUE 14): the committed rounds as trajectories.

The repo commits its own benchmark record — ``BENCH_r*.json`` /
``MULTICHIP_r*.json``, one file per round, each carrying the bench's JSON
line(s) — but nothing ever *read* it: BENCH r02→r05 sat flat at ~76.85 ms /
``mfu_exec`` 0.49 for four consecutive rounds and no instrument noticed,
because every instrument looked at one run. This module ingests the
committed rounds into per-metric trajectories and runs two detectors over
them:

* **flat streak** — ``min_rounds`` consecutive rounds whose values all sit
  within a relative band (spread/mean <= ``rel_tol``). A plateau is the
  signature of perf work not landing (the motivating r02→r05 case — the
  committed files are this module's own self-test,
  ``scripts/bench_history.py --self-test``). Boundary semantics are exact:
  ``min_rounds - 1`` flat rounds stay quiet, ``min_rounds`` fire.
* **regression** — a round-over-round move beyond tolerance in the *bad*
  direction for metrics whose direction is known (``step_ms`` up = bad,
  ``value``/``mfu*`` down = bad; unknown fields are tracked but never
  accused).

Each entry also carries its provenance record when present (ISSUE 14
stamping — pre-stamping committed rounds simply have none), and the ledger
notes consecutive entries whose provenance *configuration* diverged
(``telemetry.provenance.differing_keys``): a trajectory that silently
changed dtype mid-history is not one trajectory.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re

from distributed_training_pytorch_tpu.telemetry.provenance import differing_keys

__all__ = [
    "BenchEntry",
    "FLAT_MIN_ROUNDS",
    "FLAT_REL_TOL",
    "HistoryReport",
    "LOWER_IS_BETTER",
    "HIGHER_IS_BETTER",
    "Regression",
    "Streak",
    "analyze_history",
    "detect_flat_streaks",
    "detect_regressions",
    "load_bench_rounds",
    "load_round_file",
    "trajectories",
]

_ROUND_RE = re.compile(r"(BENCH|MULTICHIP)_r(\d+)\.json$")

# Defaults calibrated on the motivating plateau: r02-r05 spread 1.4% on
# both value and step_ms -> inside the 2% band; four rounds is the streak
# that actually happened and the shortest one worth an alarm.
FLAT_REL_TOL = 0.02
FLAT_MIN_ROUNDS = 4
REGRESSION_REL_TOL = 0.05

# Direction vocabulary for regression detection. Fields outside both sets
# are tracked (trajectory + flat detection) but never called a regression.
LOWER_IS_BETTER = frozenset({
    "step_ms", "trainer_step_ms", "dispatch_gap_ms", "step_ms_dispatch",
    "comm_bytes_per_step", "chip_skew_ms", "save_stall_ms",
    "predicted_peak_bytes", "live_bytes", "peak_bytes",
    "goodput.data_wait", "goodput.checkpoint", "goodput.other",
})
HIGHER_IS_BETTER = frozenset({
    "value", "vs_baseline", "mfu", "mfu_exec", "mfu_xla",
    "device_busy_frac", "goodput.productive_step",
    "e2e_images_per_sec", "items_per_sec_per_replica",
})

# Top-level fields that are identity/config, not measurements.
_NON_METRIC_FIELDS = frozenset({
    "batch", "n", "rc", "steps", "oom", "trainer_chain_steps", "schema",
})


@dataclasses.dataclass
class BenchEntry:
    """One bench JSON line of one committed round."""

    kind: str  # "bench" | "multichip"
    round: int
    source: str  # file path
    fields: dict

    @property
    def series_label(self) -> str:
        """The trajectory this entry belongs to: metric name + the config
        facets a sweep varies (dtype, mesh). Two entries with the same
        label across rounds are comparable points on one line.

        A facet value the metric string already embeds is NOT repeated:
        bench's image metrics name their dtype ("... bf16)"), and the
        explicit ``dtype`` field only appeared mid-history (ISSUE 3) — a
        redundant facet would split the headline trajectory at the round
        that introduced the field, hiding exactly the across-rounds
        comparisons the ledger exists for."""
        parts = [str(self.fields.get("metric", "?"))]
        for facet in ("dtype", "mesh"):
            value = self.fields.get(facet)
            if value and str(value) not in parts[0]:
                parts.append(f"{facet}={value}")
        return " | ".join(parts)

    @property
    def provenance(self) -> "dict | None":
        prov = self.fields.get("provenance")
        return prov if isinstance(prov, dict) else None

    def numeric_fields(self) -> dict[str, float]:
        """The trackable measurements: numeric top-level fields (identity/
        config keys excluded) + goodput bucket fractions flattened as
        ``goodput.<bucket>``."""
        out: dict[str, float] = {}
        for key, value in self.fields.items():
            if key in _NON_METRIC_FIELDS or key == "provenance":
                continue
            if isinstance(value, bool):
                continue
            if isinstance(value, (int, float)):
                out[key] = float(value)
            elif key == "goodput" and isinstance(value, dict):
                for bucket, frac in value.items():
                    if isinstance(frac, (int, float)) and not isinstance(frac, bool):
                        out[f"goodput.{bucket}"] = float(frac)
        return out


def load_round_file(path: str) -> list[BenchEntry]:
    """Parse one committed round file into its bench entries. The harness
    wraps the bench's stdout: every JSON-parseable line of ``tail`` that
    carries a ``metric`` key is an entry (sweeps emit several); the
    pre-parsed ``parsed`` dict is the fallback when the tail yields none
    (and for MULTICHIP files whose tail is mesh-sweep noise)."""
    m = _ROUND_RE.search(os.path.basename(path))
    if m is None:
        raise ValueError(f"{path}: not a BENCH_r*/MULTICHIP_r* round file")
    kind = "bench" if m.group(1) == "BENCH" else "multichip"
    rnd = int(m.group(2))
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries: list[BenchEntry] = []
    for line in str(data.get("tail") or "").splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            entries.append(BenchEntry(kind=kind, round=rnd, source=path, fields=rec))
    if not entries and isinstance(data.get("parsed"), dict):
        entries.append(
            BenchEntry(kind=kind, round=rnd, source=path, fields=data["parsed"])
        )
    return entries


def load_bench_rounds(root: str) -> list[BenchEntry]:
    """Every entry of every committed round under ``root``, round-ordered."""
    entries: list[BenchEntry] = []
    for pattern in ("BENCH_r*.json", "MULTICHIP_r*.json"):
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            entries.extend(load_round_file(path))
    entries.sort(key=lambda e: (e.kind, e.series_label, e.round))
    return entries


def trajectories(entries: list[BenchEntry]) -> dict[str, list[tuple[int, float]]]:
    """``"<series label> :: <field>" -> [(round, value), ...]`` over every
    numeric field of every entry, round-ordered. One key = one line a
    dashboard (or the flat detector) can follow across rounds."""
    out: dict[str, list[tuple[int, float]]] = {}
    for entry in entries:
        for field, value in entry.numeric_fields().items():
            out.setdefault(f"{entry.series_label} :: {field}", []).append(
                (entry.round, value)
            )
    for points in out.values():
        points.sort(key=lambda p: p[0])
    return out


@dataclasses.dataclass
class Streak:
    """A flat plateau: consecutive rounds whose values sit in one band."""

    series: str
    rounds: list  # the round numbers, in order
    values: list
    spread: float  # (max - min) / mean over the streak

    def to_dict(self) -> dict:
        return {
            "series": self.series,
            "rounds": list(self.rounds),
            "values": [round(v, 4) for v in self.values],
            "spread": round(self.spread, 4),
        }

    def describe(self) -> str:
        return (
            f"FLAT r{self.rounds[0]:02d}->r{self.rounds[-1]:02d} "
            f"({len(self.rounds)} rounds, spread {100 * self.spread:.1f}%): "
            f"{self.series} ~ {sum(self.values) / len(self.values):.4g}"
        )


@dataclasses.dataclass
class Regression:
    """One bad-direction round-over-round move past tolerance."""

    series: str
    round_before: int
    round_after: int
    before: float
    after: float
    change: float  # signed relative change (after/before - 1)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def describe(self) -> str:
        return (
            f"REGRESSION r{self.round_before:02d}->r{self.round_after:02d}: "
            f"{self.series} {self.before:.4g} -> {self.after:.4g} "
            f"({100 * self.change:+.1f}%)"
        )


def _spread(values: list[float]) -> float:
    mean = sum(values) / len(values)
    if mean == 0:
        return 0.0 if max(values) == min(values) else float("inf")
    return (max(values) - min(values)) / abs(mean)


def detect_flat_streaks(
    points: list[tuple[int, float]],
    *,
    series: str = "",
    rel_tol: float = FLAT_REL_TOL,
    min_rounds: int = FLAT_MIN_ROUNDS,
) -> list[Streak]:
    """Maximal flat windows of one trajectory. A window is flat when its
    value spread relative to its mean is <= ``rel_tol``; a maximal flat
    window of at least ``min_rounds`` points fires (exactly ``min_rounds -
    1`` stays quiet — the boundary the tests pin). Overlapping flat windows
    collapse to the maximal ones (two-pointer sweep)."""
    if min_rounds < 2:
        raise ValueError(f"min_rounds must be >= 2, got {min_rounds}")

    def _streak(window: list[tuple[int, float]]) -> Streak:
        return Streak(
            series=series,
            rounds=[r for r, _ in window],
            values=[v for _, v in window],
            spread=_spread([v for _, v in window]),
        )

    out: list[Streak] = []
    start = 0
    for end in range(len(points)):
        if _spread([v for _, v in points[start:end + 1]]) <= rel_tol:
            continue  # still flat through `end`: keep extending
        # `end` broke the band: the window ending at end-1 was maximal.
        # Record it ONCE (shrinking further would re-report its suffixes),
        # then advance start until `end` fits a band again.
        if end - start >= min_rounds:
            out.append(_streak(points[start:end]))
        while start < end and _spread([v for _, v in points[start:end + 1]]) > rel_tol:
            start += 1
    if len(points) - start >= min_rounds:
        out.append(_streak(points[start:]))
    return out


def detect_regressions(
    points: list[tuple[int, float]],
    field: str,
    *,
    series: str = "",
    rel_tol: float = REGRESSION_REL_TOL,
) -> list[Regression]:
    """Round-over-round bad-direction moves past ``rel_tol`` for fields
    whose direction is known (:data:`LOWER_IS_BETTER` /
    :data:`HIGHER_IS_BETTER`); unknown fields return no findings."""
    if field in LOWER_IS_BETTER:
        bad = lambda change: change > rel_tol  # noqa: E731 — tiny direction predicate
    elif field in HIGHER_IS_BETTER:
        bad = lambda change: change < -rel_tol  # noqa: E731
    else:
        return []
    out = []
    for (r0, v0), (r1, v1) in zip(points, points[1:], strict=False):
        if v0 == 0:
            continue
        change = v1 / v0 - 1.0
        if bad(change):
            out.append(Regression(
                series=series, round_before=r0, round_after=r1,
                before=v0, after=v1, change=change,
            ))
    return out


@dataclasses.dataclass
class HistoryReport:
    """The ledger: every trajectory + every detection over one repo root."""

    entries: list
    series: dict  # trajectories() output
    streaks: list
    regressions: list
    provenance_breaks: list  # [(series_label, round_a, round_b, keys)]

    def to_dict(self) -> dict:
        return {
            "rounds": sorted({e.round for e in self.entries}),
            "entries": len(self.entries),
            "series": {
                k: [[r, v] for r, v in pts] for k, pts in sorted(self.series.items())
            },
            "streaks": [s.to_dict() for s in self.streaks],
            "regressions": [r.to_dict() for r in self.regressions],
            "provenance_breaks": [
                {"series": s, "round_before": a, "round_after": b, "keys": keys}
                for s, a, b, keys in self.provenance_breaks
            ],
        }

    def describe(self) -> str:
        lines = [
            f"bench history: {len(self.entries)} entries across "
            f"{len({e.round for e in self.entries})} round(s), "
            f"{len(self.series)} tracked series"
        ]
        for finding in self.streaks:
            lines.append("  " + finding.describe())
        for finding in self.regressions:
            lines.append("  " + finding.describe())
        for series, a, b, keys in self.provenance_breaks:
            lines.append(
                f"  PROVENANCE r{a:02d}->r{b:02d}: {series} changed "
                f"{', '.join(keys)} — not one trajectory across that edge"
            )
        if len(lines) == 1:
            lines.append("  no flat streaks or regressions detected")
        return "\n".join(lines)


def analyze_history(
    root: str,
    *,
    flat_tol: float = FLAT_REL_TOL,
    flat_min_rounds: int = FLAT_MIN_ROUNDS,
    regression_tol: float = REGRESSION_REL_TOL,
) -> HistoryReport:
    """Ingest + detect over one repo root's committed rounds."""
    entries = load_bench_rounds(root)
    series = trajectories(entries)
    streaks: list[Streak] = []
    regressions: list[Regression] = []
    for key, points in sorted(series.items()):
        field = key.rsplit(" :: ", 1)[-1]
        streaks.extend(detect_flat_streaks(
            points, series=key, rel_tol=flat_tol, min_rounds=flat_min_rounds,
        ))
        regressions.extend(detect_regressions(
            points, field, series=key, rel_tol=regression_tol,
        ))
    # Provenance breaks: consecutive rounds of one series whose stamped
    # configuration diverged (pre-stamping entries carry none and are
    # silently compatible — history stays readable backwards).
    by_label: dict[str, list[BenchEntry]] = {}
    for entry in entries:
        by_label.setdefault(entry.series_label, []).append(entry)
    breaks = []
    for label, group in sorted(by_label.items()):
        group.sort(key=lambda e: e.round)
        for a, b in zip(group, group[1:], strict=False):
            keys = differing_keys(a.provenance, b.provenance)
            if keys:
                breaks.append((label, a.round, b.round, keys))
    return HistoryReport(
        entries=entries,
        series=series,
        streaks=streaks,
        regressions=regressions,
        provenance_breaks=breaks,
    )
