"""Unified run timeline: one Chrome/Perfetto trace from a run's telemetry.

PR 4 (events/goodput), PR 5 (async checkpoint commits), PR 6 (profile
captures), and PR 8 (live memory) each write a rich stream into the same
JSONL flight log — but they stay *columns*, and diagnosing a stall means a
human cross-reading three vocabularies. This module merges a run
directory's event log onto the one monotonic clock ``events.py`` already
stamps (``t_mono``) and exports it as **trace-event JSON** (the
``chrome://tracing`` / Perfetto / ``about:tracing`` wire format), so "open
the trace, see the stall" replaces grep:

========================  ==================================================
track                     contents
========================  ==================================================
``steps``                 one span per ``window`` record (the ``log_every``
                          cadence): duration = steps x step_ms, args carry
                          mfu / live-memory / straggler fields
``epochs``                one span per ``epoch_end`` (windows nest inside
                          it visually; kept on its own track so every
                          track's spans stay non-overlapping)
``goodput``               the wall-time partition re-laid as spans: between
                          consecutive cumulative ``goodput_seconds``
                          snapshots (run_start / epoch_end / run_end), each
                          bucket's delta becomes one span — so summing span
                          durations per bucket re-derives the meter's
                          fractions exactly (CI-gated in telemetry_smoke)
``goodput async``         ``checkpoint_async`` deltas (background commit
                          wall — overlapped with training, so it cannot sit
                          in the sequential main-thread partition)
``checkpoint``            hot-loop save stalls: async snapshot spans
                          (``snapshot_ms``) and synchronous save spans
                          (``save_ms``)
``committer``             the async committer thread as its own track:
                          ``queued:<name>`` (snapshot landed -> commit
                          started) and ``commit:<name>`` (``commit_ms``)
                          spans — a checkpoint's snapshot->queued->
                          committing->committed lifecycle reads left to
                          right across the two tracks
``profile``               the ``profile_capture`` traced window
                          (``span_us``), args carry the StepProfile
                          category fractions + dispatch-gap audit
``markers``               instants for everything narrative: compile,
                          preemption, fault_injection, anomaly,
                          loss_scale_backoff, hung_step, restore/reshard/
                          elastic events, memory_preflight, gate verdicts
counters                  ``live_bytes`` and ``chip_skew_ms`` as counter
                          series (the memory-leak ramp and straggler skew
                          are visible as line plots above the spans)
========================  ==================================================

Every track's spans are **monotone and non-overlapping by construction**
(:class:`_Track` trims a span that would start before its predecessor
ended — measured durations and event timestamps come from different
clock reads, so sub-ms overhangs are expected), and the whole file is
strict JSON (``events._jsonable`` already de-NaN'd the inputs). Load it in
Perfetto (ui.perfetto.dev), ``chrome://tracing``, or re-parse it with
stdlib ``json`` — the doctor (``telemetry/doctor.py``) and the tests do
the latter.

Export ritual (docs/observability.md): ``scripts/run_doctor.py <run_dir>
--timeline`` or :func:`export_timeline` directly; the file lands next to
the event log as ``telemetry/timeline.json``.
"""

from __future__ import annotations

import json
import os

from distributed_training_pytorch_tpu.telemetry.events import (
    load_run_events,  # noqa: F401 — re-exported: the historical import site
)
from distributed_training_pytorch_tpu.telemetry.goodput import BUCKETS

__all__ = [
    "TRACKS",
    "build_timeline",
    "export_timeline",
    "load_run_events",
    "span_bucket_seconds",
]

# Stable thread ids per track (trace-event `tid`; named via "M" metadata
# records). One pid per writing process — a resumed run's records keep
# their own pid, so each attempt lays out as its own process group.
TRACKS = {
    "steps": 1,
    "epochs": 2,
    "goodput": 3,
    "goodput async": 4,
    "checkpoint": 5,
    "committer": 6,
    "profile": 7,
    "markers": 8,
}

# Event kinds that become instant markers (everything narrative; span-
# bearing kinds are handled individually). Unknown kinds fall through to
# markers too — a future event kind shows up in the trace by default
# instead of silently vanishing.
_COMMON_FIELDS = ("event", "t_wall", "t_mono", "process", "host", "pid", "chips", "schema")


# load_run_events lives in ``telemetry/events.py`` since ISSUE 15 — ONE
# shared torn-line-tolerant reader (``events.EventFollower``) behind the
# timeline, the run doctor, and the live monitor, so the parsers cannot
# drift. The name stays importable here (the historical import site;
# test-enforced that this module owns no private parser).


class _Track:
    """One (pid, tid) span lane with the non-overlap invariant enforced."""

    def __init__(self, out: list, pid, tid: int):
        self._out = out
        self._pid = pid
        self._tid = tid
        self._cursor = None  # end (us) of the last span laid

    def span(self, name: str, end_us: float, dur_us: float, args: dict | None = None):
        dur_us = max(float(dur_us), 0.0)
        ts = end_us - dur_us
        if self._cursor is not None and ts < self._cursor:
            # Trim the overhang: measured durations and the record's
            # timestamp come from different clock reads, so a span can
            # claim to start slightly before its predecessor ended. Keep
            # the END anchored (the timestamped fact) and shorten.
            ts = min(self._cursor, end_us)
            dur_us = end_us - ts
        self._cursor = ts + dur_us
        ev = {"name": name, "ph": "X", "ts": ts, "dur": dur_us,
              "pid": self._pid, "tid": self._tid}
        if args:
            ev["args"] = args
        self._out.append(ev)


def _args(rec: dict) -> dict:
    return {k: v for k, v in rec.items()
            if k not in _COMMON_FIELDS and not k.startswith("_")}


def build_timeline(events: list[dict]) -> dict:
    """Merge parsed event records into a trace-event dict (see module doc).

    ``t_mono`` seconds map to trace ``ts`` microseconds verbatim — all
    records of one process already share that clock, which is the whole
    reason ``events.py`` stamps it."""
    out: list[dict] = []
    pids = []
    tracks: dict[tuple, _Track] = {}

    def track(pid, name: str) -> _Track:
        key = (pid, name)
        if key not in tracks:
            tracks[key] = _Track(out, pid, TRACKS[name])
        return tracks[key]

    def counter(pid, t_us, name, value):
        out.append({"name": name, "ph": "C", "ts": t_us, "pid": pid,
                    "args": {name: float(value)}})

    # Per-pid goodput snapshot chain + pending async-save handoffs. The
    # goodput lanes advance on their own continuous cursors (seeded at the
    # first snapshot's timestamp) rather than re-anchoring to each record's
    # t_mono: the meter's ticks and the record's emit timestamp are
    # different clock reads, and re-anchoring would force sub-ms trims
    # whose lost microseconds break the exact span->fraction re-derivation
    # the smoke gate checks. Alignment drift vs the other tracks stays
    # bounded by the emit-vs-tick offset (sub-ms); durations stay EXACT.
    last_goodput: dict = {}
    goodput_cursor: dict = {}
    async_cursor: dict = {}
    pending_snapshot: dict = {}

    for rec in sorted(events, key=lambda r: (r.get("pid", 0), r.get("t_mono", 0.0))):
        kind = rec.get("event")
        t = rec.get("t_mono")
        if kind is None or t is None:
            continue
        pid = rec.get("pid", 0)
        if pid not in pids:
            pids.append(pid)
        t_us = float(t) * 1e6
        args = _args(rec)
        args["line"] = rec.get("_line")

        # -- goodput partition: cumulative snapshot -> per-bucket spans ----
        snap = rec.get("goodput_seconds")
        if isinstance(snap, dict):
            prev = last_goodput.get(pid)
            if prev is None:
                goodput_cursor[pid] = async_cursor[pid] = t_us
            else:
                for bucket in BUCKETS:
                    delta = float(snap.get(bucket, 0.0)) - float(prev.get(bucket, 0.0))
                    if delta <= 0.0:
                        continue
                    dur = delta * 1e6
                    if bucket == "checkpoint_async":
                        # Overlapped with training: its own lane (it would
                        # double-lay wall the main partition already covers).
                        track(pid, "goodput async").span(
                            bucket, async_cursor[pid] + dur, dur,
                            {"line": rec.get("_line")},
                        )
                        async_cursor[pid] += dur
                    else:
                        track(pid, "goodput").span(
                            bucket, goodput_cursor[pid] + dur, dur
                        )
                        goodput_cursor[pid] += dur
            last_goodput[pid] = dict(snap)

        # -- heartbeats (ISSUE 15): liveness plumbing. Their goodput
        # snapshot (handled above) refines the goodput span chain; an
        # instant marker per pulse would bury the narrative lane under
        # one dot every heartbeat_every_s.
        if kind == "heartbeat":
            continue

        # -- span-bearing kinds -------------------------------------------
        if kind == "window":
            steps = float(rec.get("steps", 0) or 0)
            step_ms = float(rec.get("step_ms", 0.0) or 0.0)
            track(pid, "steps").span(
                f"window@{rec.get('step_in_epoch')}", t_us, steps * step_ms * 1e3, args
            )
            if rec.get("live_bytes") is not None:
                counter(pid, t_us, "live_bytes", rec["live_bytes"])
            if rec.get("chip_skew_ms") is not None:
                counter(pid, t_us, "chip_skew_ms", rec["chip_skew_ms"])
            continue
        if kind == "epoch_end":
            track(pid, "epochs").span(
                f"epoch {rec.get('epoch')}", t_us, float(rec.get("wall_s", 0.0)) * 1e6, args
            )
            if rec.get("live_bytes") is not None:
                counter(pid, t_us, "live_bytes", rec["live_bytes"])
            continue
        if kind == "checkpoint_save":
            name = str(rec.get("name", "ckpt"))
            if rec.get("snapshot_ms") is not None:  # async: the hot-loop stall
                track(pid, "checkpoint").span(
                    f"snapshot:{name}", t_us, float(rec["snapshot_ms"]) * 1e3, args
                )
                pending_snapshot[(pid, name)] = t_us
            elif rec.get("save_ms") is not None:  # sync/emergency: full stall
                track(pid, "checkpoint").span(
                    f"save:{name}", t_us, float(rec["save_ms"]) * 1e3, args
                )
            else:
                out.append({"name": f"save:{name}", "ph": "i", "ts": t_us, "s": "t",
                            "pid": pid, "tid": TRACKS["checkpoint"], "args": args})
            continue
        if kind == "checkpoint_commit":
            name = str(rec.get("name", "ckpt"))
            commit_us = float(rec.get("commit_ms", 0.0) or 0.0) * 1e3
            queued_from = pending_snapshot.pop((pid, name), None)
            commit_start = t_us - commit_us
            if queued_from is not None and commit_start > queued_from:
                track(pid, "committer").span(
                    f"queued:{name}", commit_start, commit_start - queued_from
                )
            track(pid, "committer").span(f"commit:{name}", t_us, commit_us, args)
            continue
        if kind == "profile_capture" and rec.get("span_us") is not None:
            track(pid, "profile").span("profile_capture", t_us, float(rec["span_us"]), args)
            continue

        # -- everything else: a narrative instant marker ------------------
        out.append({"name": str(kind), "ph": "i", "ts": t_us, "s": "t",
                    "pid": pid, "tid": TRACKS["markers"], "args": args})

    meta = []
    for pid in pids:
        host = next((r.get("host") for r in events if r.get("pid") == pid), None)
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": f"pid {pid}" + (f" @ {host}" if host else "")}})
        for name, tid in TRACKS.items():
            meta.append({"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                         "args": {"name": name}})
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def span_bucket_seconds(trace: dict) -> dict:
    """Re-derive goodput bucket seconds from the exported goodput tracks —
    the independent consumer-side check (telemetry_smoke gates that these
    re-derive the meter's fractions within epsilon): sum span durations per
    bucket name over the ``goodput`` + ``goodput async`` lanes."""
    lanes = {TRACKS["goodput"], TRACKS["goodput async"]}
    totals = {b: 0.0 for b in BUCKETS}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "X" and ev.get("tid") in lanes and ev.get("name") in totals:
            totals[ev["name"]] += float(ev.get("dur", 0.0)) / 1e6
    return totals


def export_timeline(run_dir: str, out_path: str | None = None) -> tuple[dict, str]:
    """Read a run directory's event log, build the trace, write it as
    strict JSON. Returns ``(trace_dict, written_path)``. Default output:
    ``<run_dir>/telemetry/timeline.json`` (next to the event log it was
    derived from; for a direct ``.jsonl`` input, ``<stem>.timeline.json``)."""
    events = load_run_events(run_dir)
    trace = build_timeline(events)
    if out_path is None:
        if os.path.isdir(run_dir):
            out_path = os.path.join(run_dir, "telemetry", "timeline.json")
        else:
            out_path = os.path.splitext(run_dir)[0] + ".timeline.json"
    with open(out_path, "w", encoding="utf-8") as f:  # jaxlint: disable=file-write-without-rank-gate -- offline export CLI over a finished run dir, not a training-job writer
        json.dump(trace, f)
        f.write("\n")
    return trace, out_path
