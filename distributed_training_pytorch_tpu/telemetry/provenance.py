"""Run provenance stamping (ISSUE 14 satellite): who produced this number?

A bench line, a dryrun entry, or a ``run_start`` event is only comparable
to another one if both say what produced them: the git SHA, the jax/jaxlib
versions, the effective ``XLA_FLAGS``, the mesh spec, the compute dtype,
and the chain length. Four flat BENCH rounds went undiagnosed partly
because nothing recorded whether r03's number even ran the same program as
r02's. This module is the ONE provenance builder, stamped by:

* ``bench.py`` — every sweep JSON line (including the OOM lines);
* ``__graft_entry__.dryrun_multichip`` — every mesh-sweep entry;
* the Trainer's ``run_start`` event (rank-0, telemetry-on runs).

Comparison semantics (``scripts/run_compare.py`` / ``telemetry.history``):
:data:`COMPARE_KEYS` are the *configuration* keys — two entries differing
on any of them measure different programs and are refused without
``--force`` (naming the keys). ``git_sha`` is deliberately NOT a compare
key: differing code is the *point* of an A/B comparison; it is recorded so
the report can cite which commits are being compared. Entries with no
provenance at all (the pre-ISSUE-14 committed rounds) compare with a
warning, not a refusal — history must stay readable backwards.
"""

from __future__ import annotations

import os
import subprocess

__all__ = ["COMPARE_KEYS", "differing_keys", "provenance_fields"]

# Configuration keys that must MATCH for a comparison to be meaningful.
# git_sha is excluded on purpose (see module doc).
COMPARE_KEYS = ("jax", "jaxlib", "xla_flags", "mesh", "dtype", "chain_steps", "batch")

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Memoized per process: the SHA cannot change mid-run, and run_start +
# every sweep line asking would otherwise each pay a subprocess.
_GIT_SHA: "str | None" = None


def _git_sha() -> str:
    global _GIT_SHA
    if _GIT_SHA is None:
        try:
            _GIT_SHA = subprocess.run(
                ["git", "-C", _REPO_ROOT, "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10, check=True,
            ).stdout.strip() or "unknown"
        except (OSError, subprocess.SubprocessError):
            # No git / not a checkout (an installed wheel, a stripped CI
            # image): provenance degrades to "unknown", never raises.
            _GIT_SHA = "unknown"
    return _GIT_SHA


def provenance_fields(
    *,
    mesh=None,
    dtype: "str | None" = None,
    chain_steps: "int | None" = None,
    batch: "int | None" = None,
) -> dict:
    """The provenance record: environment identity resolved here (git SHA,
    jax/jaxlib, ``XLA_FLAGS``) + the caller's program identity (mesh spec or
    axis dict, compute dtype, chain length, global batch). Pure host-side
    reads — never initializes the jax backend."""
    import jax
    import jaxlib

    return {
        "git_sha": _git_sha(),
        "jax": str(jax.__version__),
        "jaxlib": str(getattr(jaxlib, "__version__", "unknown")),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
        "mesh": mesh if mesh is None or isinstance(mesh, (str, dict)) else str(mesh),
        "dtype": dtype,
        "chain_steps": chain_steps,
        "batch": batch,
    }


def differing_keys(a: "dict | None", b: "dict | None") -> list[str]:
    """The configuration keys on which two provenance records disagree —
    empty = comparable. A key absent (or None) on either side never
    disagrees: old entries must not be un-comparable just because they
    predate a field."""
    if not a or not b:
        return []
    out = []
    for key in COMPARE_KEYS:
        va, vb = a.get(key), b.get(key)
        if va is None or vb is None:
            continue
        if va != vb:
            out.append(key)
    return out
