"""Host-side anomaly detectors: loss spikes, grad explosions, step-time
regressions, and live-memory growth.

These run ONLY at the trainer's existing host sync points (``log_every``
boundaries and epoch end), on metric values the sync already fetched —
detection adds zero device syncs, exactly like ``Trainer._apply_nan_policy``.
Each signal keeps an exponentially weighted moving average as its baseline;
a value exceeding ``factor x baseline`` (after a warmup of observations, so
the noisy first steps never false-positive) is an anomaly. Non-finite loss
or grad-norm values are always anomalous (no baseline needed).

The detector only *detects*; policy lives with the caller: the trainer
emits an ``anomaly`` event + a warning log line per finding, and raises
:class:`AnomalyError` when constructed with ``action="raise"`` (the
observability analog of ``nan_policy="raise"`` — useful for sweeps where a
diverged run should die early, not burn its remaining budget).
"""

from __future__ import annotations

import dataclasses
import math

__all__ = ["Anomaly", "AnomalyError", "AnomalyDetector"]


class AnomalyError(RuntimeError):
    """Raised by the trainer (``action="raise"``) when a detector fires."""


@dataclasses.dataclass
class Anomaly:
    """One finding: ``kind`` is ``loss_spike`` | ``grad_explosion`` |
    ``step_time_regression`` | ``memory_growth`` | ``straggler``; ``value``
    tripped at ``factor`` x ``baseline`` (the EWMA at detection time — or,
    for the floor-baselined kinds ``memory_growth``/``straggler``, the
    post-warmup steady-state floor) at global step ``step``."""

    kind: str
    step: int
    value: float
    baseline: float
    factor: float

    def describe(self) -> str:
        return (
            f"{self.kind} at step {self.step}: {self.value:.4g} vs "
            f"baseline {self.baseline:.4g} (threshold x{self.factor:g})"
        )


class AnomalyDetector:
    """EWMA-baselined detectors over the trainer's host-synced metrics.

    ``loss_spike`` / ``grad_explosion`` / ``step_time_regression`` are the
    trip factors (None disables that signal's threshold comparison — a
    non-finite value still fires); ``ewma_alpha`` the baseline's
    smoothing; ``warmup`` the observations per signal before it can fire
    (compile-skewed first windows and init-transient losses are normal).

    ``memory_growth`` watches the trainer's per-window ``live_bytes``
    (``memory.live``) differently: steady-state live bytes must be FLAT
    across windows — every step's buffers are freed or reused by the next —
    so its baseline is the post-warmup **minimum** (the steady-state floor),
    not an EWMA. An EWMA would *track* a slow leak and never alarm; a floor
    cannot be dragged upward, so a host-side buffer leak (a prefetch queue
    that stops draining — the exact bug class of the PR-2 shutdown race — a
    metrics list pinning device arrays) eventually crosses
    ``factor x floor`` no matter how gradual the slope. Signals whose value
    is absent (statless backends pass ``live_bytes=None``) never fire.

    ``straggler`` (ISSUE 13) uses the same floor rule on the per-window
    slowest-chip ratio (``telemetry.straggler.ratio`` — 1.0 = chips in
    lockstep): a healthy mesh's ratio floor sits near 1, and a chip that
    degrades *gradually* (thermals, a failing link) would drag an EWMA
    with it exactly like a slow leak — the post-warmup floor cannot be
    dragged, so the ratio eventually crosses ``factor x floor``. Absent on
    single-chip hosts (the sampler returns no ratio): never fires.
    """

    def __init__(
        self,
        *,
        action: str = "warn",
        loss_spike: float | None = 3.0,
        grad_explosion: float | None = 10.0,
        step_time_regression: float | None = 2.5,
        memory_growth: float | None = 1.5,
        straggler: float | None = 1.5,
        ewma_alpha: float = 0.1,
        warmup: int = 5,
    ):
        if action not in ("warn", "raise"):
            raise ValueError(f"action must be 'warn' or 'raise', got {action!r}")
        self.action = action
        self._factors = {
            "loss_spike": loss_spike,
            "grad_explosion": grad_explosion,
            "step_time_regression": step_time_regression,
        }
        self.memory_growth = memory_growth
        self.straggler = straggler
        self.ewma_alpha = float(ewma_alpha)
        self.warmup = int(warmup)
        self._ewma: dict[str, float] = {}
        self._seen: dict[str, int] = {}
        self._floors: dict[str, float] = {}
        self.total_fired = 0

    def _check(self, kind: str, value: float | None, step: int) -> Anomaly | None:
        factor = self._factors[kind]
        if value is None:
            return None
        value = float(value)
        baseline = self._ewma.get(kind)
        seen = self._seen.get(kind, 0)
        anomaly = None
        if not math.isfinite(value):
            # Non-finite is anomalous unconditionally — even for a signal
            # whose threshold factor is disabled (None turns off the EWMA
            # comparison, not NaN detection) — and must NOT be folded into
            # the baseline (one NaN would poison the EWMA for the rest of
            # the run).
            return Anomaly(kind, step, value, baseline or 0.0, factor or 0.0)
        if factor is None:
            return None
        if baseline is not None and seen >= self.warmup and value > factor * abs(baseline):
            anomaly = Anomaly(kind, step, value, baseline, factor)
        # Baseline update AFTER the check; a detected spike still feeds in
        # with bounded (alpha) weight, so a persistent regime shift re-bases
        # instead of alarming forever.
        a = self.ewma_alpha
        self._ewma[kind] = value if baseline is None else (1 - a) * baseline + a * value
        self._seen[kind] = seen + 1
        return anomaly

    def _check_floor(
        self, kind: str, factor: float | None, value: float | None, step: int
    ) -> Anomaly | None:
        """Floor-baselined detection (see class docstring; shared by
        ``memory_growth`` and ``straggler``): warmup observations pass
        untracked (allocator ramp / compile-skewed first windows are
        normal), then the running minimum is the steady-state floor and a
        value above ``factor x floor`` fires. The floor only ever moves
        DOWN, so it never absorbs the drift it is there to catch."""
        if value is None or factor is None:
            return None
        value = float(value)
        seen = self._seen.get(kind, 0)
        self._seen[kind] = seen + 1
        if seen < self.warmup or not math.isfinite(value):
            return None
        floor = self._floors.get(kind)
        if floor is None:
            self._floors[kind] = value
            return None
        self._floors[kind] = min(floor, value)
        if value > factor * floor:
            return Anomaly(kind, step, value, floor, factor)
        return None

    def observe(
        self,
        step: int,
        *,
        loss: float | None = None,
        grad_norm: float | None = None,
        step_time: float | None = None,
        live_bytes: float | None = None,
        straggler_ratio: float | None = None,
    ) -> list[Anomaly]:
        """Feed one sync point's values; returns the anomalies fired (empty
        list almost always). ``step`` labels findings only."""
        found = []
        for kind, value in (
            ("loss_spike", loss),
            ("grad_explosion", grad_norm),
            ("step_time_regression", step_time),
        ):
            a = self._check(kind, value, int(step))
            if a is not None:
                found.append(a)
        for kind, factor, value in (
            ("memory_growth", self.memory_growth, live_bytes),
            ("straggler", self.straggler, straggler_ratio),
        ):
            a = self._check_floor(kind, factor, value, int(step))
            if a is not None:
                found.append(a)
        self.total_fired += len(found)
        return found
