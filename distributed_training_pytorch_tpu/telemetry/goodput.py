"""Goodput accounting: wall time partitioned into named buckets.

"Goodput" (MegaScale's per-run headline) is the fraction of a job's wall
time spent making training progress. This meter partitions wall time into:

* ``productive_step``   — executing (or draining) compiled train steps;
* ``compile``           — XLA tracing/compilation (first window per shape);
* ``data_wait``         — the step loop blocked on the input pipeline;
* ``checkpoint``        — save/commit time the step loop actually waited on
  (under async checkpointing: just the device->host snapshot stall, plus
  any emergency-save commit);
* ``checkpoint_async``  — background checkpoint-commit time (the
  ``resilience.AsyncCheckpointSaver`` worker's wall time per commit, booked
  via :meth:`GoodputMeter.account` from its completion callback). This is
  the save cost the hot loop *no longer* pays — overlapped with training,
  so it is extra accounted time on top of the main thread's partition;
* ``restart_rollback``  — resume overhead: checkpoint restore + replaying
  the loader past already-trained batches after a preemption;
* ``other``             — everything else (validation, logging, epoch glue).

The partition is **exhaustive by construction**: the meter attributes the
time between consecutive :meth:`tick` calls to exactly one bucket, so the
bucket fractions always sum to 1 (the ``scripts/telemetry_smoke.py`` CI
gate asserts it). Attribution is host-side wall time — with async dispatch
the device's work surfaces wherever the host blocks (a sync point, or
backpressure in the next data fetch), which is exactly the operator-visible
cost each bucket names.

Counters are **cumulative across restarts**: the trainer embeds
:meth:`to_state` in every checkpoint's meta json (next to ``loop`` state)
and re-seeds a resumed run's meter from it — goodput survives SIGTERM
kill/resume the way ``loss_scale`` state survives via its checkpoint item.
JSON round-trips Python floats exactly, so restored counters are
bit-identical to the saved ones (test-enforced).
"""

from __future__ import annotations

import time

__all__ = ["BUCKETS", "GoodputMeter"]

# Canonical bucket names, in reporting order. The meter accepts only these —
# a typo'd bucket must fail loudly, not silently open a seventh bucket that
# drains the fractions the smoke gate checks.
BUCKETS = (
    "productive_step",
    "compile",
    "data_wait",
    "checkpoint",
    "checkpoint_async",
    "restart_rollback",
    "other",
)


class GoodputMeter:
    """Tick-based wall-time partitioner.

    ``tick(bucket)`` attributes the time since the previous tick to
    ``bucket`` and restarts the clock; the first tick (or the first after
    :meth:`stop`) only starts the clock. ``account(bucket, seconds)`` adds
    an externally measured duration (e.g. a checkpoint restore timed before
    the loop starts).
    """

    def __init__(self, state: dict | None = None):
        self.buckets: dict[str, float] = {b: 0.0 for b in BUCKETS}
        if state:
            self.load_state(state)
        self._last: float | None = None

    # -- time attribution --------------------------------------------------

    def tick(self, bucket: str) -> float:
        """Attribute elapsed-since-last-tick to ``bucket``; returns the
        seconds attributed (0.0 on the starting tick)."""
        if bucket not in self.buckets:
            raise KeyError(f"unknown goodput bucket {bucket!r} (one of {BUCKETS})")
        now = time.perf_counter()
        if self._last is None:
            self._last = now
            return 0.0
        dt = now - self._last
        self._last = now
        self.buckets[bucket] += dt
        return dt

    def account(self, bucket: str, seconds: float) -> None:
        """Add an externally measured duration without touching the clock.

        Safe to call from a non-main thread for a bucket the main thread's
        ``tick`` stream never writes (the async-commit worker books
        ``checkpoint_async`` this way): distinct dict keys, so the += races
        nothing."""
        if bucket not in self.buckets:
            raise KeyError(f"unknown goodput bucket {bucket!r} (one of {BUCKETS})")
        self.buckets[bucket] += float(seconds)

    def start(self) -> None:
        """Start (or restart) the clock without attributing anything."""
        self._last = time.perf_counter()

    def stop(self, bucket: str = "other") -> None:
        """Close the open interval into ``bucket`` and stop the clock; the
        next tick starts a fresh interval (a re-entered ``train()`` does not
        absorb the idle gap between runs)."""
        if self._last is not None:
            self.tick(bucket)
        self._last = None

    # -- reporting ---------------------------------------------------------

    def total(self) -> float:
        return sum(self.buckets.values())

    def fractions(self) -> dict[str, float]:
        """Bucket fractions of total accounted wall time. Computed from the
        same dict they partition, so they sum to 1 up to float rounding
        (empty meter: all zeros)."""
        total = self.total()
        if total <= 0.0:
            return {b: 0.0 for b in BUCKETS}
        return {b: v / total for b, v in self.buckets.items()}

    @property
    def goodput(self) -> float:
        """The headline: productive-step fraction of accounted wall time."""
        return self.fractions()["productive_step"]

    # -- checkpoint round trip ---------------------------------------------

    def to_state(self) -> dict[str, float]:
        """Plain-float snapshot for checkpoint meta (json-safe)."""
        return {b: float(v) for b, v in self.buckets.items()}

    def load_state(self, state: dict) -> None:
        """Seed cumulative counters from a checkpoint snapshot. Unknown keys
        (a future bucket rename) fold into ``other`` rather than being
        dropped — the partition property must survive schema drift."""
        for key, value in dict(state).items():
            if key in self.buckets:
                self.buckets[key] = float(value)
            else:
                self.buckets["other"] += float(value)
