"""Streaming run monitor: the run doctor, live (ISSUE 15).

Everything observability built so far — events, goodput, timeline, doctor,
run comparison — runs *after* (or outside) the training process: an
operator cannot tell a healthy slow run from a wedged or SIGKILL'd one
without ssh-ing in, and no anomaly reaches anyone until someone runs
``run_doctor.py`` by hand. This module tails a run's events.jsonl while
the run is alive and maintains the doctor's diagnosis *online*:

* **One reader, one verdict engine.** Records stream in through the same
  :class:`~.events.EventFollower` the one-shot ``load_run_events`` wraps,
  and fold into :class:`~.doctor.Signals` through the same
  :func:`~.doctor.update_signals` the post-hoc doctor loops — the monitor
  cannot disagree with ``run_doctor.py`` about a log they both read
  (regression-tested: same log => byte-identical verdicts).

* **The liveness contract.** The trainer emits a cheap ``heartbeat``
  record at every ``log_every`` sync (``source="loop"``) and — between
  syncs — from the step watchdog's patrol thread (``source="watchdog"``,
  carrying ``since_progress_s``). That makes *no signal itself a signal*:

  ===================  ===================================================
  status               rule
  ===================  ===================================================
  ``training``         fresh records, and an execution unit completed
                       within ``stale_after_s``
  ``stale_heartbeat``  records still arrive (the process breathes) but no
                       unit has completed for ``stale_after_s`` — a hung
                       collective, a wedged storage mount, a stuck loader
  ``dead``             the log itself is silent past ``dead_after_s``
                       (freshest of last record ``t_wall`` and file
                       mtime): the process was SIGKILL'd, OOM-reaped, or
                       lost its host
  ``finished``         a ``run_end`` record closed the attempt — the
                       post-hoc verdict applies, nothing is stale
  ``waiting``          no event log (or no records) yet
  ===================  ===================================================

* **Alert rules** (:class:`AlertConfig`): the stale/dead ceilings above,
  steady-state ``data_wait``/``checkpoint`` fraction ceilings (the
  doctor's thresholds by default), anomaly kinds, and verdict transitions
  (``compile_bound``/``straggler``/``comm_heavy`` crossing score 1.0).
  Every rule is **debounced**: it fires once when its condition goes
  false->true and re-arms only after the condition clears — a starved
  pipeline that stays starved pages once, not once per poll. Firings
  surface as ``monitor_alert`` JSONL records (``run_monitor.py
  --events``) and as a non-zero exit for CI (``--once``).

``scripts/run_monitor.py`` renders this as a live single-run view or a
multi-run fleet table; ``verify.sh`` proves the contract with a real run
driven through the existing fault seams (hang -> ``stale_heartbeat``,
SIGKILL -> ``dead``, loader sleep -> exactly one ``data_bound`` alert).

Clock caveat: liveness compares the writer's ``t_wall`` (and the log
file's mtime) against *this* process's ``time.time()`` — cross-host
monitoring inherits whatever clock skew the fleet tolerates. Keep the
ceilings comfortably above NTP drift (the defaults are).
"""

from __future__ import annotations

import dataclasses
import os
import time

from distributed_training_pytorch_tpu.telemetry import doctor as doctor_lib
from distributed_training_pytorch_tpu.telemetry.events import (
    EventFollower,
    resolve_events_path,
)

__all__ = ["AlertConfig", "MonitorStatus", "RunMonitor", "worst_exit_code"]

# Record kinds whose arrival proves the MAIN thread completed (or is
# completing) execution units — the "progress" half of the liveness
# contract. Worker-thread records (checkpoint_commit, watchdog-source
# heartbeats, hung_step) deliberately absent: a wedged main thread keeps
# none of these from being written.
_PROGRESS_KINDS = (
    "run_start",
    "checkpoint_restore",
    "window",
    "epoch_end",
    "compile",
    "checkpoint_save",
    "preemption",
    "run_end",
    # The serving vocabulary (ISSUE 18): the server's dispatch loop emits
    # request_batch as a ~1 Hz summary pulse even when idle — it is the
    # server's liveness heartbeat, exactly as `window` is the trainer's.
    "serve_start",
    "request_batch",
    "hot_swap",
    # The actuated-handshake vocabulary (ISSUE 20): the dispatch loop
    # keeps pulsing through a drain/re-plan, and these are emitted by
    # that same (live) loop's machinery — a replica mid-drain must read
    # as draining, never as dead or stale.
    "drain_start",
    "replan_done",
    "offer_accept",
    "offer_decline",
)

# Verdicts alerted on transition (score crossing 1.0). data_bound /
# checkpoint_stall are NOT here — their fraction ceilings below are the
# configurable alert surface, and double-reporting one disease through
# two rules would page twice.
_VERDICT_RULES = ("compile_bound", "straggler", "comm_heavy")


@dataclasses.dataclass
class AlertConfig:
    """The monitor's rule thresholds (ISSUE 15 tentpole d).

    * ``stale_after_s`` — no completed execution unit for this long (while
      records still arrive) => ``stale_heartbeat``. Keep it above the
      slowest honest window wall (and above epoch glue like validation).
    * ``dead_after_s``   — the log silent for this long => ``dead``.
      ``None`` = ``3 x stale_after_s``. Keep it above
      ``Telemetry(heartbeat_every_s)`` with margin, or every network
      hiccup reads as a death.
    * ``data_wait_frac`` / ``checkpoint_frac`` — steady-state goodput
      fraction ceilings (the doctor's thresholds by default, but an alert
      ceiling may legitimately sit below a diagnosis ceiling).
    * ``anomaly_kinds``  — anomaly record kinds that page (first
      occurrence per kind).
    * ``min_steady_s``   — fraction rules stay quiet until this much
      steady-state wall is accounted: the first post-warmup sync's tiny
      denominator must not page the fleet.
    """

    stale_after_s: float = 120.0
    dead_after_s: float | None = None
    data_wait_frac: float = doctor_lib.THRESHOLDS["data_wait_frac"]
    checkpoint_frac: float = doctor_lib.THRESHOLDS["checkpoint_frac"]
    anomaly_kinds: tuple = (
        "loss_spike",
        "grad_explosion",
        "step_time_regression",
        "memory_growth",
        "straggler",
    )
    min_steady_s: float = 1.0

    def resolved_dead_after(self) -> float:
        return (
            float(self.dead_after_s)
            if self.dead_after_s is not None
            else 3.0 * float(self.stale_after_s)
        )


@dataclasses.dataclass
class MonitorStatus:
    """One poll's answer: liveness + the doctor's online diagnosis."""

    run_dir: str
    # waiting | training | serving | draining | replanning | stale_heartbeat
    # | dead | finished (draining/replanning: a serve replica mid ISSUE 20
    # drain/re-plan — still alive, deliberately not admitting)
    status: str
    verdict: str  # liveness kind when stale/dead; doctor's top verdict for
    # trainers; healthy|slo_breach for servers (ISSUE 18 satellite 2)
    diagnosis: "doctor_lib.Diagnosis | None"
    steady_fractions: dict
    last_event_age_s: float | None
    progress_age_s: float | None
    headline: dict  # epoch / step_in_epoch / units / step_ms from the last pulse
    alerts: list  # rules that fired THIS poll (debounced)
    active_alerts: tuple  # every rule currently over its line
    attempt: int | None = None  # restart generation the verdict describes
    kind: str = "train"  # "train" | "serve" (a serve_start record flips it)
    serve: dict = dataclasses.field(default_factory=dict)  # last request_batch pulse

    @property
    def exit_code(self) -> int:
        """The ``--once`` CI contract: 0 = alive (or finished) and clean,
        1 = degraded (stale heartbeat, a non-healthy verdict, or any alert
        rule currently over its line), 2 = dead, 3 = nothing to monitor."""
        if self.status == "dead":
            return 2
        if self.status == "waiting":
            return 3
        if (
            self.status == "stale_heartbeat"
            or self.verdict != "healthy"
            or self.active_alerts
        ):
            return 1
        return 0

    def to_dict(self) -> dict:
        out = {
            "run_dir": self.run_dir,
            "status": self.status,
            "verdict": self.verdict,
            "attempt": self.attempt,
            "steady_fractions": self.steady_fractions,
            "last_event_age_s": self.last_event_age_s,
            "progress_age_s": self.progress_age_s,
            "headline": self.headline,
            "alerts": self.alerts,
            "active_alerts": list(self.active_alerts),
            "exit_code": self.exit_code,
            "kind": self.kind,
        }
        if self.serve:
            out["serve"] = self.serve
        if self.diagnosis is not None:
            out["diagnosis"] = self.diagnosis.to_dict()
        return out

    def describe(self) -> str:
        """The single-run console view (``scripts/run_monitor.py``)."""
        ages = []
        if self.last_event_age_s is not None:
            ages.append(f"last event {self.last_event_age_s:.1f}s ago")
        if self.progress_age_s is not None:
            ages.append(f"progress {self.progress_age_s:.1f}s ago")
        if self.kind == "serve":
            hl = ", ".join(
                f"{k} {self.serve[k]}"
                for k in ("qps", "p50_ms", "p99_ms", "params_version")
                if self.serve.get(k) is not None
            )
        else:
            hl = ", ".join(
                f"{k} {self.headline[k]}"
                for k in ("epoch", "step_in_epoch", "units", "step_ms")
                if self.headline.get(k) is not None
            )
        lines = [
            f"{self.run_dir}: {self.status.upper()} [{self.verdict}]"
            + (f" ({'; '.join(ages)})" if ages else ""),
        ]
        if hl:
            lines.append(f"  {hl}")
        fr = self.steady_fractions
        if any(fr.values()):
            lines.append(
                "  steady: productive {:.0%} data_wait {:.0%} checkpoint {:.0%}".format(
                    fr.get("productive_step", 0.0),
                    fr.get("data_wait", 0.0),
                    fr.get("checkpoint", 0.0),
                )
            )
        if self.diagnosis is not None and self.status not in ("waiting",):
            lines.append(self.diagnosis.describe())
        for a in self.alerts:
            lines.append(f"  ALERT [{a['rule']}]: {a.get('message', '')}")
        return "\n".join(lines)

    def fleet_row(self) -> dict:
        """The multi-run table projection (stable key order). Trainer and
        server rows share one schema (ISSUE 18 satellite 2): server rows
        fill qps/p99 and blank the trainer-only columns; trainer rows the
        inverse — so a mixed fleet renders side by side in one table."""
        fr = self.steady_fractions
        age = self.last_event_age_s
        serving = self.kind == "serve"

        def _num(v, fmt="{:.1f}"):
            return fmt.format(v) if isinstance(v, (int, float)) else "-"

        return {
            "run": os.path.basename(os.path.normpath(self.run_dir)) or self.run_dir,
            "status": self.status,
            "verdict": self.verdict,
            "att": self.attempt if self.attempt is not None else "-",
            "epoch": "-" if serving else self.headline.get("epoch", "-"),
            "step": "-" if serving else self.headline.get("step_in_epoch", "-"),
            "step_ms": "-" if serving else _num(self.headline.get("step_ms")),
            "qps": _num(self.serve.get("qps"), "{:.2f}") if serving else "-",
            "p99": _num(self.serve.get("p99_ms")) if serving else "-",
            "good%": "-" if serving else f"{100 * fr.get('productive_step', 0.0):.0f}",
            "data%": "-" if serving else f"{100 * fr.get('data_wait', 0.0):.0f}",
            "ckpt%": "-" if serving else f"{100 * fr.get('checkpoint', 0.0):.0f}",
            "age_s": f"{age:.1f}" if age is not None else "-",
            "alerts": ",".join(self.active_alerts) or "-",
        }


def worst_exit_code(statuses) -> int:
    """Fleet aggregation for ``--once``: a real finding (dead=2 over
    degraded=1) wins over everything; otherwise ``waiting`` (3 — nothing
    to monitor, the likely misconfiguration) wins over clean (0)."""
    codes = [s.exit_code for s in statuses]
    real = [c for c in codes if c in (1, 2)]
    if real:
        return max(real)
    return 3 if (3 in codes or not codes) else 0


class RunMonitor:
    """Incremental monitor over one run directory (see module doc).

    ``alert_log`` is an :class:`~.events.EventLog` (or None) receiving one
    ``monitor_alert`` record per debounced rule firing; ``clock`` is
    injectable for tests (defaults to ``time.time`` — the same clock
    domain as the records' ``t_wall``).
    """

    def __init__(
        self,
        run_dir: str,
        config: AlertConfig | None = None,
        *,
        alert_log=None,
        clock=time.time,
    ):
        self.run_dir = str(run_dir)
        self.path = resolve_events_path(self.run_dir)
        self.config = config if config is not None else AlertConfig()
        self._follower = EventFollower(self.path)
        self.event_log = alert_log
        self._clock = clock
        self._generation = self._follower.generation
        self._reset_state()

    def _reset_state(self) -> None:
        """Fresh accumulation state — the ctor, again whenever the follower
        detects the log was truncated/rotated underneath us, and again on
        an ``attempt`` change (ISSUE 16: a controller-restarted run APPENDS
        to the same file, so the generation counter never bumps — the
        attempt id on ``run_start``/``heartbeat`` records is the in-band
        restart marker): the old Signals describe a process that no longer
        exists, and folding the new attempt's records on top would
        double-count and weld two attempts' verdicts together. Alert
        debounce state resets too (a fresh attempt's recurrence of a
        condition is a fresh page — the re-arm-across-restart contract)."""
        self.signals = doctor_lib.Signals()
        self._seen_any = False
        self._run_ended = False
        self._drained_tail = False
        self._last_wall: float | None = None  # newest record's t_wall
        self._progress_wall: float | None = None  # when a unit last completed
        self._active: dict[str, bool] = {}  # rule -> currently-over-the-line
        self.headline: dict = {}
        self._attempt: int | None = None  # last attempt id seen in-band
        self._kind = "train"  # flips to "serve" on a serve_start record
        self._serve: dict = {}  # last request_batch pulse's summary fields
        # Cumulative-goodput snapshot at the newest attempt's start: goodput
        # counters ride checkpoint meta across restarts (trainer resume
        # path), so the raw cumulative fractions would keep indicting a
        # disease the restart already cured. Verdicts/alerts are computed
        # on (cumulative - base) — this attempt's own accrual.
        self._goodput_base: dict | None = None

    # -- ingestion ---------------------------------------------------------

    def _ingest(self, rec: dict) -> None:
        attempt = rec.get("attempt")
        if isinstance(attempt, int):
            if self._attempt is not None and attempt != self._attempt:
                # In-band restart marker (see _reset_state): drop the dead
                # attempt's accumulation, then rebase goodput at the new
                # attempt's carried-over snapshot so fraction verdicts
                # describe THIS attempt, not the welded cumulative.
                self._reset_state()
                if isinstance(rec.get("goodput_seconds"), dict):
                    self._goodput_base = dict(rec["goodput_seconds"])
            self._attempt = attempt
        doctor_lib.update_signals(self.signals, rec)
        self._seen_any = True
        kind = rec.get("event")
        t_wall = rec.get("t_wall")
        t_wall = float(t_wall) if isinstance(t_wall, (int, float)) else None
        if t_wall is not None and (self._last_wall is None or t_wall > self._last_wall):
            self._last_wall = t_wall
        if kind == "heartbeat":
            for key in ("epoch", "step_in_epoch", "units", "step_ms"):
                if rec.get(key) is not None:
                    self.headline[key] = rec[key]
            if t_wall is not None:
                if rec.get("source") == "watchdog":
                    # The patrol thread says how long ago the main thread
                    # last completed a unit — progress is t_wall minus that
                    # lag, NOT the record's own (worker-thread) timestamp.
                    lag = float(rec.get("since_progress_s") or 0.0)
                    prog = t_wall - lag
                else:
                    prog = t_wall
                if self._progress_wall is None or prog > self._progress_wall:
                    self._progress_wall = prog
        elif kind in _PROGRESS_KINDS:
            if kind == "run_start":
                self._run_ended = False  # a resumed attempt re-opens the run
            elif kind == "run_end":
                self._run_ended = True
            elif kind == "serve_start":
                # This run dir belongs to an inference server (ISSUE 18):
                # liveness keys off request_batch pulses, verdicts off the
                # pulse's SLO flag rather than goodput fractions.
                self._kind = "serve"
                self._run_ended = False
            elif kind == "request_batch":
                for key in (
                    "qps",
                    "p50_ms",
                    "p99_ms",
                    "slo_ok",
                    "slo_p99_ms",
                    "params_version",
                    "rejected_total",
                    # ISSUE 20: the pulse carries the admission state and
                    # the per-MESH-chip throughput the A/B judge reads.
                    "state",
                    "qps_per_chip",
                    "mesh_chips",
                    "shed_total",
                ):
                    if key in rec:
                        self._serve[key] = rec[key]
            elif kind == "hot_swap" and rec.get("to_version") is not None:
                self._serve["params_version"] = rec["to_version"]
            elif kind == "drain_start":
                # Admission just stopped: even if the next pulse is a
                # second out, status must already read "draining", never
                # "dead" (ISSUE 20 acceptance).
                self._serve["state"] = "draining"
            elif kind == "replan_done":
                self._serve["state"] = "serving"
                if rec.get("device_ids"):
                    self._serve["mesh_chips"] = len(rec["device_ids"])
            for key in ("epoch", "step_in_epoch"):
                if rec.get(key) is not None:
                    self.headline[key] = rec[key]
            if rec.get("step_ms") is not None:
                self.headline["step_ms"] = rec["step_ms"]
            if t_wall is not None and (
                self._progress_wall is None or t_wall > self._progress_wall
            ):
                self._progress_wall = t_wall

    def _scoped_signals(self) -> "doctor_lib.Signals":
        """The Signals the verdict engine should see: identical to the
        accumulated ones, except goodput is rebased to the current
        attempt's own accrual when a restart was observed (cumulative
        minus the snapshot its ``run_start`` carried). Without a restart
        this IS ``self.signals`` — byte-identical to the post-hoc doctor's
        view of the same log."""
        base = self._goodput_base
        cum = self.signals.goodput_seconds
        if not base or not cum:
            return self.signals
        rebased = {
            k: max(0.0, float(v) - float(base.get(k, 0.0))) for k, v in cum.items()
        }
        return dataclasses.replace(self.signals, goodput_seconds=rebased)

    # -- liveness ----------------------------------------------------------

    def _freshness(self) -> float | None:
        """Newest of (last record t_wall, log-file mtime) — the mtime
        covers the torn-write case where bytes landed but no complete
        record has parsed yet."""
        last = self._last_wall
        try:
            mtime = os.path.getmtime(self.path)
        except OSError:
            mtime = None
        if mtime is not None and (last is None or mtime > last):
            last = mtime
        return last

    def _liveness(self, now: float) -> str:
        if not self._seen_any:
            return "waiting"
        if self._run_ended:
            return "finished"
        fresh = self._freshness()
        if fresh is None:
            return "waiting"
        if now - fresh >= self.config.resolved_dead_after():
            return "dead"
        progress = self._progress_wall if self._progress_wall is not None else fresh
        if now - progress >= self.config.stale_after_s:
            return "stale_heartbeat"
        return "training"

    # -- alert rules (debounced) -------------------------------------------

    def _evaluate_alerts(self, status: str, diagnosis, fractions, now, sig) -> list:
        cfg = self.config
        fired: list[dict] = []

        def rule(key: str, firing: bool, value=None, threshold=None, message=""):
            was = self._active.get(key, False)
            self._active[key] = bool(firing)
            if firing and not was:
                fired.append(
                    {
                        "rule": key,
                        "value": value,
                        "threshold": threshold,
                        "message": message,
                    }
                )

        fresh = self._freshness()
        age = None if fresh is None else now - fresh
        prog_age = None if self._progress_wall is None else now - self._progress_wall
        rule(
            "dead",
            status == "dead",
            value=None if age is None else round(age, 1),
            threshold=cfg.resolved_dead_after(),
            message="event log silent — process killed or host lost",
        )
        rule(
            "stale_heartbeat",
            status == "stale_heartbeat",
            value=None if prog_age is None else round(prog_age, 1),
            threshold=cfg.stale_after_s,
            message="heartbeats arrive but no execution unit completes — hung",
        )
        steady = sum(
            float(v)
            for b, v in (sig.goodput_seconds or {}).items()
            if b not in doctor_lib._EXCLUDED
        )
        fractions_armed = steady >= cfg.min_steady_s
        rule(
            "data_bound",
            fractions_armed and fractions.get("data_wait", 0.0) > cfg.data_wait_frac,
            value=round(fractions.get("data_wait", 0.0), 4),
            threshold=cfg.data_wait_frac,
            message="steady-state data_wait fraction over the alert ceiling",
        )
        rule(
            "checkpoint_stall",
            fractions_armed and fractions.get("checkpoint", 0.0) > cfg.checkpoint_frac,
            value=round(fractions.get("checkpoint", 0.0), 4),
            threshold=cfg.checkpoint_frac,
            message="steady-state checkpoint fraction over the alert ceiling",
        )
        for kind in cfg.anomaly_kinds:
            n = int(sig.anomaly_counts.get(kind, 0))
            rule(
                f"anomaly:{kind}",
                n > 0,
                value=n,
                threshold=1,
                message=f"{n} {kind} anomaly record(s) in the log",
            )
        if self._kind == "serve":
            p99 = self._serve.get("p99_ms")
            rule(
                "slo_breach",
                self._serve.get("slo_ok") is False,
                value=None if not isinstance(p99, (int, float)) else round(p99, 1),
                threshold=self._serve.get("slo_p99_ms"),
                message="server p99 latency over its SLO (last request_batch pulse)",
            )
        scores = {v.kind: v for v in (diagnosis.verdicts if diagnosis else [])}
        for kind in _VERDICT_RULES:
            v = scores.get(kind)
            rule(
                kind,
                v is not None and v.score >= 1.0,
                value=None if v is None else round(v.score, 3),
                threshold=1.0,
                message=v.summary if v is not None else "",
            )

        if fired and self.event_log is not None:
            for a in fired:
                self.event_log.emit(
                    "monitor_alert",
                    run_dir=self.run_dir,
                    status=status,
                    **a,
                )
        return fired

    # -- the poll ----------------------------------------------------------

    def poll(self) -> MonitorStatus:
        """Consume newly completed records, re-derive liveness + diagnosis,
        evaluate the (debounced) alert rules. Call on any cadence — each
        poll costs one stat + one incremental read."""
        now = self._clock()
        recs = self._follower.poll()
        if self._follower.generation != self._generation:
            # The log shrank underneath us (fresh attempt, rotation): the
            # follower re-read from the top and `recs` IS the new file —
            # drop the old file's accumulated state before folding it.
            self._generation = self._follower.generation
            self._reset_state()
        for rec in recs:
            self._ingest(rec)
        status = self._liveness(now)
        if status in ("dead", "finished") and not self._drained_tail:
            # No more bytes are coming: a killed writer's torn tail (or a
            # final complete line missing its newline) is data now.
            self._drained_tail = True
            for rec in self._follower.poll(final=True):
                self._ingest(rec)
        sig = self._scoped_signals()
        if self._kind == "serve":
            # A server has no goodput buckets or step cadence: the doctor's
            # training heuristics on those empty signals would read a
            # perfectly healthy server as diseased. Its verdict surface is
            # liveness + the SLO flag its request_batch pulse carries.
            diagnosis = None
            if status == "training":
                # The replica reports its own admission state (ISSUE 20):
                # a live drain/re-plan reads as that state, not as a
                # generic "serving" — and because the dispatch loop keeps
                # pulsing through both, never as "dead".
                state = self._serve.get("state")
                status = (
                    state if state in ("draining", "replanning") else "serving"
                )
        else:
            diagnosis = doctor_lib.diagnose(sig) if self._seen_any else None
        fractions = doctor_lib.steady_fractions(sig.goodput_seconds or {})
        if status in ("stale_heartbeat", "dead"):
            verdict = status
        elif self._kind == "serve":
            verdict = "slo_breach" if self._serve.get("slo_ok") is False else "healthy"
        elif diagnosis is not None:
            verdict = diagnosis.verdict
        else:
            verdict = "healthy"
        fresh = self._freshness()
        alerts = self._evaluate_alerts(status, diagnosis, fractions, now, sig)
        return MonitorStatus(
            run_dir=self.run_dir,
            status=status,
            verdict=verdict,
            diagnosis=diagnosis,
            steady_fractions=fractions,
            last_event_age_s=None if fresh is None else max(0.0, now - fresh),
            progress_age_s=(
                None
                if self._progress_wall is None
                else max(0.0, now - self._progress_wall)
            ),
            headline=dict(self.headline),
            alerts=alerts,
            active_alerts=tuple(k for k, on in self._active.items() if on),
            attempt=self._attempt,
            kind=self._kind,
            serve=dict(self._serve),
        )
