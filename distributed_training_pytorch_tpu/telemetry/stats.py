"""On-device train-health statistics — computed INSIDE the compiled step.

The classic health panel (global grad norm, param norm, update-to-param
ratio, non-finite flag) answers "is this run training?" without a debugger:
a grad norm trending to zero is a dead graph, an update ratio far from the
~1e-3 rule-of-thumb is a mis-tuned lr, a nonfinite flag is the first frame
of a NaN post-mortem.

The design constraint (the same one ``precision.loss_scale`` and the
chained-window metrics obey): the statistics are computed inside
``TrainEngine._train_step_impl`` and returned as ordinary metric entries —
device scalars that ride the existing per-step metrics path, stack as scan
outputs through chained windows, and reach the host only at the sync points
the trainer already pays (``log_every`` / epoch end). **Zero extra host
syncs, zero extra dispatches**; enabling them must not retrace the step more
than its one trace per shape (``TrainEngine.trace_counts`` parity is
test-enforced) nor perturb the update arithmetic (params stay bit-exact
with a stats-off run — the norms read the dataflow, they are not in it).
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

__all__ = ["STAT_KEYS", "train_health_stats"]

# The metric keys stats mode adds (``nonfinite`` only when the engine's
# unified non-finite guard has not already claimed the key with its exact
# per-leaf predicate).
STAT_KEYS = ("grad_norm", "param_norm", "update_ratio", "nonfinite")


def train_health_stats(*, loss, grads, params, updates, eps: float = 1e-12) -> dict:
    """Health scalars for one step, all on device.

    * ``grad_norm``    — global L2 norm of the (unscaled, fp32) gradients;
    * ``param_norm``   — global L2 norm of the pre-update master params;
    * ``update_ratio`` — ||update|| / (||param|| + eps): the effective
      relative step size (the lr-sanity number);
    * ``nonfinite``    — 1.0 when the loss or any gradient went NaN/Inf.
      Computed from the already-reduced ``grad_norm`` (any non-finite leaf
      poisons the norm), so it adds no second pass over the gradient tree.
    """
    grad_norm = optax.global_norm(grads)
    param_norm = optax.global_norm(params)
    update_norm = optax.global_norm(updates)
    finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
    return {
        "grad_norm": grad_norm,
        "param_norm": param_norm,
        "update_ratio": update_norm / (param_norm + eps),
        "nonfinite": 1.0 - finite.astype(jnp.float32),
    }
