"""Fleet-controller policy engine: doctor verdicts -> remediation actions.

PRs 11-15 built the diagnosis stack — elastic N->M resume, the streaming
doctor, per-chip straggler attribution, debounced alerts — but every
remediation was still a human reading a fleet table (ROADMAP item 5).
This module is the decision half of the closed loop (ISSUE 16): a pure,
clock-injected state machine that turns one run's :class:`~.monitor.
MonitorStatus` stream (plus its trainer subprocess's liveness) into a
bounded catalog of actions. ``scripts/fleet_controller.py`` owns the
*mechanism* — spawning/killing trainers, re-planning meshes, emitting the
``controller_action`` records; everything here is *policy*, unit-testable
with synthetic statuses and a fake clock.

Action catalog (docs/fault_tolerance.md "Closed-loop recovery"):

=====================  ====================================================
``restart``            the run is ``dead`` (log silent / process exited
                       abnormally) or ``stale_heartbeat`` (hung past the
                       watchdog) — kill what remains and respawn; the
                       trainer resumes from ``latest_valid`` on its own
                       (``snapshot_path`` machinery, PR 5/12).
``restart_excluding``  a persistent ``straggler`` verdict NAMES a chip
                       (``Signals.slowest_chip``) — respawn onto the
                       surviving devices via ``parallel.elastic.
                       replan_excluding``.
``tune``               a persistent ``data_bound`` / ``checkpoint_stall``
                       alert — ONE bounded knob change (prefetch depth up
                       to a cap / ``commit_delay_s`` to a floor), applied
                       by respawn.
``keep`` / ``revert``  the tune's A/B verdict: after the tuned attempt
                       accrues steady-state wall, its fractions are diffed
                       against the pre-tune attempt's through
                       ``run_compare``'s steady-fraction diff — improved
                       and under the ceiling => ``keep`` (record only),
                       else ``revert`` (respawn with the old value).
``give_up``            the max-restarts budget is exhausted, or a reverted
                       disease recurs — stop acting; the run surfaces as
                       ``dead``/degraded for a human.
``refuse``             ``max_restarts == 0``: the controller is forbidden
                       to act at all — recorded once, then silence (the
                       CI self-test proves a zero-budget controller cannot
                       restart anything).
=====================  ====================================================

Rate limiting, all test-enforced: every status-based trigger must hold for
``confirm_polls`` consecutive polls (debounce — one slow window must not
restart a run); a subprocess *exit* is definitive and acts immediately;
after every executed action the policy is silent for an exponentially
growing backoff window; at most one action is ever in flight per run
(``decide`` returns nothing while the last action awaits
:meth:`RunPolicy.note_applied`); and every respawn consumes one unit of
the ``max_restarts`` budget, so a flapping run exhausts its budget and
surfaces as ``dead`` — never a restart loop.

Every :class:`Action` carries the verdict/alert evidence rows that
justified it, so the ``controller_action`` record can be audited with the
same timeline/doctor ritual as the trainer events it reacted to.
"""

from __future__ import annotations

import dataclasses

__all__ = [
    "Action",
    "ACTION_KINDS",
    "ControllerConfig",
    "OfferHandshake",
    "RunPolicy",
]

ACTION_KINDS = (
    "restart",
    "restart_excluding",
    "tune",
    "keep",
    "revert",
    "give_up",
    "refuse",
    # Mixed-fleet: when restart_excluding frees a chip from a trainer's
    # mesh, the fleet controller offers it to a serving replica. Advisory
    # in ISSUE 18; ACTUATED since ISSUE 20 — the replica accepts or
    # declines over its /admin surface, an accepted offer drains,
    # re-plans onto the freed chip, and is A/B-judged on before/after
    # QPS-per-chip + p99 (kept-or-reverted, :class:`OfferHandshake`);
    # a handshake that times out reverts and re-arms. Never a respawn of
    # the serving replica's process.
    "offer_chip",
)

# Actions that respawn the trainer subprocess (and therefore consume one
# unit of the max-restarts budget and start a backoff window).
_RESPAWN_KINDS = ("restart", "restart_excluding", "tune", "revert")

# Knob bounds per tunable disease: the ONE bounded change the policy may
# apply, and the steady-fraction bucket whose movement judges it.
_TUNES = {
    "data_bound": {"knob": "prefetch_batches", "bucket": "data_wait"},
    "checkpoint_stall": {"knob": "commit_delay_s", "bucket": "checkpoint"},
}


@dataclasses.dataclass
class ControllerConfig:
    """The policy's budgets and ceilings (see module doc).

    * ``max_restarts`` — total respawns allowed per run dir across the
      controller's lifetime. 0 = the controller must refuse to act.
    * ``backoff_s`` / ``backoff_factor`` — silence window after each
      executed action, growing exponentially (5s, 10s, 20s, ... by
      default): a remediation must get time to prove itself before the
      next one, and a flapping run burns wall clock, not the fleet.
    * ``confirm_polls`` — consecutive polls a status-based trigger must
      hold before acting (a subprocess exit is definitive and exempt).
    * ``max_prefetch`` — cap for the ``data_bound`` prefetch bump;
      ``commit_delay_to`` — floor for the ``checkpoint_stall`` tune
      (0.0 = drop the injected commit delay entirely).
    * ``ab_noise_floor`` — the steady-fraction noise floor the tune's A/B
      verdict uses (``run_compare``'s default).
    * ``ab_min_steady_s`` — steady wall the tuned attempt must accrue
      before it is judged (the first post-warmup sync's tiny denominator
      must not decide a revert).
    * ``offer_timeout_s`` — wall budget for the whole actuated chip
      offer (ISSUE 20): offer -> accept -> drain/re-plan -> serving
      again. Past it the handshake reverts (the replica re-plans back,
      or was never touched) and re-arms.
    * ``offer_settle_s`` — post-re-plan settle window before the offer's
      A/B judge reads the after-side probe (the first seconds after a
      re-plan are warmup + queue flush, not steady state).
    """

    max_restarts: int = 3
    backoff_s: float = 5.0
    backoff_factor: float = 2.0
    confirm_polls: int = 2
    max_prefetch: int = 8
    commit_delay_to: float = 0.0
    ab_noise_floor: float = 0.10
    ab_min_steady_s: float = 0.5
    offer_timeout_s: float = 60.0
    offer_settle_s: float = 2.0


@dataclasses.dataclass
class Action:
    """One decided remediation. ``params`` is the mechanism's input (knob
    name/values, the excluded chip); ``evidence`` the verdict/alert rows
    that justified the decision — copied onto the ``controller_action``
    record verbatim."""

    kind: str  # one of ACTION_KINDS
    reason: str  # triggering verdict/rule ("dead", "straggler", ...)
    message: str = ""
    params: dict = dataclasses.field(default_factory=dict)
    evidence: list = dataclasses.field(default_factory=list)

    @property
    def respawns(self) -> bool:
        return self.kind in _RESPAWN_KINDS

    def event_fields(self) -> dict:
        """The ``controller_action`` record's action-specific payload."""
        return {
            "action": self.kind,
            "reason": self.reason,
            "message": self.message,
            "params": dict(self.params),
            "evidence": list(self.evidence),
        }


class OfferHandshake:
    """The actuated chip offer's pure state machine (ISSUE 20 tentpole b).

    Policy only, clock-injected, no sockets: ``scripts/fleet_controller.
    py`` owns the transport (the replica's ``/admin/offer`` +
    ``/admin/replan`` routes and ``/status`` probes) and drives this
    object through it, exactly as :class:`RunPolicy` is driven by the
    spawn/kill mechanism. States::

        offered --decline--> declined                      (terminal)
        offered --accept--> accepted --actuate--> settling
        settling --judge--> kept | reverted                (terminal)
        any non-terminal --deadline--> expired             (terminal,
                                                 revert + re-arm)

    The judge compares before/after ``/status`` probes on the two
    metrics the tentpole names — QPS-per-chip and p99 — with the
    chip-count correction that makes the comparison honest: absorbing a
    chip under a fixed-rate open-loop load *halves* per-chip QPS by
    construction, so the keep floor is the before-side throughput scaled
    by ``before_chips / after_chips`` (what the same offered load yields
    spread over more chips), noise-floored like every other A/B in the
    controller. SLO health is primary: an after-side ``slo_ok=False``
    reverts regardless of throughput arithmetic. Optional
    ``steady_diff`` rows (run_compare's machinery, the PR 16 judge) ride
    along as evidence when window fractions are available on both sides.
    """

    TERMINAL = ("declined", "kept", "reverted", "expired")

    def __init__(
        self,
        chip: int,
        *,
        before: dict,
        now: float,
        timeout_s: float = 60.0,
        settle_s: float = 2.0,
    ):
        self.chip = int(chip)
        self.before = dict(before or {})
        self.deadline = float(now) + float(timeout_s)
        self.settle_s = float(settle_s)
        self.state = "offered"
        self.reason = ""
        self.settle_until: "float | None" = None
        self.actuation: dict = {}

    @property
    def done(self) -> bool:
        return self.state in self.TERMINAL

    def expired(self, now: float) -> bool:
        """True (and the state flips to ``expired``) when the bounded
        handshake wall ran out before a terminal state: the mechanism
        must revert whatever was actuated and re-arm the offer."""
        if not self.done and float(now) >= self.deadline:
            self.reason = (
                f"handshake timed out in state {self.state!r} before "
                "completing — reverting and re-arming"
            )
            self.state = "expired"
            return True
        return False

    def note_decision(self, decision: str, reason: str = "") -> None:
        """Fold the replica's ``/admin/offer`` answer in."""
        if self.state != "offered":
            raise RuntimeError(f"decision arrived in state {self.state!r}")
        if decision == "accept":
            self.state = "accepted"
        elif decision == "decline":
            self.state = "declined"
        else:
            raise ValueError(f"unknown offer decision {decision!r}")
        self.reason = reason

    def note_actuated(self, summary: dict, *, now: float) -> None:
        """The replica drained, re-planned and resumed (``/admin/replan``
        returned 200): start the settle window the judge waits out."""
        if self.state != "accepted":
            raise RuntimeError(f"actuation arrived in state {self.state!r}")
        self.state = "settling"
        self.actuation = dict(summary or {})
        self.settle_until = float(now) + self.settle_s

    def ready_to_judge(self, now: float) -> bool:
        return (
            self.state == "settling"
            and self.settle_until is not None
            and float(now) >= self.settle_until
        )

    def judge(
        self, after: dict, *, noise_floor: float = 0.10, steady_diff=None
    ) -> "tuple[str, list]":
        """The offer's A/B verdict from before/after ``/status`` probes.
        Returns ``("keep"|"revert", evidence_rows)`` and moves to the
        matching terminal state. See the class doc for the chip-scaled
        throughput floor; ``steady_diff(before_fractions, after_fractions,
        noise_floor=...)`` contributes evidence rows when both probes
        carry window fractions (same injection seam as RunPolicy's)."""
        if self.state != "settling":
            raise RuntimeError(f"judge called in state {self.state!r}")
        after = dict(after or {})
        before_qpc = float(self.before.get("qps_per_chip") or 0.0)
        after_qpc = float(after.get("qps_per_chip") or 0.0)
        before_chips = max(1, int(self.before.get("chips") or 1))
        after_chips = max(1, int(after.get("chips") or before_chips))
        # The same offered load spread over the grown device set: the
        # honest floor a fixed-rate client leaves an absorbing replica.
        expected = before_qpc * (before_chips / after_chips)
        floor = expected * (1.0 - float(noise_floor))
        slo_bad = after.get("slo_ok") is False
        evidence = [
            {
                "metric": "qps_per_chip",
                "before": round(before_qpc, 3),
                "after": round(after_qpc, 3),
                "expected_floor": round(floor, 3),
                "chips": [before_chips, after_chips],
            },
            {
                "metric": "p99_ms",
                "before": self.before.get("p99_ms"),
                "after": after.get("p99_ms"),
            },
            {
                "metric": "slo_ok",
                "before": self.before.get("slo_ok"),
                "after": after.get("slo_ok"),
            },
        ]
        if steady_diff is not None:
            bf = self.before.get("steady_fractions")
            af = after.get("steady_fractions")
            if bf and af:
                diff = steady_diff(bf, af, noise_floor=noise_floor)
                evidence += [
                    r.to_dict() if hasattr(r, "to_dict") else dict(r)
                    for r in (diff.get("rows") or [])[:4]
                ]
        keep = not slo_bad and after_qpc >= floor
        if keep:
            self.state = "kept"
            self.reason = (
                f"qps/chip {after_qpc:.3f} >= floor {floor:.3f} "
                f"({before_chips}->{after_chips} chips) and SLO healthy"
            )
            return "keep", evidence
        self.state = "reverted"
        self.reason = (
            "SLO breached after absorb"
            if slo_bad
            else f"qps/chip {after_qpc:.3f} < floor {floor:.3f} "
            f"({before_chips}->{after_chips} chips)"
        )
        return "revert", evidence


def _steady_seconds(fractions_or_seconds: dict | None) -> float:
    from distributed_training_pytorch_tpu.telemetry import doctor as doctor_lib

    if not fractions_or_seconds:
        return 0.0
    return sum(
        float(v)
        for b, v in fractions_or_seconds.items()
        if b not in doctor_lib._EXCLUDED
    )


class RunPolicy:
    """The per-run decision state machine (see module doc).

    ``knobs`` seeds the current tunable-knob values (the spawn spec's
    ``prefetch_batches`` / ``commit_delay_s``); ``steady_diff`` is the A/B
    judge — ``scripts/fleet_controller.py`` passes ``run_compare.
    steady_diff`` so the controller's verdict is computed by literally the
    operator's comparison code; tests may pass a stub. It is called as
    ``steady_diff(before_fractions, after_fractions, noise_floor=...)``
    (steady fractions are a fixed point of ``steady_fractions``, so
    fraction dicts feed the seconds-shaped signature unchanged).

    Protocol per poll::

        action = policy.decide(status, proc_running=..., exit_code=...,
                               now=...)
        if action:  # execute it (kill/respawn/emit), then:
            policy.note_applied(action, now=...)

    ``decide`` never returns a second action while one awaits
    ``note_applied`` (the never-two-concurrent-actions rule).
    """

    def __init__(
        self,
        config: ControllerConfig | None = None,
        *,
        knobs: dict | None = None,
        steady_diff=None,
    ):
        self.config = config or ControllerConfig()
        self.knobs = dict(knobs or {})
        self._steady_diff = steady_diff
        self.restarts_used = 0
        self.gave_up = False
        self._pending: Action | None = None
        self._next_allowed = 0.0  # monotonic gate: backoff between actions
        self._backoff = float(self.config.backoff_s)
        self._confirm: dict[str, int] = {}
        # One tune per disease kind; a reverted kind that recurs => give_up.
        self._tuned: dict[str, str] = {}  # reason -> "applied"|"kept"|"reverted"
        self._ab: dict | None = None  # in-flight A/B: knob, bucket, before, ...
        self.excluded_chips: list[int] = []
        self._acted_attempt: int | None = None  # attempt id at decision time
        self._ab_before: dict | None = None  # newest pre-tune steady fractions
        # Attempt id the last RESPAWN acted on: verdict-driven actions
        # (straggler exclusion, knob tunes) stay gated until the monitor
        # reports an attempt PAST it — the stale status between the kill
        # and the new attempt's run_start must not re-fire the same
        # disease and burn the budget on one incident.
        self._respawn_attempt: int | None = None

    # -- helpers -----------------------------------------------------------

    def _confirmed(self, key: str, firing: bool) -> bool:
        """Debounce: ``key``'s condition must hold ``confirm_polls``
        consecutive decide() calls. Counters for quiet keys reset, so an
        intermittent blip never accumulates to a trigger."""
        if not firing:
            self._confirm[key] = 0
            return False
        self._confirm[key] = self._confirm.get(key, 0) + 1
        return self._confirm[key] >= max(1, int(self.config.confirm_polls))

    def _budgeted(self, reason: str, evidence: list, build) -> Action:
        """Gate a respawn through the max-restarts budget: a zero budget
        refuses, an exhausted one gives up — each recorded once, then the
        policy is silent (the run surfaces as dead/degraded)."""
        cfg = self.config
        if cfg.max_restarts <= 0:
            self.gave_up = True
            return Action(
                "refuse",
                reason,
                message="max_restarts=0: controller is forbidden to act",
                params={"restarts_used": self.restarts_used,
                        "max_restarts": cfg.max_restarts},
                evidence=evidence,
            )
        if self.restarts_used >= cfg.max_restarts:
            self.gave_up = True
            return Action(
                "give_up",
                reason,
                message=(
                    f"restart budget exhausted "
                    f"({self.restarts_used}/{cfg.max_restarts}) — surfacing "
                    "to a human"
                ),
                params={"restarts_used": self.restarts_used,
                        "max_restarts": cfg.max_restarts},
                evidence=evidence,
            )
        return build()

    @staticmethod
    def _verdict_evidence(status, kind: str) -> list:
        diag = getattr(status, "diagnosis", None)
        if diag is None:
            return []
        for v in diag.verdicts:
            if v.kind == kind:
                return [dict(r) for r in v.evidence]
        return []

    @staticmethod
    def _alert_evidence(status, rule: str) -> list:
        """The debounced alert's own row (it carries measured value vs
        threshold) — the firing poll's record if present, else a synthetic
        row from the current fractions."""
        for a in getattr(status, "alerts", None) or []:
            if a.get("rule") == rule:
                return [dict(a)]
        return []

    # -- the decision ------------------------------------------------------

    def decide(
        self,
        status,
        *,
        proc_running: bool,
        exit_code: int | None,
        now: float,
    ) -> Action | None:
        """One poll's decision for one run. ``status`` is the monitor's
        :class:`~.monitor.MonitorStatus`; ``proc_running``/``exit_code``
        describe the supervised subprocess (``exit_code`` None while
        running or when the run is adopted); ``now`` is the controller's
        monotonic clock. A returned action is marked in flight — decide()
        stays silent until :meth:`note_applied` releases it."""
        self.note_status(status)
        action = self._decide(
            status, proc_running=proc_running, exit_code=exit_code, now=now
        )
        if action is not None:
            self._pending = action
        return action

    def _decide(
        self,
        status,
        *,
        proc_running: bool,
        exit_code: int | None,
        now: float,
    ) -> Action | None:
        if self.gave_up:
            return None
        if self._pending is not None:
            return None  # never two concurrent actions on one run
        finished_clean = (
            status.status == "finished"
            and not proc_running
            and (exit_code in (None, 0))
        )
        if finished_clean:
            return self._judge_ab(status, now, final=True)
        if now < self._next_allowed:
            return None  # backoff: the last action is still proving itself

        # 1) Dead: the process exited abnormally (definitive — no debounce)
        #    or the log went silent / the main thread hung past the
        #    monitor's ceilings (debounced).
        proc_dead = not proc_running and exit_code not in (None, 0)
        if proc_dead or self._confirmed(
            "dead", status.status in ("dead", "stale_heartbeat")
        ):
            reason = "dead" if proc_dead or status.status == "dead" else (
                "stale_heartbeat"
            )
            evidence = [
                {
                    "metric": "exit_code" if proc_dead else "last_event_age_s",
                    "value": exit_code if proc_dead else status.last_event_age_s,
                }
            ]
            evidence += self._alert_evidence(status, reason)
            return self._budgeted(
                reason,
                evidence,
                lambda: Action(
                    "restart",
                    reason,
                    message="respawning; trainer resumes from latest_valid",
                    evidence=evidence,
                ),
            )

        # A respawn's remediation is unproven until the NEW attempt
        # reports: everything below keys off verdicts/alerts, and the
        # status in hand may still describe the attempt we just replaced.
        if (
            self._respawn_attempt is not None
            and (getattr(status, "attempt", None) or 0) <= self._respawn_attempt
        ):
            return None

        # 2) In-flight A/B verdict (before any new tune/exclude is weighed).
        ab_action = self._judge_ab(status, now, final=False)
        if ab_action is not None:
            return ab_action

        # 3) Persistent straggler WITH a named chip -> exclude-and-replan.
        diag = getattr(status, "diagnosis", None)
        strag = None
        if diag is not None:
            for v in diag.verdicts:
                if v.kind == "straggler" and v.score >= 1.0:
                    strag = v
                    break
        chip = getattr(diag.signals, "slowest_chip", None) if diag else None
        if self._confirmed("straggler", strag is not None and chip is not None):
            evidence = [dict(r) for r in strag.evidence]
            chip = int(chip)

            def build():
                return Action(
                    "restart_excluding",
                    "straggler",
                    message=f"excluding degraded chip {chip} and re-planning "
                    "onto the survivors",
                    params={"exclude_chip": chip,
                            "excluded_chips": self.excluded_chips + [chip]},
                    evidence=evidence,
                )

            return self._budgeted("straggler", evidence, build)

        # 4) Persistent tunable-fraction alerts -> ONE bounded knob change.
        active = set(getattr(status, "active_alerts", None) or ())
        for reason, spec in _TUNES.items():
            if not self._confirmed(reason, reason in active):
                continue
            if self._ab is not None:
                continue  # one knob experiment at a time
            state = self._tuned.get(reason)
            evidence = self._alert_evidence(status, reason) or [
                {
                    "metric": f"{spec['bucket']}_frac_steady",
                    "value": (status.steady_fractions or {}).get(
                        spec["bucket"]
                    ),
                }
            ]
            if state in ("reverted", "kept"):
                # The one bounded change was already tried: a reverted
                # disease recurring has no further automatic cure; a kept
                # one recurring means the cure did not hold. Either way —
                # a human's turn.
                self.gave_up = True
                return Action(
                    "give_up",
                    reason,
                    message=f"knob {spec['knob']} already {state} — no "
                    "further automatic remediation",
                    params={"knob": spec["knob"], "state": state},
                    evidence=evidence,
                )
            change = self._plan_tune(reason, spec)
            if change is None:
                continue  # knob unknown or already at its bound

            def build_tune(change=change, reason=reason, evidence=evidence):
                return Action(
                    "tune",
                    reason,
                    message=f"{change['knob']} {change['from']} -> "
                    f"{change['to']} (bounded; A/B-judged before keeping)",
                    params=change,
                    evidence=evidence,
                )

            return self._budgeted(reason, evidence, build_tune)
        return None

    def _plan_tune(self, reason: str, spec: dict) -> dict | None:
        cfg = self.config
        knob = spec["knob"]
        if knob not in self.knobs:
            return None
        cur = self.knobs[knob]
        if knob == "prefetch_batches":
            to = int(cfg.max_prefetch)
            if int(cur) >= to:
                return None  # already at the bound — nothing left to try
        else:  # commit_delay_s
            to = float(cfg.commit_delay_to)
            if float(cur) <= to:
                return None
        return {"knob": knob, "from": cur, "to": to, "bucket": spec["bucket"]}

    def _judge_ab(self, status, now: float, *, final: bool) -> Action | None:
        """The tune's A/B verdict: once the tuned attempt accrued enough
        steady wall (or the run finished), diff its steady fractions
        against the pre-tune attempt's through the injected
        ``steady_diff`` (run_compare's). Improved and under the noise
        floor-adjusted ceiling => keep; else revert (one respawn)."""
        ab = self._ab
        if ab is None:
            return None
        attempt = getattr(status, "attempt", None)
        if attempt is not None and attempt <= ab["since_attempt"] and not final:
            return None  # the monitor has not seen the tuned attempt yet
        after = dict(status.steady_fractions or {})
        if not final:
            diag = getattr(status, "diagnosis", None)
            sig = getattr(diag, "signals", None) if diag else None
            accrued = _steady_seconds(getattr(sig, "goodput_seconds", None))
            if accrued < self.config.ab_min_steady_s:
                return None  # too little evidence to judge yet
        bucket = ab["bucket"]
        diff = None
        if self._steady_diff is not None and any(after.values()):
            diff = self._steady_diff(
                ab["before"], after, noise_floor=self.config.ab_noise_floor
            )
        before_frac = float(ab["before"].get(bucket, 0.0))
        after_frac = float(after.get(bucket, 0.0))
        improved = after_frac < before_frac and ab["reason"] not in set(
            getattr(status, "active_alerts", None) or ()
        )
        evidence = [
            {
                "metric": f"{bucket}_frac_steady",
                "before": round(before_frac, 4),
                "after": round(after_frac, 4),
            }
        ]
        if diff is not None:
            evidence += [
                r.to_dict() if hasattr(r, "to_dict") else dict(r)
                for r in diff["rows"][:4]
            ]
        reason = ab["reason"]
        knob = ab["knob"]
        self._ab = None
        if improved:
            self._tuned[reason] = "kept"
            return Action(
                "keep",
                reason,
                message=f"{knob}={self.knobs.get(knob)!r} kept: steady "
                f"{bucket} {before_frac:.0%} -> {after_frac:.0%}",
                params={"knob": knob, "value": self.knobs.get(knob)},
                evidence=evidence,
            )
        self._tuned[reason] = "reverted"
        if final:
            # The run already finished; respawning to revert would redo
            # completed work. Record the failed experiment only.
            return Action(
                "give_up",
                reason,
                message=f"{knob} tune did not improve steady {bucket} and "
                "the run finished — reverting is moot",
                params={"knob": knob, "from": self.knobs.get(knob),
                        "to": ab["old"]},
                evidence=evidence,
            )

        def build():
            return Action(
                "revert",
                reason,
                message=f"{knob} tune did not improve steady {bucket} "
                f"({before_frac:.0%} -> {after_frac:.0%}) — reverting to "
                f"{ab['old']!r}",
                params={"knob": knob, "from": self.knobs.get(knob),
                        "to": ab["old"], "bucket": bucket},
                evidence=evidence,
            )

        return self._budgeted(reason, evidence, build)

    # -- bookkeeping -------------------------------------------------------

    def note_decided(self, action: Action) -> None:
        """Mark ``action`` in flight (decide() returned it; the mechanism
        is about to execute). Called implicitly by decide() — split out
        only for tests that construct actions by hand."""
        self._pending = action

    def note_applied(self, action: Action, *, now: float) -> None:
        """The mechanism executed ``action``: consume budget, start the
        backoff window, update knob/exclusion state, reset debounce
        counters (the new attempt's recurrence must re-confirm from
        scratch)."""
        if self._pending is action or self._pending is None:
            self._pending = None
        if action.respawns:
            self.restarts_used += 1
            self._next_allowed = now + self._backoff
            self._backoff *= max(1.0, float(self.config.backoff_factor))
            self._respawn_attempt = self._acted_attempt
        self._confirm.clear()
        if action.kind == "tune":
            p = action.params
            self._ab = {
                "knob": p["knob"],
                "bucket": p["bucket"],
                "old": p["from"],
                "before": dict(getattr(self, "_ab_before", None) or {}),
                "reason": action.reason,
                "since_attempt": self._acted_attempt,
            }
            self._tuned[action.reason] = "applied"
            self.knobs[p["knob"]] = p["to"]
        elif action.kind == "revert":
            self.knobs[action.params["knob"]] = action.params["to"]
        elif action.kind == "restart_excluding":
            chip = int(action.params["exclude_chip"])
            if chip not in self.excluded_chips:
                self.excluded_chips.append(chip)

    def note_status(self, status) -> None:
        """Record the poll context actions will need (the acting attempt
        id and the pre-action steady fractions for the A/B's 'before'
        side). decide() calls this itself on every poll."""
        attempt = getattr(status, "attempt", None)
        if attempt is not None:
            self._acted_attempt = attempt
        if self._ab is None and any((status.steady_fractions or {}).values()):
            self._ab_before = dict(status.steady_fractions)
