from distributed_training_pytorch_tpu.trainer.trainer import Trainer  # noqa: F401
